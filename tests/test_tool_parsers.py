"""Tool-call parser unit tests (parity: --tool-call-parser qwen3_coder in
.env.server:11; plugin import hook, launch.py:417-418)."""

import json

from vllm_distributed_tpu.entrypoints.openai.tool_parsers import (
    ToolParserManager,
)


def test_hermes_parser():
    parser = ToolParserManager.get("hermes")
    text = (
        'thinking...\n<tool_call>\n{"name": "get_weather", '
        '"arguments": {"city": "SF"}}\n</tool_call>'
    )
    content, calls = parser.extract(text)
    assert content == "thinking..."
    assert len(calls) == 1
    assert calls[0]["function"]["name"] == "get_weather"
    assert json.loads(calls[0]["function"]["arguments"]) == {"city": "SF"}


def test_hermes_no_tool_call_passthrough():
    parser = ToolParserManager.get("hermes")
    content, calls = parser.extract("just words")
    assert content == "just words"
    assert calls == []


def test_qwen3_coder_parser():
    parser = ToolParserManager.get("qwen3_coder")
    text = (
        "I'll check.\n<tool_call>\n<function=read_file>\n"
        "<parameter=path>/tmp/x.txt</parameter>\n"
        "<parameter=limit>10</parameter>\n"
        "</function>\n</tool_call>"
    )
    content, calls = parser.extract(text)
    assert content == "I'll check."
    assert calls[0]["function"]["name"] == "read_file"
    args = json.loads(calls[0]["function"]["arguments"])
    assert args == {"path": "/tmp/x.txt", "limit": 10}


def test_unknown_parser_rejected():
    import pytest

    with pytest.raises(ValueError, match="unknown tool parser"):
        ToolParserManager.get("nope")


def test_plugin_import(tmp_path):
    plugin = tmp_path / "plug.py"
    plugin.write_text(
        "from vllm_distributed_tpu.entrypoints.openai.tool_parsers import "
        "ToolParserManager, ToolParser\n"
        "@ToolParserManager.register('custom_test')\n"
        "class P(ToolParser):\n"
        "    def extract(self, text):\n"
        "        return text, []\n"
    )
    ToolParserManager.import_tool_parser(str(plugin))
    assert ToolParserManager.get("custom_test") is not None


# ---- streaming (SSE tool-call deltas) ----
QWEN3_TEXT = (
    "Let me check.\n<tool_call>\n<function=get_weather>\n"
    "<parameter=city>San Francisco</parameter>\n"
    "<parameter=days>3</parameter>\n"
    "</function>\n</tool_call>\ndone"
)


def _drive(parser_name, text, chunk=3):
    sp = ToolParserManager.get(parser_name).streaming()
    content, tools = "", []
    for i in range(0, len(text), chunk):
        c, t = sp.push(text[i : i + chunk])
        content += c
        tools += t
    c, t = sp.finish()
    return content + c, tools + t


def _reassemble(tools):
    """Concatenate streamed fragments per index into full calls."""
    calls = {}
    for frag in tools:
        call = calls.setdefault(
            frag["index"], {"function": {"arguments": ""}}
        )
        if "id" in frag:
            call["id"] = frag["id"]
        fn = frag.get("function", {})
        if "name" in fn:
            call["function"]["name"] = fn["name"]
        call["function"]["arguments"] += fn.get("arguments", "")
    return [calls[i] for i in sorted(calls)]


def test_qwen3_streaming_matches_extract():
    for chunk in (1, 3, 7, len(QWEN3_TEXT)):
        content, tools = _drive("qwen3_coder", QWEN3_TEXT, chunk)
        calls = _reassemble(tools)
        assert len(calls) == 1, (chunk, tools)
        assert calls[0]["function"]["name"] == "get_weather"
        assert json.loads(calls[0]["function"]["arguments"]) == {
            "city": "San Francisco",
            "days": 3,
        }
        assert "Let me check." in content and "done" in content
        assert "<tool_call>" not in content


def test_qwen3_streaming_emits_header_before_block_end():
    """The call header (name) must stream out BEFORE </tool_call>
    arrives — that's the point of streaming deltas."""
    sp = ToolParserManager.get("qwen3_coder").streaming()
    _, tools = sp.push(
        "<tool_call>\n<function=run>\n<parameter=cmd>ls</parameter>\n"
    )
    assert any(
        f.get("function", {}).get("name") == "run" for f in tools
    )
    assert any(
        "cmd" in f.get("function", {}).get("arguments", "")
        for f in tools
    )


def test_qwen3_streaming_truncated_closes_json():
    sp = ToolParserManager.get("qwen3_coder").streaming()
    _, t1 = sp.push("<tool_call><function=run><parameter=cmd>ls</parameter>")
    _, t2 = sp.finish()
    calls = _reassemble(t1 + t2)
    assert json.loads(calls[0]["function"]["arguments"]) == {"cmd": "ls"}


def test_hermes_streaming_block_granular():
    text = (
        'hi <tool_call>{"name": "f", "arguments": {"a": 1}}</tool_call>'
        ' bye'
    )
    content, tools = _drive("hermes", text, chunk=5)
    calls = _reassemble(tools)
    assert len(calls) == 1
    assert calls[0]["function"]["name"] == "f"
    assert json.loads(calls[0]["function"]["arguments"]) == {"a": 1}
    assert content.startswith("hi ") and content.endswith(" bye")


def test_streaming_partial_marker_held_back():
    sp = ToolParserManager.get("qwen3_coder").streaming()
    c1, _ = sp.push("text <tool_")
    assert c1 == "text "  # the possible marker prefix is held
    c2, _ = sp.push("gap continues")  # not a marker after all
    c3, _ = sp.finish()
    assert (c1 + c2 + c3) == "text <tool_gap continues"


def test_qwen3_streaming_malformed_body_does_not_wedge():
    """A parameter missing its closing tag must not swallow the rest
    of the stream: the call closes at </function> and trailing content
    keeps flowing."""
    sp = ToolParserManager.get("qwen3_coder").streaming()
    text = (
        "<tool_call>\n<function=f>\n<parameter=a>x</function>\n"
        "</tool_call>\ndone"
    )
    # Note: the half-open parameter waits until </function> proves no
    # </parameter> is coming — feed everything, then finish.
    c1, t1 = sp.push(text)
    c2, t2 = sp.finish()
    content = c1 + c2
    # The malformed half-parameter is dropped; args stay valid JSON.
    calls = _reassemble(t1 + t2)
    assert json.loads(calls[0]["function"]["arguments"]) == {}
    assert "done" in content

