"""Tool-call parser unit tests (parity: --tool-call-parser qwen3_coder in
.env.server:11; plugin import hook, launch.py:417-418)."""

import json

from vllm_distributed_tpu.entrypoints.openai.tool_parsers import (
    ToolParserManager,
)


def test_hermes_parser():
    parser = ToolParserManager.get("hermes")
    text = (
        'thinking...\n<tool_call>\n{"name": "get_weather", '
        '"arguments": {"city": "SF"}}\n</tool_call>'
    )
    content, calls = parser.extract(text)
    assert content == "thinking..."
    assert len(calls) == 1
    assert calls[0]["function"]["name"] == "get_weather"
    assert json.loads(calls[0]["function"]["arguments"]) == {"city": "SF"}


def test_hermes_no_tool_call_passthrough():
    parser = ToolParserManager.get("hermes")
    content, calls = parser.extract("just words")
    assert content == "just words"
    assert calls == []


def test_qwen3_coder_parser():
    parser = ToolParserManager.get("qwen3_coder")
    text = (
        "I'll check.\n<tool_call>\n<function=read_file>\n"
        "<parameter=path>/tmp/x.txt</parameter>\n"
        "<parameter=limit>10</parameter>\n"
        "</function>\n</tool_call>"
    )
    content, calls = parser.extract(text)
    assert content == "I'll check."
    assert calls[0]["function"]["name"] == "read_file"
    args = json.loads(calls[0]["function"]["arguments"])
    assert args == {"path": "/tmp/x.txt", "limit": 10}


def test_unknown_parser_rejected():
    import pytest

    with pytest.raises(ValueError, match="unknown tool parser"):
        ToolParserManager.get("nope")


def test_plugin_import(tmp_path):
    plugin = tmp_path / "plug.py"
    plugin.write_text(
        "from vllm_distributed_tpu.entrypoints.openai.tool_parsers import "
        "ToolParserManager, ToolParser\n"
        "@ToolParserManager.register('custom_test')\n"
        "class P(ToolParser):\n"
        "    def extract(self, text):\n"
        "        return text, []\n"
    )
    ToolParserManager.import_tool_parser(str(plugin))
    assert ToolParserManager.get("custom_test") is not None
