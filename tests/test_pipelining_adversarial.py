"""Adversarial pipelining coverage (VERDICT r2 weak #7): abort while a
fused dispatch is in flight, page-pressure preemption racing the device
carry, and a request exhausting its budget mid-pipeline.  Uses the
production-kernel interpret path so the in-place writer + carry are the
code under test."""

import os
from unittest import mock

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


def _engine(model_dir, **kw):
    defaults = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        num_kv_pages=64,
        max_model_len=256,
        max_num_seqs=8,
        num_decode_steps=8,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return make_tiny_llama(str(tmp_path_factory.mktemp("adv")))


def _sp(max_tokens=64):
    return SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )


def _drive_until_pipelined(engine):
    """Step until a fused dispatch is in flight (pending non-empty)."""
    for _ in range(20):
        engine.step()
        if engine._pending:
            return
    raise AssertionError("pipelining never engaged")


def test_abort_mid_flight(model_dir):
    """Aborting a request whose tokens are still on the device must not
    corrupt the survivors: they finish with exact lengths and match an
    undisturbed run's prefix behavior."""
    with mock.patch.dict(os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}):
        engine = _engine(model_dir)
        for i in range(3):
            engine.add_request(
                f"r{i}", prompt_token_ids=[3 + i, 7, 11], sampling_params=_sp(40)
            )
        _drive_until_pipelined(engine)
        engine.abort_request("r1")
        done = {}
        for _ in range(200):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
            if not engine.has_unfinished_requests():
                break
        assert set(done) == {"r0", "r2"}
        assert all(len(t) == 40 for t in done.values())

        # Oracle: same prompts, no abort — survivors' tokens unchanged.
        engine2 = _engine(model_dir)
        for i in range(3):
            engine2.add_request(
                f"r{i}", prompt_token_ids=[3 + i, 7, 11], sampling_params=_sp(40)
            )
        ref = {}
        while engine2.has_unfinished_requests():
            for out in engine2.step():
                if out.finished:
                    ref[out.request_id] = out.outputs[0].token_ids
        assert done["r0"] == ref["r0"]
        assert done["r2"] == ref["r2"]


def test_late_arrival_mid_flight(model_dir):
    """A request added while a fused dispatch is in flight (waiting
    non-empty breaks _pipeline_safe) must drain cleanly and everyone
    finishes with exact lengths."""
    with mock.patch.dict(os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}):
        engine = _engine(model_dir)
        for i in range(2):
            engine.add_request(
                f"a{i}", prompt_token_ids=[5, 9 + i], sampling_params=_sp(32)
            )
        _drive_until_pipelined(engine)
        engine.add_request("late", prompt_token_ids=[42, 43, 44],
                           sampling_params=_sp(16))
        done = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
        assert len(done["a0"]) == 32 and len(done["a1"]) == 32
        assert len(done["late"]) == 16


def test_page_pressure_with_pipelining(model_dir):
    """A page pool tight enough to force preemption while multi-step
    decode is on: everything still completes with exact lengths (the
    preempted request re-prefills and regenerates deterministically)."""
    with mock.patch.dict(os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}):
        # 18 usable pages × 16 slots vs 4 requests × (8 prompt + 120
        # output) ≈ 512 slots needed at peak — guaranteed preemption.
        engine = _engine(model_dir, num_kv_pages=19, max_model_len=160)
        for i in range(4):
            engine.add_request(
                f"p{i}",
                prompt_token_ids=[2 + i] * 8,
                sampling_params=_sp(120),
            )
        done = {}
        for _ in range(2000):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
            if not engine.has_unfinished_requests():
                break
        assert set(done) == {f"p{i}" for i in range(4)}
        assert all(len(t) == 120 for t in done.values())
        assert engine.scheduler.num_preemptions > 0, "test lost its teeth"


def test_budget_exhaustion_mid_pipeline(model_dir):
    """Requests whose remaining budget is smaller than the fused K while
    a dispatch is in flight: the engine drains instead of overrunning
    max_tokens."""
    with mock.patch.dict(os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}):
        engine = _engine(model_dir)
        engine.add_request("x", prompt_token_ids=[9, 8, 7],
                           sampling_params=_sp(max_tokens=13))  # not ÷ 8
        done = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
        assert len(done["x"]) == 13
