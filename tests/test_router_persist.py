"""Durable router control-plane state suite (ISSUE 17).

Layered like the feature: record-codec properties (torn-write
truncation at EVERY byte offset, checksum rejection);
``RouterJournal`` checkpoint round-trips (randomized property over the
prompt forms plus a hand-built mid-SSE chat state); ``RouterStateLog``
recovery semantics (membership latest-wins, journal_done removal,
config snapshot, bounded compaction); the pool's ``verifying`` grace
window for re-adopted replicas; and the ``AdoptedHandle`` /
``adopt_recovered`` units over real pids — all pure-python and
loopback-free, so the whole file runs in well under a second.
"""

from __future__ import annotations

import asyncio
import os
import random
import subprocess
import sys
import time

import pytest

from vllm_distributed_tpu.router.fleet import (
    AdoptedHandle,
    ReplicaManager,
    _pid_alive,
)
from vllm_distributed_tpu.router.journal import ChoiceState, RouterJournal
from vllm_distributed_tpu.router.metrics import RouterMetrics
from vllm_distributed_tpu.router.persist import (
    RouterStateLog,
    decode_segment,
    encode_record,
    load_state,
)
from vllm_distributed_tpu.router.pool import ReplicaPool

pytestmark = pytest.mark.router


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------
# record codec: torn writes and corruption
# ---------------------------------------------------------------------
_RECORDS = [
    {"t": "meta", "version": 1},
    {"t": "replica", "id": "fleet-1", "port": 8101, "pid": 4242,
     "role": "mixed", "template": "serve --port {port}"},
    {"t": "journal", "rid": "rtr-1", "j": {"kind": "completions",
     "body": {"prompt": "hello éè", "max_tokens": 8}}},
    {"t": "config", "cfg": {"policy": "least_loaded", "qos": "a" * 50}},
    {"t": "journal_done", "rid": "rtr-1"},
]


def test_encode_decode_round_trip():
    data = b"".join(encode_record(r) for r in _RECORDS)
    assert decode_segment(data) == _RECORDS


def test_torn_write_truncated_at_every_byte_offset():
    """The core crash-safety property: a segment cut at ANY byte
    decodes to an exact prefix of the written records — never a
    partial, corrupt, or reordered record, and never an exception."""
    data = b"".join(encode_record(r) for r in _RECORDS)
    boundaries = []
    off = 0
    for r in _RECORDS:
        off += len(encode_record(r))
        boundaries.append(off)
    for cut in range(len(data) + 1):
        decoded = decode_segment(data[:cut])
        # how many records are wholly (newline included) before the cut
        want = sum(1 for b in boundaries if b <= cut)
        assert decoded == _RECORDS[:want], f"cut at byte {cut}"


def test_corrupt_record_truncates_suffix():
    """A flipped byte mid-log fails the checksum; the record AND
    everything after it are distrusted, earlier records survive."""
    encoded = [encode_record(r) for r in _RECORDS]
    blob = bytearray(b"".join(encoded))
    # flip a payload byte inside the third record
    pos = len(encoded[0]) + len(encoded[1]) + 12
    blob[pos] ^= 0xFF
    assert decode_segment(bytes(blob)) == _RECORDS[:2]


def test_decode_rejects_non_dict_and_bad_prefix():
    good = encode_record({"t": "meta", "version": 1})
    # valid CRC over a JSON array: not a record
    import json
    import zlib

    payload = json.dumps([1, 2]).encode()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    array_line = b"%08x " % crc + payload + b"\n"
    assert decode_segment(good + array_line + good) == [
        {"t": "meta", "version": 1}
    ]
    assert decode_segment(b"not-a-wal-line\n" + good) == []


# ---------------------------------------------------------------------
# RouterJournal checkpoint round-trip
# ---------------------------------------------------------------------
def _random_journal(rng: random.Random) -> RouterJournal:
    kind = rng.choice(["completions", "chat"])
    n = rng.randint(1, 3)
    if kind == "chat":
        body = {
            "messages": [{"role": "user", "content": "hi there"}],
            "n": n,
            "max_tokens": rng.randint(1, 32),
            "stream": rng.random() < 0.5,
        }
    else:
        prompt = rng.choice(
            [
                "plain text prompt",
                ["batch one", "batch two"],
                [1, 2, 3, 4],
                [[5, 6], [7, 8, 9]],
            ]
        )
        body = {
            "prompt": prompt,
            "n": n,
            "max_tokens": rng.randint(1, 32),
            "stream": rng.random() < 0.5,
        }
    j = RouterJournal(f"rtr-{rng.randint(1, 999)}", kind, body)
    j.upstream_id = rng.choice([None, "cmpl-abc123"])
    j.model = rng.choice([None, "m"])
    j.migrations = rng.randint(0, 2)
    j.served_by = rng.choice([None, "fleet-2"])
    j.slo_class = rng.choice([None, "interactive", "batch"])
    for c in j.choices.values():
        if rng.random() < 0.7:
            c.emitted_token_ids = [
                rng.randint(0, 1000) for _ in range(rng.randint(0, 12))
            ]
        c.forwarded_text_len = rng.randint(0, 64)
        if c.prompt_token_ids is None and rng.random() < 0.5:
            # learned from a vdt_prompt_token_ids frame mid-stream
            c.prompt_token_ids = [rng.randint(0, 1000) for _ in range(3)]
        if rng.random() < 0.3:
            c.finish_reason = rng.choice(["stop", "length"])
        c.role_sent = rng.random() < 0.5
    return j


def test_journal_round_trip_property():
    """to_dict -> JSON -> from_dict is lossless for every prompt form
    (text, batch text, token ids, batch token ids, chat), any n, and
    any mid-stream progress — including through a real WAL record."""
    import json

    rng = random.Random(0x17)
    for _ in range(200):
        j = _random_journal(rng)
        d = j.to_dict()
        wire = decode_segment(
            encode_record({"t": "journal", "rid": j.request_id, "j": d})
        )[0]["j"]
        back = RouterJournal.from_dict(json.loads(json.dumps(wire)))
        assert back.to_dict() == d
        assert back.request_id == j.request_id
        assert back.stream == j.stream
        assert sorted(back.choices) == sorted(j.choices)
        for idx, c in j.choices.items():
            assert back.choices[idx].to_dict() == c.to_dict()
            if not c.finished:
                assert back.resume_payload(
                    back.choices[idx]
                ) == j.resume_payload(c)
        assert [c.index for c in back.unfinished()] == [
            c.index for c in j.unfinished()
        ]


def test_journal_round_trip_mid_sse_chat_checkpoint():
    """A chat stream checkpointed mid-SSE: role delta sent, one choice
    finished, the other mid-generation with learned prompt ids — the
    restored journal resumes only the unfinished choice with the exact
    emitted-token state."""
    j = RouterJournal(
        "rtr-7", "chat", {"messages": [], "n": 2, "stream": True}
    )
    j.upstream_id = "chatcmpl-x"
    j.slo_class = "interactive"
    j.observe_choice(
        {
            "index": 0,
            "delta": {"role": "assistant", "content": "Hel"},
            "vdt_token_ids": [11, 12],
            "vdt_prompt_token_ids": [1, 2, 3],
            "finish_reason": None,
        }
    )
    j.observe_choice(
        {
            "index": 1,
            "delta": {"role": "assistant", "content": "done"},
            "vdt_token_ids": [21, 22, 23],
            "finish_reason": "stop",
        }
    )
    back = RouterJournal.from_dict(j.to_dict())
    assert [c.index for c in back.unfinished()] == [0]
    c0 = back.choices[0]
    assert c0.emitted_token_ids == [11, 12]
    assert c0.prompt_token_ids == [1, 2, 3]
    assert c0.forwarded_text_len == 3
    assert c0.role_sent is True
    assert back.choices[1].finished
    payload = back.resume_payload(c0)
    assert payload["emitted_token_ids"] == [11, 12]
    assert payload["prompt_token_ids"] == [1, 2, 3]
    assert payload["slo_class"] == "interactive"


# ---------------------------------------------------------------------
# RouterStateLog: recovery semantics + bounded compaction
# ---------------------------------------------------------------------
def _journal(rid: str, toks: list[int]) -> RouterJournal:
    j = RouterJournal(rid, "completions", {"prompt": [1, 2], "stream": True})
    j.choices[0].emitted_token_ids = list(toks)
    return j


def test_state_log_recovers_membership_journals_config(tmp_path):
    d = str(tmp_path)
    log = RouterStateLog(d, ckpt_interval=0.0)
    assert log.open().empty
    log.record_replica(
        "fleet-1", port=8101, pid=4242, role="mixed", template="t {port}"
    )
    log.record_replica("fleet-2", port=8102, pid=4243, role="prefill")
    log.record_config({"policy": "least_loaded"})
    log.checkpoint_journal(_journal("rtr-1", [5]), force=True)
    log.checkpoint_journal(_journal("rtr-1", [5, 6, 7]), force=True)
    log.checkpoint_journal(_journal("rtr-2", [9]), force=True)
    log.journal_done("rtr-2")
    log.close()

    rec = load_state(d)
    assert sorted(rec.replicas) == ["fleet-1", "fleet-2"]
    assert rec.replicas["fleet-1"]["pid"] == 4242
    assert rec.replicas["fleet-1"]["template"] == "t {port}"
    assert rec.replicas["fleet-2"]["role"] == "prefill"
    assert rec.config == {"policy": "least_loaded"}
    # latest checkpoint wins; journal_done removes
    assert sorted(rec.journals) == ["rtr-1"]
    back = RouterJournal.from_dict(rec.journals["rtr-1"])
    assert back.choices[0].emitted_token_ids == [5, 6, 7]


def test_state_log_replica_gone_and_reopen_compacts(tmp_path):
    d = str(tmp_path)
    log = RouterStateLog(d)
    log.open()
    log.record_replica("fleet-1", port=8101, pid=1)
    log.record_replica("fleet-2", port=8102, pid=2)
    log.record_replica_gone("fleet-1")
    log.close()

    # torn tail appended by a crash mid-write must not poison recovery
    segs = sorted(p for p in os.listdir(d) if p.startswith("wal."))
    with open(os.path.join(d, segs[-1]), "ab") as f:
        f.write(b"deadbeef {\"t\":\"replica\",\"id\":\"gho")

    log2 = RouterStateLog(d)
    rec = log2.open()
    assert sorted(rec.replicas) == ["fleet-2"]
    # a second incarnation compacts to a single fresh segment: a crash
    # loop must not accrete WAL files
    segs2 = [p for p in os.listdir(d) if p.startswith("wal.")]
    assert len(segs2) == 1
    log2.close()
    assert sorted(load_state(d).replicas) == ["fleet-2"]


def test_state_log_rotation_bounds_segments(tmp_path):
    """Many checkpoints for one request must compact, not accrete: the
    dir holds at most a couple of segments and recovery still sees only
    the latest journal state."""
    d = str(tmp_path)
    log = RouterStateLog(
        d, segment_bytes=512, fsync_interval=1e9, ckpt_interval=0.0
    )
    log.open()
    log.record_replica("fleet-1", port=8101, pid=4242)
    toks: list[int] = []
    for i in range(200):
        toks.append(i)
        log.checkpoint_journal(_journal("rtr-1", toks))
    log.close()

    segs = [p for p in os.listdir(d) if p.startswith("wal.")]
    assert len(segs) <= 2, segs
    total = sum(os.path.getsize(os.path.join(d, p)) for p in segs)
    assert total < 16 * 512
    rec = load_state(d)
    assert sorted(rec.replicas) == ["fleet-1"]
    back = RouterJournal.from_dict(rec.journals["rtr-1"])
    assert back.choices[0].emitted_token_ids == toks


def test_checkpoint_rate_limit_keeps_wal_linear(tmp_path):
    """Per-token checkpoint calls inside the interval are dropped (the
    WAL must stay linear in stream length); force bypasses."""
    now = {"t": 100.0}
    log = RouterStateLog(
        str(tmp_path), ckpt_interval=0.25, clock=lambda: now["t"]
    )
    log.open()
    assert log.checkpoint_journal(_journal("rtr-1", [1]))
    assert not log.checkpoint_journal(_journal("rtr-1", [1, 2]))
    assert log.checkpoint_journal(_journal("rtr-1", [1, 2]), force=True)
    now["t"] += 0.3
    assert log.checkpoint_journal(_journal("rtr-1", [1, 2, 3]))
    log.close()


def test_fleet_targets_survive_restart_and_compaction(tmp_path):
    """Scale targets are control-plane state: latest record wins, and
    the snapshot rewrite on reopen carries them forward — a crash
    between a scale-up and convergence must not revert the fleet."""
    log = RouterStateLog(str(tmp_path))
    log.open()
    log.record_fleet_targets(5, {"prefill": 2, "decode": 1})
    log.record_fleet_targets(7, {"prefill": 2, "decode": 3})
    log.close()

    recovered = load_state(str(tmp_path))
    assert recovered.fleet_target == 7
    assert recovered.fleet_role_targets == {"prefill": 2, "decode": 3}

    # Second incarnation: open() compacts into a fresh segment; the
    # targets must survive the rewrite.
    log2 = RouterStateLog(str(tmp_path))
    rec2 = log2.open()
    assert rec2.fleet_target == 7
    assert rec2.fleet_role_targets == {"prefill": 2, "decode": 3}
    log2.close()
    assert load_state(str(tmp_path)).fleet_target == 7


def test_scale_to_records_target_in_wal(tmp_path):
    """ReplicaManager.scale_to / scale_role_to write the new targets to
    the WAL on every change (and only on change)."""
    log = RouterStateLog(str(tmp_path))
    log.open()
    m = _manager()
    m.persist = log
    m.scale_to(4, reason="manual")
    m.scale_to(4, reason="manual")  # no-op: must not re-append
    m.scale_role_to("prefill", 2, reason="autoscale")
    log.close()

    recovered = load_state(str(tmp_path))
    assert recovered.fleet_target == 4
    assert recovered.fleet_role_targets == {"prefill": 2}
    fleet_recs = [
        r
        for _seg, path in _segments(str(tmp_path))
        for r in decode_segment(open(path, "rb").read())
        if r.get("t") == "fleet"
    ]
    assert len(fleet_recs) == 2  # one per actual change


def _segments(state_dir):
    from vllm_distributed_tpu.router.persist import _list_segments

    return _list_segments(state_dir)


# ---------------------------------------------------------------------
# pool: the "verifying" grace window (re-adoption, ISSUE 17)
# ---------------------------------------------------------------------
class _FakeResp:
    def __init__(self, status: int, body: dict):
        self.status = status
        self._body = body

    async def json(self):
        return self._body

    async def text(self):
        return ""

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False


class _FakeSession:
    """ClientSession stub: /health answers per the script, /metrics 404s."""

    def __init__(self, script):
        self.script = script  # callable url -> _FakeResp (or raises)

    def get(self, url, timeout=None):
        return self.script(url)

    async def request(self, method, url, timeout=None, **kw):
        # The pool probe egresses via the resilience wrapper, whose
        # passthrough awaits session.request (ISSUE 19).
        return self.script(url)


def test_pool_verify_window_enters_verifying_not_routable():
    pool = ReplicaPool([], allow_empty=True)
    r = pool.add(
        "http://127.0.0.1:9", replica_id="fleet-1", verify_window=30.0
    )
    assert r.state == "verifying"
    assert r.verifying
    assert not r.routable
    # faster re-probe cadence while any replica is verifying
    assert pool._next_interval() == max(pool.health_interval / 4, 0.2)
    r.state = "healthy"
    r.verify_deadline_mono = 0.0
    assert pool._next_interval() == pool.health_interval


def test_pool_verifying_immune_to_transport_failures():
    """A restart storm's connection refusals inside the grace window
    must NOT eject the replica; after the window expires the same
    failure marks it unreachable as usual."""

    def refuse(url):
        raise ConnectionError("refused")

    pool = ReplicaPool([], allow_empty=True)
    r = pool.add("http://127.0.0.1:9", verify_window=30.0)
    _run(pool.probe(_FakeSession(refuse), r))
    assert r.state == "verifying"
    assert r.consecutive_failures == 1
    assert r.last_error
    # window expiry: same transport failure now ejects
    r.verify_deadline_mono = time.monotonic() - 1
    _run(pool.probe(_FakeSession(refuse), r))
    assert r.state == "unreachable"


def test_pool_probe_promotes_verifying_to_healthy():
    def healthy(url):
        if url.endswith("/health"):
            return _FakeResp(
                200, {"status": "healthy", "replica_id": "fleet-1"}
            )
        return _FakeResp(404, {})

    pool = ReplicaPool([], allow_empty=True)
    r = pool.add("http://127.0.0.1:9", verify_window=30.0)
    _run(pool.probe(_FakeSession(healthy), r))
    assert r.state == "healthy"
    assert r.replica_id == "fleet-1"
    assert r.verify_deadline_mono == 0.0
    assert r.routable


# ---------------------------------------------------------------------
# adoption units: AdoptedHandle + adopt_recovered over real pids
# ---------------------------------------------------------------------
def _dead_pid() -> int:
    proc = subprocess.Popen(  # vdt-lint: disable=thread-leak — reaped two lines down
        [sys.executable, "-c", "pass"]
    )
    proc.wait(timeout=30)
    return proc.pid


def test_adopted_handle_live_pid():
    h = AdoptedHandle(os.getpid())
    assert h.poll() is None
    with pytest.raises(TimeoutError):
        h.wait(timeout=0.15)


def test_adopted_handle_dead_pid():
    pid = _dead_pid()
    assert not _pid_alive(pid)
    h = AdoptedHandle(pid)
    # exit code of a reparented orphan is unknowable: reported as -1
    assert h.poll() == -1
    assert h.wait(timeout=1.0) == -1


def _manager(pool=None):
    pool = pool or ReplicaPool([], allow_empty=True)
    return ReplicaManager(
        pool,
        RouterMetrics(enabled=False),
        launcher=None,
        warmup_timeout=5.0,
        drain_timeout=5.0,
        check_interval=0.05,
        max_restarts=3,
        restart_window=300.0,
        backoff_base=0.0,
        backoff_cap=0.0,
    )


def test_adopt_recovered_dead_pid_reaped_without_crash_charge():
    """A recorded child that died while no supervisor existed is reaped
    from the log and respawned through the normal shortfall path — NOT
    charged to the crash-loop budget (it did not crash-loop)."""
    pid = _dead_pid()

    async def go():
        manager = _manager()
        adopted = manager.adopt_recovered(
            {"fleet-3": {"id": "fleet-3", "port": 8103, "pid": pid}}
        )
        assert adopted == []
        assert manager.replicas == []
        kinds = [e["kind"] for e in manager.events]
        assert kinds == ["adopt_dead"]
        assert manager.restarts_total == 0
        assert not manager.exhausted
        assert len(manager._restart_times) == 0

    _run(go())


def test_adopt_recovered_live_pid_supervised_and_verifying():
    """A live recorded child becomes a supervised ManagedReplica again
    (ready, AdoptedHandle) and enters the pool in the verifying grace
    state; fresh spawn ids stay disjoint from adopted ones."""

    async def go():
        pool = ReplicaPool([], allow_empty=True)
        manager = _manager(pool)
        adopted = manager.adopt_recovered(
            {
                "fleet-7": {
                    "id": "fleet-7",
                    "port": 8107,
                    "pid": os.getpid(),
                    "role": "decode",
                }
            },
            verify_window=30.0,
        )
        try:
            assert [mr.replica_id for mr in adopted] == ["fleet-7"]
            mr = adopted[0]
            assert mr.state == "ready"
            assert isinstance(mr.handle, AdoptedHandle)
            assert mr.role == "decode"
            r = pool.by_id("fleet-7")
            assert r is not None and r.state == "verifying"
            assert not r.routable
            assert [e["kind"] for e in manager.events] == ["adopt"]
            # seq bumped past the adopted tail: next spawn is fleet-8
            assert manager._seq >= 7
        finally:
            for mr in adopted:
                if mr.task is not None:
                    mr.task.cancel()
                    await asyncio.gather(mr.task, return_exceptions=True)

    _run(go())


def test_adopt_recovered_identity_mismatch_drops_without_signal():
    """A stranger answering /health on the recorded port (pid/port
    reuse) is dropped from supervision WITHOUT being signalled, and the
    drop does count against the crash budget (something ate our
    child)."""

    async def go():
        pool = ReplicaPool([], allow_empty=True)
        manager = _manager(pool)
        signalled = []

        async def stranger(url):
            return True, "somebody-else"

        manager._health_identity = stranger
        adopted = manager.adopt_recovered(
            {"fleet-1": {"id": "fleet-1", "port": 8101, "pid": os.getpid()}},
            verify_window=5.0,
        )
        mr = adopted[0]
        mr.handle.terminate = lambda: signalled.append("TERM")
        mr.handle.kill = lambda: signalled.append("KILL")
        await asyncio.wait_for(mr.task, timeout=5.0)
        assert signalled == []
        assert mr.state == "failed"
        assert manager.replicas == []
        assert pool.by_id("fleet-1") is None
        kinds = [e["kind"] for e in manager.events]
        assert kinds == ["adopt", "adopt_identity_mismatch"]

    _run(go())


def test_adopt_recovered_verified_by_matching_identity():
    async def go():
        pool = ReplicaPool([], allow_empty=True)
        manager = _manager(pool)

        async def ours(url):
            return True, "fleet-1"

        manager._health_identity = ours
        adopted = manager.adopt_recovered(
            {"fleet-1": {"id": "fleet-1", "port": 8101, "pid": os.getpid()}},
            verify_window=5.0,
        )
        mr = adopted[0]
        await asyncio.wait_for(mr.task, timeout=5.0)
        assert mr.state == "ready"
        assert mr in manager.replicas
        kinds = [e["kind"] for e in manager.events]
        assert kinds == ["adopt", "adopt_verified"]

    _run(go())
