"""Staged-decode flush kernel vs the functional scatter oracle, in
interpret mode on CPU (the production TPU path is re-checked on-chip by
bench's _check_kernels)."""

import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.ops.attention import kv_pool_shape, write_kv_pages
from vllm_distributed_tpu.ops.pallas.kv_flush import kv_flush


def _run_case(
    *,
    base_lens,  # python list; 0 = padding row
    n_side,
    k_blk=16,
    page_size=16,
    hkv=2,
    d=64,
    num_pages=32,
    seed=0,
    table_slack=1,  # 0 = exact-fit table (the slab slack column steps
    #                 past the table and must hit the dump page)
):
    rng = np.random.default_rng(seed)
    s = len(base_lens)
    kv = jnp.asarray(
        rng.standard_normal(kv_pool_shape(num_pages, page_size, hkv, d)),
        jnp.float32,
    )
    side = jnp.asarray(
        rng.standard_normal((s, 2, k_blk, hkv * d)), jnp.float32
    )
    # Per-seq block tables: enough pages for base + k rows, disjoint.
    max_pages = max(
        -(-(b + k_blk) // page_size) for b in base_lens
    ) + table_slack
    bt = np.zeros((s, max_pages), np.int32)
    nxt = 1
    for i, b in enumerate(base_lens):
        if b <= 0:
            continue
        need = -(-(b + k_blk) // page_size)
        bt[i, :need] = np.arange(nxt, nxt + need)
        nxt += need
    assert nxt <= num_pages

    got = kv_flush(
        kv,
        side,
        jnp.asarray(bt),
        jnp.asarray(np.asarray(base_lens, np.int32)),
        jnp.asarray([n_side], jnp.int32),
        interpret=True,
    )

    # Oracle: scatter each live sequence's first n_side side rows at
    # slots base..base+n_side-1.
    want = kv
    for i, b in enumerate(base_lens):
        if b <= 0:
            continue
        for j in range(n_side):
            pos = b + j
            slot = bt[i, pos // page_size] * page_size + pos % page_size
            want = write_kv_pages(
                want,
                side[i, 0, j].reshape(1, hkv, d),
                side[i, 1, j].reshape(1, hkv, d),
                jnp.asarray([slot], jnp.int32),
            )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_page_aligned_base():
    _run_case(base_lens=[16, 48], n_side=16)


def test_unaligned_bases():
    _run_case(base_lens=[3, 21, 70], n_side=16)


def test_partial_flush():
    _run_case(base_lens=[5, 33], n_side=7)


def test_padding_rows_skipped():
    _run_case(base_lens=[9, 0, 25, 0], n_side=16)


def test_small_page_spans_three():
    # k=16 rows over page_size 8 spans up to 3 pages.
    _run_case(base_lens=[5, 19], n_side=16, page_size=8)


def test_single_row_flush():
    _run_case(base_lens=[31], n_side=1)


def test_exact_fit_table():
    # base + k exactly fills the table's last page and the table has NO
    # slack column: the slab's extra page must fall through to the dump
    # page instead of duplicating (and clobbering) the last real page.
    _run_case(base_lens=[48], n_side=16, table_slack=0)


def test_exact_fit_mixed_lengths():
    # Row 0's slack page is an in-table zero entry (dump page); row 1's
    # steps past the table width entirely.
    _run_case(base_lens=[32, 64], n_side=16, table_slack=0)
