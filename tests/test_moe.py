"""MoE (Mixtral / Qwen3-MoE) correctness + expert parallelism
(milestone config 5: Mixtral tp + EP; BASELINE.md).

Oracles: transformers on torch CPU for model math; single-device greedy
for sharding bit-compatibility on the 8-device virtual CPU mesh.
"""

import pytest

from tests.utils import (
    hf_greedy_generate,
    make_tiny_mixtral,
    make_tiny_qwen3_moe,
)
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [[1, 5, 9, 23, 77, 41, 3], [7, 2, 88, 14], [100, 3, 9]]


@pytest.fixture(scope="module")
def tiny_mixtral(tmp_path_factory):
    # heads=8/kv=4 so tp up to 4 divides; 4 experts so ep 2/4 divide.
    return make_tiny_mixtral(
        str(tmp_path_factory.mktemp("mixtral")), heads=8, kv_heads=4
    )


@pytest.fixture(scope="module")
def tiny_qwen3_moe(tmp_path_factory):
    return make_tiny_qwen3_moe(str(tmp_path_factory.mktemp("qwen3moe")))


def _greedy(model_dir, tp=1, dp=1, ep=False, max_tokens=6):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=256,
            tensor_parallel_size=tp,
            data_parallel_size=dp,
            enable_expert_parallel=ep,
        )
    )
    for i, p in enumerate(PROMPTS):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    done = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
    return [done[f"r{i}"] for i in range(len(PROMPTS))]


def test_mixtral_greedy_matches_hf(tiny_mixtral):
    """Model math vs the transformers Mixtral implementation."""
    expected = [hf_greedy_generate(tiny_mixtral, p, 6) for p in PROMPTS]
    assert _greedy(tiny_mixtral) == expected


def test_qwen3_moe_greedy_matches_hf(tiny_qwen3_moe):
    """Qwen3-MoE (the reference's flagship family: Qwen3-Coder MoE,
    /root/reference/.env.server:11) vs transformers."""
    expected = [hf_greedy_generate(tiny_qwen3_moe, p, 6) for p in PROMPTS]
    assert _greedy(tiny_qwen3_moe) == expected


@pytest.fixture(scope="module")
def mixtral_baseline(tiny_mixtral):
    return _greedy(tiny_mixtral)


def test_mixtral_tp4_matches_single_device(tiny_mixtral, mixtral_baseline):
    """Non-EP: every expert split over tp like a dense MLP."""
    assert _greedy(tiny_mixtral, tp=4) == mixtral_baseline


def test_mixtral_ep4_matches_single_device(tiny_mixtral, mixtral_baseline):
    """EP: whole experts sharded over the tp axis (1 expert/device);
    GSPMD inserts the combine psum."""
    assert _greedy(tiny_mixtral, tp=4, ep=True) == mixtral_baseline


def test_mixtral_ep2_dp2_matches_single_device(tiny_mixtral, mixtral_baseline):
    """EP composed with data parallelism on the same mesh."""
    assert _greedy(tiny_mixtral, tp=2, dp=2, ep=True) == mixtral_baseline


def test_ep_requires_divisible_experts(tiny_mixtral):
    # 4 experts cannot shard 8 ways.
    with pytest.raises(Exception, match="divisible"):
        _greedy(tiny_mixtral, tp=8, ep=True)
