"""MoE (Mixtral / Qwen3-MoE) correctness + expert parallelism
(milestone config 5: Mixtral tp + EP; BASELINE.md).

Oracles: transformers on torch CPU for model math; single-device greedy
for sharding bit-compatibility on the 8-device virtual CPU mesh.
"""

import pytest

from tests.utils import (
    hf_greedy_generate,
    make_tiny_mixtral,
    make_tiny_qwen3_moe,
)
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [[1, 5, 9, 23, 77, 41, 3], [7, 2, 88, 14], [100, 3, 9]]


@pytest.fixture(scope="module")
def tiny_mixtral(tmp_path_factory):
    # heads=8/kv=4 so tp up to 4 divides; 4 experts so ep 2/4 divide.
    return make_tiny_mixtral(
        str(tmp_path_factory.mktemp("mixtral")), heads=8, kv_heads=4
    )


@pytest.fixture(scope="module")
def tiny_qwen3_moe(tmp_path_factory):
    return make_tiny_qwen3_moe(str(tmp_path_factory.mktemp("qwen3moe")))


def _greedy(model_dir, tp=1, dp=1, ep=False, max_tokens=6):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=256,
            tensor_parallel_size=tp,
            data_parallel_size=dp,
            enable_expert_parallel=ep,
        )
    )
    for i, p in enumerate(PROMPTS):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    done = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
    return [done[f"r{i}"] for i in range(len(PROMPTS))]


def test_mixtral_greedy_matches_hf(tiny_mixtral):
    """Model math vs the transformers Mixtral implementation."""
    expected = [hf_greedy_generate(tiny_mixtral, p, 6) for p in PROMPTS]
    assert _greedy(tiny_mixtral) == expected


def test_qwen3_moe_greedy_matches_hf(tiny_qwen3_moe):
    """Qwen3-MoE (the reference's flagship family: Qwen3-Coder MoE,
    /root/reference/.env.server:11) vs transformers."""
    expected = [hf_greedy_generate(tiny_qwen3_moe, p, 6) for p in PROMPTS]
    assert _greedy(tiny_qwen3_moe) == expected


@pytest.fixture(scope="module")
def mixtral_baseline(tiny_mixtral):
    return _greedy(tiny_mixtral)


def test_mixtral_tp4_matches_single_device(tiny_mixtral, mixtral_baseline):
    """Non-EP: every expert split over tp like a dense MLP."""
    assert _greedy(tiny_mixtral, tp=4) == mixtral_baseline


def test_mixtral_ep4_matches_single_device(tiny_mixtral, mixtral_baseline):
    """EP: whole experts sharded over the tp axis (1 expert/device);
    GSPMD inserts the combine psum."""
    assert _greedy(tiny_mixtral, tp=4, ep=True) == mixtral_baseline


def test_mixtral_ep2_dp2_matches_single_device(tiny_mixtral, mixtral_baseline):
    """EP composed with data parallelism on the same mesh."""
    assert _greedy(tiny_mixtral, tp=2, dp=2, ep=True) == mixtral_baseline


def test_ep_requires_divisible_experts(tiny_mixtral):
    # 4 experts cannot shard 8 ways.
    with pytest.raises(Exception, match="divisible"):
        _greedy(tiny_mixtral, tp=8, ep=True)


def _greedy_env(model_dir, env, **kw):
    import os
    from unittest import mock

    with mock.patch.dict(os.environ, env):
        return _greedy(model_dir, **kw)


@pytest.fixture(scope="module")
def tiny_mixtral_16e(tmp_path_factory):
    # The VERDICT r3 #4 shape: 16 experts, top-2 — sparse dispatch must
    # do ~k/E of the dense expert FLOPs.
    return make_tiny_mixtral(
        str(tmp_path_factory.mktemp("mixtral16")),
        num_experts=16,
        top_k=2,
        heads=8,
        kv_heads=4,
    )


def test_ragged_matches_dense_16_experts(tiny_mixtral_16e):
    dense = _greedy_env(tiny_mixtral_16e, {"VDT_MOE_IMPL": "dense"})
    ragged = _greedy_env(tiny_mixtral_16e, {"VDT_MOE_IMPL": "ragged"})
    assert ragged == dense


def test_ragged_matches_dense_under_ep(tiny_mixtral_16e):
    dense = _greedy_env(tiny_mixtral_16e, {"VDT_MOE_IMPL": "dense"})
    ragged_ep = _greedy_env(
        tiny_mixtral_16e, {"VDT_MOE_IMPL": "ragged"}, tp=4, ep=True
    )
    assert ragged_ep == dense


def test_ragged_dispatch_is_sparse(tiny_mixtral_16e):
    """The ragged MLP must dispatch T*k rows through grouped matmuls —
    not T*E token-expert pairs like the dense path.  Asserted on the
    jaxpr (op shapes): CPU's ragged_dot lowering is masked-dense, so
    FLOP counts only reflect sparsity on TPU, where the real lowering
    was verified at exactly 2*M*H*I flops (bench _check_kernels asserts
    this on-chip every run)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.models.registry import get_model_class

    config = EngineArgs(
        model=tiny_mixtral_16e, skip_tokenizer_init=True
    ).create_engine_config()
    model = get_model_class(config.model_config.architecture)(
        config.model_config
    )
    params = jax.jit(model.init_params)(jax.random.PRNGKey(0))
    layer = params["layers"][0]
    t = 64
    h = jnp.asarray(np.random.default_rng(0).standard_normal((t, 64)),
                    jnp.float32)

    jaxpr = jax.make_jaxpr(lambda x: model._mlp_ragged(x, layer))(h)

    def all_eqns(jxp):
        for eqn in jxp.eqns:
            yield eqn
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    yield from all_eqns(inner)

    ragged_eqns = [
        e for e in all_eqns(jaxpr.jaxpr)
        if e.primitive.name.startswith("ragged_dot")
    ]
    assert len(ragged_eqns) == 3, jaxpr  # w1, w3, w2
    for eqn in ragged_eqns:
        m = eqn.invars[0].aval.shape[0]
        assert m == t * model.top_k, (m, t, model.top_k)
