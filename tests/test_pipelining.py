"""Fused multi-step decode + engine pipelining (VERDICT r1 items #2/#3).

Proves (a) two dispatches are genuinely in flight at once, (b) the fused
decode scan and the pipelined engine produce bit-identical tokens to the
fully synchronous single-step engine, and (c) mixed finish times / stop
conditions drain the pipeline correctly.
"""

from __future__ import annotations

import pytest

from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config


def _run(num_decode_steps: int, sampling_kwargs_per_req, track=None):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=write_llama_config(),
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=128,
            max_model_len=256,
            num_decode_steps=num_decode_steps,
        )
    )
    for i, kw in enumerate(sampling_kwargs_per_req):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=[3 + i, 7, 11 + i],
            sampling_params=SamplingParams(**kw),
        )
    results: dict[str, list[int]] = {}
    steps = 0
    while engine.has_unfinished_requests():
        if track is not None:
            track.append(len(engine._pending))
        for out in engine.step():
            if out.finished:
                results[out.request_id] = out.outputs[0].token_ids
        steps += 1
        assert steps < 500
    return results


def test_pipelined_greedy_matches_sync():
    reqs = [dict(temperature=0.0, max_tokens=33, ignore_eos=True)] * 4
    sync = _run(1, reqs)
    pipelined = _run(8, reqs)
    assert sync == pipelined


def test_pipelined_seeded_sampling_matches_sync():
    reqs = [
        dict(temperature=0.9, seed=41 + i, max_tokens=19, ignore_eos=True)
        for i in range(3)
    ]
    sync = _run(1, reqs)
    pipelined = _run(4, reqs)
    assert sync == pipelined


def test_two_dispatches_in_flight():
    depths: list[int] = []
    reqs = [dict(temperature=0.0, max_tokens=49, ignore_eos=True)] * 2
    _run(8, reqs, track=depths)
    # At least one step() began with a dispatch still unresolved.
    assert max(depths) >= 1


def test_mixed_finish_times_drain():
    reqs = [
        dict(temperature=0.0, max_tokens=9, ignore_eos=True),
        dict(temperature=0.0, max_tokens=30, ignore_eos=True),
        dict(temperature=0.0, max_tokens=17, ignore_eos=True),
    ]
    out = _run(8, reqs)
    assert sorted(len(v) for v in out.values()) == [9, 17, 30]
    assert out == _run(1, reqs)


def test_penalties_fall_back_to_sync_and_match():
    reqs = [
        dict(
            temperature=0.8,
            seed=7,
            repetition_penalty=1.3,
            max_tokens=12,
            ignore_eos=True,
        )
    ]
    assert _run(8, reqs) == _run(1, reqs)


def test_stop_token_mid_window():
    # Greedy on dummy weights is deterministic: find what it generates,
    # then use an early token as a stop token and check truncation.
    probe = _run(1, [dict(temperature=0.0, max_tokens=24, ignore_eos=True)])
    toks = probe["r0"]
    stop_tok = toks[5]
    reqs = [dict(temperature=0.0, max_tokens=24, stop_token_ids=[stop_tok])]
    out = _run(8, reqs)
    idx = toks.index(stop_tok)
    assert out["r0"] == toks[: idx + 1]


def test_heterogeneous_tails_masked_not_recompiled():
    """Uniform-K with per-sequence tail masking (round 5): requests
    with different max_tokens — none a multiple of K, several under
    one K — must produce exactly their budget, bit-identical to the
    sync engine, while the scheduler emits only K=num_decode_steps
    fused scans (no tail-K program proliferation)."""
    reqs = [
        dict(temperature=0.0, max_tokens=m, ignore_eos=True)
        for m in (3, 17, 40, 5, 29, 8)
    ]
    sync = _run(1, reqs)
    assert [len(sync[f"r{i}"]) for i in range(6)] == [3, 17, 40, 5, 29, 8]

    from vllm_distributed_tpu.engine.scheduler import Scheduler

    seen_k = set()
    orig = Scheduler.schedule

    def spy(self):
        out = orig(self)
        if out.decode_steps > 1:
            seen_k.add(out.decode_steps)
            # Under-K tails are per-request num_new, not a smaller K.
            for c in out.cached_requests:
                assert c.num_new_tokens <= out.decode_steps
        return out

    Scheduler.schedule = spy
    try:
        fused = _run(8, reqs)
    finally:
        Scheduler.schedule = orig
    assert fused == sync
    assert seen_k == {8}, seen_k
