"""Forkable mock-uniproc replica for fleet tests and the chaos ramp
harness (ISSUE 13).

One managed replica = AsyncLLM over ``MockUniProcExecutor`` (no chips,
no agents) + the real OpenAI api_server, as its own OS process — the
thing the router's ``ReplicaManager`` spawns, health-gates, drains,
kills, and reaps.  Two launch paths share ``_child_main``:

- ``MockReplicaLauncher``: multiprocessing fork (fast — no jax
  re-import), the ChildHandle surface the manager drives.  Used by
  tests/test_fleet.py and ``chaos_soak --ramp``.
- ``python -m tests.mock_replica --port N``: a real subprocess, for
  exercising the ``CommandLauncher`` template path end to end.

The child honors the usual mock determinism env (VDT_MOCK_TOKEN_SEQ
position streams make any dropped/duplicated/restarted token visible),
installs the ISSUE 8 SIGTERM drain, and keeps capacity deliberately
small (``max_num_seqs``) so a modest rate ramp builds a real waiting
queue — the autoscaler's primary signal.
"""

from __future__ import annotations

import argparse
import multiprocessing
import os


def _child_main(
    port: int,
    replica_id: str,
    model_dir: str,
    extra_env: dict[str, str] | None = None,
    max_num_seqs: int = 2,
    enable_prefix_caching: bool = False,
) -> None:
    for k, v in (extra_env or {}).items():
        os.environ[k] = v
    import asyncio
    import signal

    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )

    async def main() -> None:
        engine = AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=128,
                max_model_len=256,
                num_decode_steps=1,
                max_num_seqs=max_num_seqs,
                # Radix index on demand (ISSUE 15): the decode side of
                # a KV hand-off needs it to adopt imported pages.
                enable_prefix_caching=enable_prefix_caching,
                distributed_executor_backend=MockUniProcExecutor,
            )
        )
        state = init_app_state(
            engine,
            served_model_name="mock-replica",
            replica_id=replica_id,
        )
        # Tiny shutdown_timeout: a kill must sever live streams (the
        # migration trigger), not wait them out.
        runner = await serve_http(
            build_app(state),
            host="127.0.0.1",
            port=port,
            shutdown_timeout=0.05,
        )
        stop = asyncio.Event()
        sigterm_seen = False

        def _on_sigterm() -> None:
            # ISSUE 8 parity: first SIGTERM drains (journal/cut
            # in-flight streams so the router migrates them), second
            # exits immediately.
            nonlocal sigterm_seen
            if stop.is_set():
                return
            if sigterm_seen:
                stop.set()
                return
            sigterm_seen = True

            async def _drain_and_stop() -> None:
                try:
                    await state.engine.drain()
                except Exception:  # noqa: BLE001 — drain is best-effort on the way down
                    pass
                finally:
                    stop.set()

            asyncio.get_running_loop().create_task(_drain_and_stop())

        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
            loop.add_signal_handler(signal.SIGINT, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
        try:
            await stop.wait()
        finally:
            await runner.cleanup()
            engine.shutdown()

    asyncio.run(main())


class ForkHandle:
    """multiprocessing.Process adapter for the manager's ChildHandle
    duck type (pid / poll / terminate / kill / wait)."""

    def __init__(self, proc: multiprocessing.Process) -> None:
        self._proc = proc

    @property
    def pid(self) -> int | None:
        return self._proc.pid

    def poll(self) -> int | None:
        if self._proc.is_alive():
            return None
        return self._proc.exitcode

    def terminate(self) -> None:
        self._proc.terminate()

    def kill(self) -> None:
        self._proc.kill()

    def wait(self, timeout: float | None = None) -> int | None:
        self._proc.join(timeout)
        return self._proc.exitcode


class MockReplicaLauncher:
    """Fork-based launcher: spawns ``_child_main`` as a daemon child.
    Keeps every handle it minted so harnesses can assert nothing
    outlives the manager (``leaked()``) and reach into a live child to
    SIGKILL it mid-resize (``alive()``)."""

    def __init__(
        self,
        model_dir: str,
        extra_env: dict[str, str] | None = None,
        max_num_seqs: int = 2,
        enable_prefix_caching: bool = False,
    ) -> None:
        self.model_dir = model_dir
        self.extra_env = dict(extra_env or {})
        self.max_num_seqs = max_num_seqs
        self.enable_prefix_caching = enable_prefix_caching
        self.spawned: list[tuple[str, ForkHandle]] = []

    def spawn(
        self, replica_id: str, port: int, role: str = "mixed"
    ) -> ForkHandle:
        # Role rides the child env exactly like CommandLauncher's
        # subprocess path: init_app_state falls back to VDT_ROUTER_ROLE,
        # so /health advertises the disaggregation role to the pool.
        proc = multiprocessing.Process(
            target=_child_main,
            args=(
                port,
                replica_id,
                self.model_dir,
                {**self.extra_env, "VDT_ROUTER_ROLE": role},
                self.max_num_seqs,
                self.enable_prefix_caching,
            ),
            daemon=True,
        )
        proc.start()
        handle = ForkHandle(proc)
        self.spawned.append((replica_id, handle))
        return handle

    def alive(self) -> list[tuple[str, ForkHandle]]:
        return [(rid, h) for rid, h in self.spawned if h.poll() is None]

    def leaked(self) -> list[str]:
        """Replica ids whose child process is still alive — must be
        empty after the manager stops (the no-zombie contract)."""
        return [rid for rid, _ in self.alive()]


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--replica-id", type=str, default="")
    parser.add_argument(
        "--model-dir",
        type=str,
        default="",
        help="llama config dir; written fresh to a tempdir when empty",
    )
    parser.add_argument("--max-num-seqs", type=int, default=2)
    parser.add_argument(
        "--enable-prefix-caching",
        action="store_true",
        default=False,
        help="boot with the radix prefix index (required on the decode "
        "side of an ISSUE 15 KV hand-off)",
    )
    args = parser.parse_args()
    model_dir = args.model_dir
    if not model_dir:
        import tempfile

        from vllm_distributed_tpu.testing import write_llama_config

        model_dir = write_llama_config(
            os.path.join(
                tempfile.mkdtemp(prefix="vdt_mock_replica_"), "m"
            )
        )
    replica_id = (
        args.replica_id
        or os.environ.get("VDT_REPLICA_ID")
        or f"mock-{args.port}"
    )
    _child_main(
        args.port,
        replica_id,
        model_dir,
        max_num_seqs=args.max_num_seqs,
        enable_prefix_caching=args.enable_prefix_caching,
    )


if __name__ == "__main__":
    main()
