"""Test helpers: synthesize tiny HF-format model snapshots on disk.

No network egress exists in CI, so every test builds its own miniature
checkpoint (config.json + model.safetensors with HF tensor names) and the
parity oracle is `transformers` running the same weights on torch CPU.
"""

from __future__ import annotations

import json
import os

import numpy as np


def _save_safetensors(path: str, tensors: dict[str, np.ndarray]) -> None:
    from safetensors.numpy import save_file

    save_file(tensors, path)


def make_tiny_llama(
    tmpdir: str,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    intermediate: int = 128,
    layers: int = 2,
    heads: int = 4,
    kv_heads: int = 2,
    max_pos: int = 512,
    tie_embeddings: bool = False,
    seed: int = 0,
    arch: str = "LlamaForCausalLM",
    model_type: str = "llama",
    attn_bias: bool = False,
    qk_norm: bool = False,
) -> str:
    """One builder for the whole llama family: Qwen2 = + q/k/v biases,
    Qwen3 dense = + per-head QK RMS-norm (the same flags the model code
    derives from model_type, models/llama.py)."""
    head_dim = hidden // heads
    cfg = {
        "architectures": [arch],
        "model_type": model_type,
        "hidden_size": hidden,
        "intermediate_size": intermediate,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": head_dim,
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "torch_dtype": "float32",
        "tie_word_embeddings": tie_embeddings,
        "hidden_act": "silu",
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w(vocab_size, hidden),
        "model.norm.weight": np.ones(hidden, np.float32),
    }
    if not tie_embeddings:
        tensors["lm_head.weight"] = w(vocab_size, hidden)
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors |= {
            p + "self_attn.q_proj.weight": w(heads * head_dim, hidden),
            p + "self_attn.k_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.v_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.o_proj.weight": w(hidden, heads * head_dim),
            p + "mlp.gate_proj.weight": w(intermediate, hidden),
            p + "mlp.up_proj.weight": w(intermediate, hidden),
            p + "mlp.down_proj.weight": w(hidden, intermediate),
            p + "input_layernorm.weight": np.ones(hidden, np.float32),
            p + "post_attention_layernorm.weight": np.ones(
                hidden, np.float32
            ),
        }
        if attn_bias:
            tensors |= {
                p + "self_attn.q_proj.bias": w(heads * head_dim, scale=0.02),
                p + "self_attn.k_proj.bias": w(
                    kv_heads * head_dim, scale=0.02
                ),
                p + "self_attn.v_proj.bias": w(
                    kv_heads * head_dim, scale=0.02
                ),
            }
        if qk_norm:
            tensors |= {
                p + "self_attn.q_norm.weight": 1.0 + w(head_dim, scale=0.1),
                p + "self_attn.k_norm.weight": 1.0 + w(head_dim, scale=0.1),
            }
    os.makedirs(tmpdir, exist_ok=True)
    with open(os.path.join(tmpdir, "config.json"), "w") as f:
        json.dump(cfg, f)
    _save_safetensors(os.path.join(tmpdir, "model.safetensors"), tensors)
    return tmpdir


def make_tiny_opt(
    tmpdir: str,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    ffn: int = 128,
    layers: int = 2,
    heads: int = 4,
    max_pos: int = 512,
    seed: int = 0,
) -> str:
    cfg = {
        "architectures": ["OPTForCausalLM"],
        "model_type": "opt",
        "hidden_size": hidden,
        "ffn_dim": ffn,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "word_embed_proj_dim": hidden,
        "do_layer_norm_before": True,
        "torch_dtype": "float32",
        "activation_function": "relu",
        "bos_token_id": 1,
        "eos_token_id": 2,
        "pad_token_id": 0,
    }
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tensors = {
        "model.decoder.embed_tokens.weight": w(vocab_size, hidden),
        "model.decoder.embed_positions.weight": w(max_pos + 2, hidden),
        "model.decoder.final_layer_norm.weight": np.ones(hidden, np.float32),
        "model.decoder.final_layer_norm.bias": np.zeros(hidden, np.float32),
    }
    for i in range(layers):
        p = f"model.decoder.layers.{i}."
        tensors |= {
            p + "self_attn.q_proj.weight": w(hidden, hidden),
            p + "self_attn.q_proj.bias": np.zeros(hidden, np.float32),
            p + "self_attn.k_proj.weight": w(hidden, hidden),
            p + "self_attn.k_proj.bias": np.zeros(hidden, np.float32),
            p + "self_attn.v_proj.weight": w(hidden, hidden),
            p + "self_attn.v_proj.bias": np.zeros(hidden, np.float32),
            p + "self_attn.out_proj.weight": w(hidden, hidden),
            p + "self_attn.out_proj.bias": np.zeros(hidden, np.float32),
            p + "self_attn_layer_norm.weight": np.ones(hidden, np.float32),
            p + "self_attn_layer_norm.bias": np.zeros(hidden, np.float32),
            p + "final_layer_norm.weight": np.ones(hidden, np.float32),
            p + "final_layer_norm.bias": np.zeros(hidden, np.float32),
            p + "fc1.weight": w(ffn, hidden),
            p + "fc1.bias": np.zeros(ffn, np.float32),
            p + "fc2.weight": w(hidden, ffn),
            p + "fc2.bias": np.zeros(hidden, np.float32),
        }
    os.makedirs(tmpdir, exist_ok=True)
    with open(os.path.join(tmpdir, "config.json"), "w") as f:
        json.dump(cfg, f)
    _save_safetensors(os.path.join(tmpdir, "model.safetensors"), tensors)
    return tmpdir


def add_tiny_tokenizer(model_dir: str) -> str:
    """Attach a 30-word word-level tokenizer (ids < 30, safe for every
    tiny model here) loadable via AutoTokenizer, with a trivial chat
    template so apply_chat_template works."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    words = [
        "<unk>", "<s>", "</s>", "hello", "world", "the", "a", "cat",
        "dog", "sat", "on", "mat", "run", "jump", "stop", "go", "yes",
        "no", "maybe", "one", "two", "three", ".", ",", "!", "?", ":",
        "assistant", "user", "system",
    ]
    vocab = {w: i for i, w in enumerate(words)}
    tok = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    tok.save(os.path.join(model_dir, "tokenizer.json"))
    cfg = {
        "tokenizer_class": "PreTrainedTokenizerFast",
        "unk_token": "<unk>",
        "bos_token": "<s>",
        "eos_token": "</s>",
        "model_max_length": 512,
        "chat_template": (
            "{% for message in messages %}{{ message['role'] }} : "
            "{{ message['content'] }} {% endfor %}"
            "{% if add_generation_prompt %}assistant :{% endif %}"
        ),
    }
    with open(os.path.join(model_dir, "tokenizer_config.json"), "w") as f:
        json.dump(cfg, f)
    return model_dir


def make_tiny_mixtral(
    tmpdir: str,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    intermediate: int = 96,
    layers: int = 2,
    heads: int = 4,
    kv_heads: int = 2,
    num_experts: int = 4,
    top_k: int = 2,
    max_pos: int = 512,
    seed: int = 0,
) -> str:
    head_dim = hidden // heads
    cfg = {
        "architectures": ["MixtralForCausalLM"],
        "model_type": "mixtral",
        "hidden_size": hidden,
        "intermediate_size": intermediate,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": head_dim,
        "num_local_experts": num_experts,
        "num_experts_per_tok": top_k,
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "torch_dtype": "float32",
        "tie_word_embeddings": False,
        "hidden_act": "silu",
        "sliding_window": None,
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w(vocab_size, hidden),
        "model.norm.weight": np.ones(hidden, np.float32),
        "lm_head.weight": w(vocab_size, hidden),
    }
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors |= {
            p + "self_attn.q_proj.weight": w(heads * head_dim, hidden),
            p + "self_attn.k_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.v_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.o_proj.weight": w(hidden, heads * head_dim),
            p + "block_sparse_moe.gate.weight": w(num_experts, hidden, scale=0.3),
            p + "input_layernorm.weight": np.ones(hidden, np.float32),
            p + "post_attention_layernorm.weight": np.ones(hidden, np.float32),
        }
        for e in range(num_experts):
            ep = p + f"block_sparse_moe.experts.{e}."
            tensors |= {
                ep + "w1.weight": w(intermediate, hidden),
                ep + "w2.weight": w(hidden, intermediate),
                ep + "w3.weight": w(intermediate, hidden),
            }
    os.makedirs(tmpdir, exist_ok=True)
    with open(os.path.join(tmpdir, "config.json"), "w") as f:
        json.dump(cfg, f)
    _save_safetensors(os.path.join(tmpdir, "model.safetensors"), tensors)
    return tmpdir


def make_tiny_qwen3_moe(
    tmpdir: str,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    intermediate: int = 128,
    moe_intermediate: int = 48,
    layers: int = 2,
    heads: int = 4,
    kv_heads: int = 2,
    num_experts: int = 4,
    top_k: int = 2,
    max_pos: int = 512,
    seed: int = 1,
) -> str:
    head_dim = hidden // heads
    cfg = {
        "architectures": ["Qwen3MoeForCausalLM"],
        "model_type": "qwen3_moe",
        "hidden_size": hidden,
        "intermediate_size": intermediate,
        "moe_intermediate_size": moe_intermediate,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": head_dim,
        "num_experts": num_experts,
        "num_experts_per_tok": top_k,
        "norm_topk_prob": True,
        "decoder_sparse_step": 1,
        "mlp_only_layers": [],
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "attention_bias": False,
        "torch_dtype": "float32",
        "tie_word_embeddings": False,
        "hidden_act": "silu",
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w(vocab_size, hidden),
        "model.norm.weight": np.ones(hidden, np.float32),
        "lm_head.weight": w(vocab_size, hidden),
    }
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors |= {
            p + "self_attn.q_proj.weight": w(heads * head_dim, hidden),
            p + "self_attn.k_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.v_proj.weight": w(kv_heads * head_dim, hidden),
            p + "self_attn.o_proj.weight": w(hidden, heads * head_dim),
            p + "self_attn.q_norm.weight": np.ones(head_dim, np.float32),
            p + "self_attn.k_norm.weight": np.ones(head_dim, np.float32),
            p + "mlp.gate.weight": w(num_experts, hidden, scale=0.3),
            p + "input_layernorm.weight": np.ones(hidden, np.float32),
            p + "post_attention_layernorm.weight": np.ones(hidden, np.float32),
        }
        for e in range(num_experts):
            ep = p + f"mlp.experts.{e}."
            tensors |= {
                ep + "gate_proj.weight": w(moe_intermediate, hidden),
                ep + "up_proj.weight": w(moe_intermediate, hidden),
                ep + "down_proj.weight": w(hidden, moe_intermediate),
            }
    os.makedirs(tmpdir, exist_ok=True)
    with open(os.path.join(tmpdir, "config.json"), "w") as f:
        json.dump(cfg, f)
    _save_safetensors(os.path.join(tmpdir, "model.safetensors"), tensors)
    return tmpdir


def hf_greedy_generate(model_dir: str, prompt_ids: list[int], max_new: int):
    """Oracle: greedy decode with transformers on torch CPU."""
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32
    )
    model.eval()
    ids = torch.tensor([prompt_ids])
    with torch.no_grad():
        out = model.generate(
            ids,
            max_new_tokens=max_new,
            do_sample=False,
            num_beams=1,
            pad_token_id=0,
        )
    return out[0, len(prompt_ids) :].tolist()


def hf_logits(model_dir: str, prompt_ids: list[int]):
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32
    )
    model.eval()
    with torch.no_grad():
        out = model(torch.tensor([prompt_ids]))
    return out.logits[0].numpy()


def make_tiny_qwen2(tmpdir: str, **kw) -> str:
    """Qwen2: the llama block plus q/k/v biases (attention_bias path)."""
    kw.setdefault("seed", 11)
    return make_tiny_llama(
        tmpdir, arch="Qwen2ForCausalLM", model_type="qwen2",
        attn_bias=True, **kw,
    )


def make_tiny_qwen3(tmpdir: str, **kw) -> str:
    """Qwen3 dense: per-head QK RMS-norm, no attention biases."""
    kw.setdefault("seed", 12)
    return make_tiny_llama(
        tmpdir, arch="Qwen3ForCausalLM", model_type="qwen3",
        qk_norm=True, **kw,
    )


