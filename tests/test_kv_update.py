"""In-place Pallas KV writer vs the functional scatter oracle, in
interpret mode on CPU (ADVICE r2: the production TPU write path needs its
own coverage — input_output_aliases/DMA behavior is where interpret mode
and real Mosaic can diverge, so the bench also re-checks on-chip)."""

import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.ops.attention import write_kv_pages
from vllm_distributed_tpu.ops.pallas.kv_update import kv_update


def _case(rng, *, t, hkv, d_in, d_pool, num_pages=8, page_size=16, slots=None):
    k_pages = jnp.asarray(
        rng.standard_normal((num_pages, page_size, hkv, d_pool)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((num_pages, page_size, hkv, d_pool)), jnp.float32
    )
    k = jnp.asarray(rng.standard_normal((t, hkv, d_in)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, hkv, d_in)), jnp.float32)
    if slots is None:
        slots = rng.choice(num_pages * page_size, size=t, replace=False)
    slots = jnp.asarray(np.asarray(slots, np.int32))
    return k_pages, v_pages, k, v, slots


def _compare(case):
    k_pages, v_pages, k, v, slots = case
    ref_k, ref_v = write_kv_pages(k_pages, v_pages, k, v, slots)
    got_k, got_v = kv_update(k_pages, v_pages, k, v, slots, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(ref_v))


def test_basic_scatter():
    rng = np.random.default_rng(0)
    _compare(_case(rng, t=16, hkv=2, d_in=64, d_pool=64))


def test_lane_padded_pool():
    # Pool head dim lane-padded to 128 while the model head dim is 64:
    # the writer must zero-pad incoming rows (model_runner layout).
    rng = np.random.default_rng(1)
    _compare(_case(rng, t=8, hkv=4, d_in=64, d_pool=128))


def test_duplicate_slots_last_write_wins_consistently():
    # Padding tokens all target reserved page 0; both paths must agree on
    # the surviving row (sequential program order).
    rng = np.random.default_rng(2)
    slots = [5, 5, 5, 17, 17, 3, 0, 0]
    _compare(
        _case(rng, t=8, hkv=2, d_in=64, d_pool=64, slots=slots)
    )


def test_single_token_decode_shape():
    rng = np.random.default_rng(3)
    _compare(_case(rng, t=1, hkv=8, d_in=128, d_pool=128))


def test_bfloat16_pool_casts_inputs():
    rng = np.random.default_rng(4)
    k_pages, v_pages, k, v, slots = _case(rng, t=4, hkv=2, d_in=64, d_pool=64)
    k_pages = k_pages.astype(jnp.bfloat16)
    v_pages = v_pages.astype(jnp.bfloat16)
    ref_k, ref_v = write_kv_pages(k_pages, v_pages, k, v, slots)
    got_k, got_v = kv_update(k_pages, v_pages, k, v, slots, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got_k, np.float32), np.asarray(ref_k, np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(got_v, np.float32), np.asarray(ref_v, np.float32)
    )
