"""In-place Pallas KV writer vs the functional scatter oracle, in
interpret mode on CPU (ADVICE r2: the production TPU write path needs its
own coverage — input_output_aliases/DMA behavior is where interpret mode
and real Mosaic can diverge, so the bench also re-checks on-chip)."""

import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.ops.attention import (
    kv_pool_shape,
    split_kv_pages,
    write_kv_pages,
)
from vllm_distributed_tpu.ops.pallas.kv_update import kv_update


def _case(rng, *, t, hkv, d, num_pages=8, page_size=16, slots=None):
    kv_pages = jnp.asarray(
        rng.standard_normal(kv_pool_shape(num_pages, page_size, hkv, d)),
        jnp.float32,
    )
    k = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    if slots is None:
        slots = rng.choice(num_pages * page_size, size=t, replace=False)
    slots = jnp.asarray(np.asarray(slots, np.int32))
    return kv_pages, k, v, slots


def _compare(case):
    kv_pages, k, v, slots = case
    ref = write_kv_pages(kv_pages, k, v, slots)
    got = kv_update(kv_pages, k, v, slots, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_basic_scatter():
    rng = np.random.default_rng(0)
    _compare(_case(rng, t=16, hkv=2, d=64))


def test_sub_tile_width_pool():
    # hkv*d = 64 < the 128-lane tile: the unpadded flat pool still
    # round-trips through the writer (tiny-model / per-shard shapes).
    rng = np.random.default_rng(1)
    kv_pages, k, v, slots = _case(rng, t=8, hkv=1, d=64)
    assert kv_pages.shape[-1] == 64
    _compare((kv_pages, k, v, slots))


def test_duplicate_slots():
    # Padding tokens all target reserved page 0 (never read), so which
    # duplicate write survives is NOT part of the contract — XLA scatter
    # leaves duplicate-index ordering unspecified.  Assert that unique
    # slots match the oracle exactly and each duplicated slot holds one
    # of its candidate rows.
    rng = np.random.default_rng(2)
    slots = [5, 5, 5, 17, 17, 3, 0, 0]
    kv_pages, k, v, slots_j = _case(rng, t=8, hkv=2, d=64, slots=slots)
    page_size = kv_pages.shape[2]
    ref = write_kv_pages(kv_pages, k, v, slots_j)
    got = kv_update(kv_pages, k, v, slots_j, interpret=True)
    ref_k, _ = split_kv_pages(ref, 2, 64)
    got_k, got_v = split_kv_pages(got, 2, 64)
    got_k, got_v = np.asarray(got_k), np.asarray(got_v)
    k_np, v_np = np.asarray(k), np.asarray(v)
    for slot in set(slots):
        writers = [i for i, s in enumerate(slots) if s == slot]
        gk = got_k[slot // page_size, slot % page_size]
        gv = got_v[slot // page_size, slot % page_size]
        if len(writers) == 1:
            np.testing.assert_array_equal(
                gk, np.asarray(ref_k)[slot // page_size, slot % page_size]
            )
            np.testing.assert_array_equal(gk, k_np[writers[0]])
        else:
            assert any(
                np.array_equal(gk, k_np[i]) and np.array_equal(gv, v_np[i])
                for i in writers
            ), f"slot {slot} holds a row no writer produced"


def test_single_token_decode_shape():
    rng = np.random.default_rng(3)
    _compare(_case(rng, t=1, hkv=8, d=128))


def test_bfloat16_pool_casts_inputs():
    rng = np.random.default_rng(4)
    kv_pages, k, v, slots = _case(rng, t=4, hkv=2, d=64)
    kv_pages = kv_pages.astype(jnp.bfloat16)
    ref = write_kv_pages(kv_pages, k, v, slots)
    got = kv_update(kv_pages, k, v, slots, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(ref, np.float32)
    )
