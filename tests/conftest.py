"""Test bootstrap: force JAX onto CPU with 8 virtual devices so sharding
tests exercise real multi-device meshes without TPU hardware (SURVEY.md §4
item 4).

The TPU tunnel's sitecustomize imports jax at interpreter startup, so env
vars set here are too late for jax's import-time defaults;
`jax.config.update` before first backend use still works because backends
initialize lazily."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("VDT_PLATFORM", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
