"""Test bootstrap: force JAX onto CPU with 8 virtual devices BEFORE jax
is imported anywhere, so sharding tests exercise real multi-device meshes
without TPU hardware (SURVEY.md §4 item 4)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("VDT_PLATFORM", "cpu")
