"""Test bootstrap: force JAX onto CPU with 8 virtual devices so sharding
tests exercise real multi-device meshes without TPU hardware (SURVEY.md §4
item 4).

The TPU tunnel's sitecustomize imports jax at interpreter startup, so env
vars set here are too late for jax's import-time defaults;
`jax.config.update` before first backend use still works because backends
initialize lazily."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("VDT_PLATFORM", "cpu")
# Hermetic compile cache: the shared default dir can hold entries
# produced by a remote AOT compiler with different host features, whose
# loader errors spam every test log (VERDICT r4 weak #8).
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

if "VDT_COMPILE_CACHE_DIR" not in os.environ:
    _cache = tempfile.mkdtemp(prefix="vdt_test_cache_")
    os.environ["VDT_COMPILE_CACHE_DIR"] = _cache
    atexit.register(shutil.rmtree, _cache, ignore_errors=True)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
