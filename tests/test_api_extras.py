"""API parity extras (VERDICT r2 missing #6/#7): /v1/embeddings, prompt
logprobs with echo, API-key auth, and the KV-connector output hook."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.utils import add_tiny_tokenizer, hf_logits, make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.executor.kv_aggregator import KVOutputAggregator
from vllm_distributed_tpu.outputs import ModelRunnerOutput
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = make_tiny_llama(str(tmp_path_factory.mktemp("apix")))
    add_tiny_tokenizer(d)
    return d


@pytest.fixture(scope="module")
def served(model_dir):
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir, num_kv_pages=128, max_model_len=256,
            max_num_seqs=8,
        )
    )
    state = init_app_state(
        engine, served_model_name="tiny", api_key="sekrit"
    )
    yield lambda: build_app(state)
    engine.shutdown()


def _call(make_app, coro_fn):
    async def go():
        server = TestServer(make_app())
        client = TestClient(server)
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.new_event_loop().run_until_complete(go())


AUTH = {"Authorization": "Bearer sekrit"}


def test_api_key_auth(served):
    async def go(client):
        # Unauthenticated: /v1 endpoints reject, probes stay open.
        r = await client.post("/v1/completions", json={"prompt": "x"})
        assert r.status == 401
        r = await client.post(
            "/v1/completions",
            json={"prompt": "x", "max_tokens": 1},
            headers={"Authorization": "Bearer wrong"},
        )
        assert r.status == 401
        assert (await client.get("/health")).status == 200
        assert (await client.get("/metrics")).status == 200
        r = await client.post(
            "/v1/completions",
            json={"prompt": "hello", "max_tokens": 2},
            headers=AUTH,
        )
        assert r.status == 200

    _call(served, go)


def test_embeddings_endpoint(served):
    async def go(client):
        r = await client.post(
            "/v1/embeddings",
            json={"input": ["hello world", "the cat sat"]},
            headers=AUTH,
        )
        assert r.status == 200
        data = await r.json()
        vecs = [np.asarray(d["embedding"]) for d in data["data"]]
        assert len(vecs) == 2 and vecs[0].shape == (64,)  # hidden_size
        for v in vecs:
            assert abs(np.linalg.norm(v) - 1.0) < 1e-5  # L2-normalized
        assert not np.allclose(vecs[0], vecs[1])
        # Deterministic
        r2 = await client.post(
            "/v1/embeddings", json={"input": "hello world"}, headers=AUTH
        )
        v2 = np.asarray((await r2.json())["data"][0]["embedding"])
        assert np.allclose(v2, vecs[0], atol=1e-6)

    _call(served, go)


def test_prompt_logprobs_echo(served, model_dir):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": "hello world the cat",
                "max_tokens": 2,
                "temperature": 0,
                "echo": True,
                "logprobs": 1,
            },
            headers=AUTH,
        )
        assert r.status == 200
        return await r.json()

    data = _call(served, go)
    choice = data["choices"][0]
    lp = choice["logprobs"]
    # 4 prompt tokens + 2 completion tokens.
    assert len(lp["tokens"]) == 6
    assert lp["token_logprobs"][0] is None  # first prompt token: no ctx
    assert choice["text"].startswith("hello world the cat")

    # Oracle: teacher-forced prompt logprobs vs transformers.
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(model_dir)
    ids = tok.encode("hello world the cat")
    ref = hf_logits(model_dir, ids)
    shifted = ref - ref.max(-1, keepdims=True)
    logps = shifted - np.log(np.exp(shifted).sum(-1, keepdims=True))
    for i in range(1, len(ids)):
        assert abs(lp["token_logprobs"][i] - logps[i - 1, ids[i]]) < 2e-3


def test_kv_aggregator_merges_world_progress():
    agg = KVOutputAggregator(world_size=2)

    def out(sending=(), recving=()):
        o = ModelRunnerOutput()
        o.kv_finished_sending = set(sending)
        o.kv_finished_recving = set(recving)
        return o

    # Step 1: only worker 0 finished sending r1 -> not globally done.
    merged = agg.aggregate([out(sending=["r1"]), out()], output_rank=0)
    assert merged.kv_finished_sending == set()
    # Step 2: worker 1 catches up -> now done.
    merged = agg.aggregate([out(), out(sending=["r1"])], output_rank=0)
    assert merged.kv_finished_sending == {"r1"}
    # Recv side, both at once.
    merged = agg.aggregate(
        [out(recving=["r2"]), out(recving=["r2"])], output_rank=0
    )
    assert merged.kv_finished_recving == {"r2"}


def test_kv_transfer_config_engine_path(model_dir):
    """With --kv-transfer-config set the engine runs through the
    aggregated all-worker path end to end."""
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
            kv_transfer_config='{"kv_connector": "noop"}',
        )
    )
    assert engine.config.kv_transfer_config == {"kv_connector": "noop"}
    engine.add_request(
        "k",
        prompt_token_ids=[1, 5, 9],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=4, ignore_eos=True
        ),
    )
    toks = None
    while engine.has_unfinished_requests():
        for o in engine.step():
            toks = o.outputs[0].token_ids
    assert len(toks) == 4


def test_get_tokenizer_info(served):
    """Parity: the reference registers vLLM's tokenizer-info endpoint
    (launch.py:34, 428)."""
    async def go(client):
        r = await client.get("/get_tokenizer_info", headers=AUTH)
        assert r.status == 200
        data = await r.json()
        assert data["vocab_size"] and data["tokenizer_class"]

    _call(served, go)
