"""RPC peer/transport unit tests (SURVEY.md §4 item 1: param, apply,
oneway, error propagation, finalize/distributed GC, sideband buffers,
disconnect detection) — in-process with paired transports."""

import asyncio
import gc
import multiprocessing
import pickle

import pytest

from vllm_distributed_tpu.distributed.rpc import RpcPeer, RPCResultError
from vllm_distributed_tpu.distributed.rpc_transport import (
    ConnectionRpcTransport,
    StreamRpcTransport,
    prepare_peer_readloop,
)


def make_peer_pair():
    """Two RpcPeers wired directly (serialize → handle_message)."""
    peers = {}

    def make_send(name):
        async def send(msg, buffers):
            # Simulate the wire: the envelope must be picklable.
            data = pickle.loads(pickle.dumps({"m": msg}))["m"]
            await peers[name].handle_message(data, buffers)

        return send

    a = RpcPeer(make_send("b"), "a")
    b = RpcPeer(make_send("a"), "b")
    peers["a"], peers["b"] = a, b
    return a, b


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_param_roundtrip():
    async def go():
        a, b = make_peer_pair()
        b.params["greeting"] = "hello"
        assert await a.get_param("greeting") == "hello"
        # camelCase alias (reference surface, launch.py:190)
        assert await a.getParam("greeting") == "hello"

    run(go())


def test_param_missing_raises_remote_error():
    async def go():
        a, b = make_peer_pair()
        with pytest.raises(RPCResultError) as ei:
            await a.get_param("nope")
        assert "KeyError" in ei.value.name

    run(go())


def test_apply_function_and_kwargs():
    async def go():
        a, b = make_peer_pair()

        def add(x, y, scale=1):
            return (x + y) * scale

        b.params["add"] = add
        proxy = await a.get_param("add")
        assert await proxy(2, 3) == 5
        assert await proxy(2, 3, scale=10) == 50

    run(go())


def test_apply_async_function():
    async def go():
        a, b = make_peer_pair()

        async def work(x):
            await asyncio.sleep(0)
            return x * 2

        b.params["work"] = work
        proxy = await a.get_param("work")
        assert await proxy(21) == 42

    run(go())


def test_object_method_dispatch():
    async def go():
        a, b = make_peer_pair()

        class Service:
            __rpc_proxy__ = True

            def __init__(self):
                self.calls = []

            def ping(self, tag):
                self.calls.append(tag)
                return f"pong-{tag}"

        svc = Service()
        b.params["svc"] = svc
        proxy = await a.get_param("svc")
        assert await proxy.ping("x") == "pong-x"
        assert svc.calls == ["x"]

    run(go())


def test_remote_error_carries_stack():
    async def go():
        a, b = make_peer_pair()

        def boom():
            raise ValueError("kaput")

        b.params["boom"] = boom
        proxy = await a.get_param("boom")
        with pytest.raises(RPCResultError) as ei:
            await proxy()
        assert ei.value.name == "ValueError"
        assert "kaput" in ei.value.message
        assert "boom" in ei.value.remote_stack  # remote frames visible

    run(go())


def test_callback_proxying_both_directions():
    """A callable passed as an argument becomes a proxy callable on the
    remote side (the create_worker/run_worker pattern, launch.py:238)."""

    async def go():
        a, b = make_peer_pair()
        got = []

        async def factory(callback):
            result = callback("from-b")  # proxy → returns awaitable
            got.append(await result)
            return "done"

        b.params["factory"] = factory
        proxy = await a.get_param("factory")

        def my_cb(msg):
            return f"a-saw-{msg}"

        assert await proxy(my_cb) == "done"
        assert got == ["a-saw-from-b"]

    run(go())


def test_value_passthrough_of_picklable_objects():
    async def go():
        a, b = make_peer_pair()

        def echo(x):
            return x

        b.params["echo"] = echo
        proxy = await a.get_param("echo")
        payload = {"nested": [1, 2.5, "s", None, {"k": (1, 2)}]}
        out = await proxy(payload)
        assert out["nested"][0] == 1
        assert out["nested"][4]["k"] == [1, 2] or out["nested"][4]["k"] == (1, 2)

    run(go())


def test_sideband_buffers_fifo():
    async def go():
        a, b = make_peer_pair()

        def concat(x, y):
            return x + y

        b.params["concat"] = concat
        proxy = await a.get_param("concat")
        # Two buffers in one message must not be swapped (reference LIFO
        # bug, rpc_reader.py:33-38).
        out = await proxy(b"first-", b"second")
        assert out == b"first-second"

    run(go())


def test_finalize_releases_remote_object():
    async def go():
        a, b = make_peer_pair()

        def handler():
            return "hi"

        b.params["h"] = handler
        proxy = await a.get_param("h")
        assert len(b._local_proxied) == 1
        del proxy
        gc.collect()
        await asyncio.sleep(0.05)  # let the finalize task run
        assert len(b._local_proxied) == 0

    run(go())


def test_kill_fails_pending_and_future_calls():
    async def go():
        a, b = make_peer_pair()

        def fn():
            return 1

        b.params["fn"] = fn
        proxy = await a.get_param("fn")
        a.kill("test disconnect")
        with pytest.raises(RPCResultError):
            await proxy()

    run(go())


def test_tcp_stream_transport_end_to_end():
    async def go():
        server_peer_box = {}

        async def on_client(reader, writer):
            transport = StreamRpcTransport(reader, writer)
            peer, readloop = prepare_peer_readloop(transport, "server")
            peer.params["mul"] = lambda x, y: x * y
            server_peer_box["peer"] = peer
            await readloop()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        transport = StreamRpcTransport(reader, writer)
        peer, readloop = prepare_peer_readloop(transport, "client")
        loop_task = asyncio.ensure_future(readloop())

        mul = await peer.get_param("mul")
        assert await mul(6, 7) == 42

        # Disconnect detection: closing the client socket EOFs the server
        # readloop, which kills the server peer and closes its writer,
        # which in turn EOFs and kills the client peer.
        writer.close()
        await asyncio.sleep(0.1)
        assert server_peer_box["peer"].killed
        assert peer.killed
        server.close()
        loop_task.cancel()

    run(go())


def test_callback_over_real_transport_no_deadlock():
    """A remote handler that awaits an RPC back to the caller must not
    deadlock the readloop (apply handling runs as a task)."""

    async def go():
        async def on_client(reader, writer):
            t = StreamRpcTransport(reader, writer)
            peer, readloop = prepare_peer_readloop(t, "server")

            async def factory(callback):
                return await callback("ping")

            peer.params["factory"] = factory
            await readloop()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        peer, readloop = prepare_peer_readloop(
            StreamRpcTransport(reader, writer), "client"
        )
        task = asyncio.ensure_future(readloop())
        factory = await peer.get_param("factory")
        result = await asyncio.wait_for(
            factory(lambda msg: f"echo-{msg}"), timeout=5
        )
        assert result == "echo-ping"
        writer.close()
        server.close()
        task.cancel()

    run(go())


def _child_proc(conn):
    async def main():
        transport = ConnectionRpcTransport(conn)
        peer, readloop = prepare_peer_readloop(transport, "child")
        peer.params["double"] = lambda x: x * 2
        try:
            await readloop()
        except (EOFError, OSError):
            pass

    asyncio.new_event_loop().run_until_complete(main())


def test_pipe_transport_cross_process():
    async def go():
        parent_conn, child_conn = multiprocessing.Pipe()
        proc = multiprocessing.Process(
            target=_child_proc, args=(child_conn,), daemon=True
        )
        proc.start()
        transport = ConnectionRpcTransport(parent_conn)
        peer, readloop = prepare_peer_readloop(transport, "parent")
        loop_task = asyncio.ensure_future(readloop())
        double = await peer.get_param("double")
        assert await double(21) == 42
        proc.terminate()
        proc.join(timeout=5)
        loop_task.cancel()

    run(go())
