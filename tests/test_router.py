"""Multi-replica router suite (ISSUE 10): affinity placement + live
request migration over journal-replay.

Layered like the feature: unit tests for the prefix-affinity index
(scoring, block granularity, LRU eviction), placement policy, the
router journal, and the exposition merger; replica-side tests for the
ISSUE 10 metadata surfaces (replica_id, vdt_token_ids stream metadata,
/internal/resume); and mocked 2-replica e2e tests asserting the
acceptance criteria — killing or draining the replica serving an
in-flight SSE request migrates it to the survivor with the stream
uninterrupted and greedy output bit-identical (the mock worker's
VDT_MOCK_TOKEN_SEQ position-token mode makes any dropped, duplicated,
or restarted token change the sequence), and affinity routing beats
round-robin on prefix-cache hits for a shared-prefix workload.
"""

from __future__ import annotations

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockUniProcExecutor, MockWorker  # noqa: F401
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
    serve_http,
)
from vllm_distributed_tpu.router.affinity import PrefixAffinityIndex
from vllm_distributed_tpu.router.app import RouterState, build_router_app
from vllm_distributed_tpu.router.journal import RouterJournal
from vllm_distributed_tpu.router.metrics import merge_expositions
from vllm_distributed_tpu.router.pool import parse_load_gauges
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port

pytestmark = pytest.mark.router


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------
# affinity index units
# ---------------------------------------------------------------------
def test_affinity_longest_prefix_scoring():
    idx = PrefixAffinityIndex(block_tokens=4, capacity=64)
    base = list(range(16))
    idx.observe("r1", idx.keys_for(prompt_token_ids=base))
    # Full match: all 16 tokens warm.
    assert idx.score(idx.keys_for(prompt_token_ids=base)) == {"r1": 16}
    # Shared 8-token prefix, divergent tail: only the prefix counts.
    probe = base[:8] + [99] * 8
    assert idx.score(idx.keys_for(prompt_token_ids=probe)) == {"r1": 8}
    # Divergence INSIDE the first block breaks the whole chain.
    probe = [99] + base[1:]
    assert idx.score(idx.keys_for(prompt_token_ids=probe)) == {}
    # Sub-block leftovers don't create a key (nothing page-aligned to
    # reuse).
    assert idx.keys_for(prompt_token_ids=[1, 2]) != []
    assert len(idx.keys_for(prompt_token_ids=[1, 2])) == 1


def test_affinity_scores_are_per_replica():
    idx = PrefixAffinityIndex(block_tokens=4, capacity=64)
    a, b = list(range(8)), list(range(100, 108))
    idx.observe("rA", idx.keys_for(prompt_token_ids=a))
    idx.observe("rB", idx.keys_for(prompt_token_ids=b))
    assert idx.score(idx.keys_for(prompt_token_ids=a)) == {"rA": 8}
    assert idx.score(idx.keys_for(prompt_token_ids=b)) == {"rB": 8}
    idx.forget("rA")
    assert idx.score(idx.keys_for(prompt_token_ids=a)) == {}
    assert idx.num_blocks("rA") == 0 and idx.num_blocks("rB") == 2


def test_affinity_lru_eviction():
    idx = PrefixAffinityIndex(block_tokens=4, capacity=4)
    old = idx.keys_for(prompt_token_ids=list(range(8)))  # 2 blocks
    new = idx.keys_for(prompt_token_ids=list(range(50, 70)))  # 5 blocks
    idx.observe("r1", old)
    idx.observe("r1", new)
    # Capacity 4 < 2 + 5: the old chain was evicted first.
    assert idx.num_blocks("r1") == 4
    assert idx.score(old) == {}
    # The newest chain's most recent blocks survive; its head may have
    # been evicted by its own tail, so only assert boundedness + that
    # re-observing refreshes.
    idx.observe("r1", old)
    assert idx.score(old) == {"r1": 8}


def test_affinity_text_and_token_namespaces_disjoint():
    idx = PrefixAffinityIndex(block_tokens=4, capacity=64)
    idx.observe("r1", idx.keys_for(prompt_text="abcd" * 8))
    # The same bytes as token ids must not cross-match the text chain.
    assert idx.score(idx.keys_for(prompt_token_ids=[1, 2, 3, 4])) == {}
    assert idx.score(idx.keys_for(prompt_text="abcd" * 8)) == {"r1": 8}
    # Text chains match on shared prefixes too.
    assert idx.score(idx.keys_for(prompt_text="abcd" * 4 + "zz")) == {
        "r1": 4
    }


# ---------------------------------------------------------------------
# placement units
# ---------------------------------------------------------------------
def _router_state(policy="affinity", **kw) -> RouterState:
    kw.setdefault("affinity_block_tokens", 4)
    kw.setdefault("affinity_min_tokens", 8)
    kw.setdefault("max_migrations", 3)
    kw.setdefault("health_interval", 60.0)
    kw.setdefault("connect_timeout", 1.0)
    kw.setdefault("read_timeout", 5.0)
    state = RouterState(
        ["http://a:1", "http://b:2"], policy=policy, **kw
    )
    for r in state.pool.replicas:
        r.state = "healthy"
    return state


def test_placement_affinity_wins_over_load():
    state = _router_state()
    ra, rb = state.pool.replicas
    keys = state.index.keys_for(prompt_token_ids=list(range(16)))
    state.index.observe(ra.replica_id, keys)
    # rb is idle, ra is loaded — affinity still picks ra (the warm
    # cache saves more than the queue costs).
    ra.waiting = 5.0
    replica, how = state.place(keys, set())
    assert (replica, how) == (ra, "affinity")
    # Below the min-token threshold the affinity signal is noise:
    # fall back to least-loaded (rb).
    weak = state.index.keys_for(prompt_token_ids=list(range(4)) + [99] * 12)
    replica, how = state.place(weak, set())
    assert (replica, how) == (rb, "least_loaded")


def test_placement_excludes_unhealthy_and_backed_off():
    state = _router_state(policy="least_loaded")
    ra, rb = state.pool.replicas
    rb.state = "draining"
    replica, _ = state.place([], set())
    assert replica is ra
    state.pool.note_backoff(ra, 30.0)
    assert state.place([], set()) == (None, "none")
    rb.state = "healthy"
    replica, _ = state.place([], set())
    assert replica is rb
    # Explicit exclusion (a migration's victim) wins over everything.
    assert state.place([], {rb.url}) == (None, "none")


def test_placement_round_robin_cycles():
    state = _router_state(policy="round_robin")
    picks = {state.place([], set())[0].replica_id for _ in range(4)}
    assert len(picks) == 2  # both replicas used


# ---------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------
def test_journal_strips_metadata_and_accumulates():
    j = RouterJournal(
        "rtr-1", "completions", {"prompt": [1, 2, 3], "n": 1, "stream": True}
    )
    out = j.observe_choice(
        {
            "index": 0,
            "text": "ab",
            "vdt_token_ids": [3, 4],
            "vdt_prompt_token_ids": [1, 2, 3],
            "finish_reason": None,
        }
    )
    assert "vdt_token_ids" not in out
    assert "vdt_prompt_token_ids" not in out
    j.observe_choice(
        {"index": 0, "text": "cd", "vdt_token_ids": [5], "finish_reason": None}
    )
    c = j.choices[0]
    assert c.emitted_token_ids == [3, 4, 5]
    assert c.forwarded_text_len == 4
    assert c.prompt_token_ids == [1, 2, 3]
    assert not c.finished and j.unfinished() == [c]
    j.observe_choice({"index": 0, "text": "", "finish_reason": "length"})
    assert c.finished and j.unfinished() == []
    payload = j.resume_payload(c)
    assert payload["prompt_token_ids"] == [1, 2, 3]
    assert payload["emitted_token_ids"] == [3, 4, 5]
    assert payload["kind"] == "completions"
    assert payload["body"]["prompt"] == [1, 2, 3]
    # Unique per (migration, choice): a resume id can never collide
    # with the victim's engine-side id.
    j.migrations = 2
    assert payload != j.resume_payload(c)


def test_journal_resume_payload_carries_slo_class():
    """ISSUE 16 satellite: a migrated request keeps its QoS standing.
    The journal records the class the router observed (header or body)
    and the resume payload carries it top-level, so the destination
    replica bills the same bucket even when the replayed body never
    named it."""
    j = RouterJournal(
        "rtr-q", "completions", {"prompt": [1, 2], "n": 1, "stream": True}
    )
    c = j.choices[0]
    assert j.slo_class is None
    assert j.resume_payload(c)["slo_class"] is None
    j.slo_class = "interactive"
    assert j.resume_payload(c)["slo_class"] == "interactive"


def test_journal_multi_prompt_choice_indexing():
    j = RouterJournal(
        "rtr-2",
        "completions",
        {"prompt": [[1, 2], [3, 4]], "n": 2, "stream": True},
    )
    # prompt-major, sample-minor — the order the replica assigns.
    assert sorted(j.choices) == [0, 1, 2, 3]
    assert j.choices[0].prompt_token_ids == [1, 2]
    assert j.choices[1].prompt_token_ids == [1, 2]
    assert j.choices[2].prompt_token_ids == [3, 4]
    text, ids = j.affinity_source()
    assert ids == [1, 2]


def test_journal_chat_affinity_source():
    j = RouterJournal(
        "rtr-3",
        "chat",
        {
            "messages": [
                {"role": "system", "content": "be brief"},
                {"role": "user", "content": "hello"},
            ],
            "stream": True,
        },
    )
    text, ids = j.affinity_source()
    assert ids is None
    assert "be brief" in text and "hello" in text


# ---------------------------------------------------------------------
# metrics merging / gauge parsing units
# ---------------------------------------------------------------------
def test_merge_expositions_labels_and_dedupes():
    ra = (
        "# HELP vllm:x doc\n# TYPE vllm:x gauge\n"
        'vllm:x{model_name="m"} 1.0\n'
    )
    rb = (
        "# HELP vllm:x doc\n# TYPE vllm:x gauge\n"
        "vllm:x 2.0\n"
    )
    merged = merge_expositions([("r0", ra), ("r1", rb)])
    assert merged.count("# TYPE vllm:x gauge") == 1
    assert 'vllm:x{model_name="m",replica="r0"} 1.0' in merged
    assert 'vllm:x{replica="r1"} 2.0' in merged


def test_merge_expositions_new_slo_families_once_per_replica():
    """ISSUE 12 satellite: each new mergeable-histogram family (the
    per-class vllm:slo_* and device-telemetry families) must appear
    EXACTLY once in the merged exposition — one HELP/TYPE — with every
    replica's samples re-labeled under it."""
    families = (
        ("vllm:slo_ttft_ms", "histogram"),
        ("vllm:slo_itl_ms", "histogram"),
        ("vllm:xla_compile_seconds", "histogram"),
        ("vllm:slo_requests_total", "counter"),
        ("vllm:goodput_requests_total", "counter"),
        ("vllm:hbm_live_bytes", "gauge"),
    )

    def exposition(value: float) -> str:
        lines = []
        for name, kind in families:
            lines.append(f"# HELP {name} doc")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                lines.append(
                    f'{name}_bucket{{slo_class="chat",le="+Inf"}} {value}'
                )
                lines.append(f'{name}_count{{slo_class="chat"}} {value}')
                lines.append(f'{name}_sum{{slo_class="chat"}} {value}')
            else:
                lines.append(f'{name}{{slo_class="chat"}} {value}')
        return "\n".join(lines) + "\n"

    merged = merge_expositions(
        [("r0", exposition(1.0)), ("r1", exposition(2.0))]
    )
    for name, kind in families:
        assert merged.count(f"# TYPE {name} {kind}") == 1, name
        sample = f"{name}_count" if kind == "histogram" else name
        assert f'{sample}{{slo_class="chat",replica="r0"}} 1.0' in merged
        assert f'{sample}{{slo_class="chat",replica="r1"}} 2.0' in merged


def test_parse_load_gauges():
    text = (
        "# TYPE vllm:num_requests_waiting gauge\n"
        'vllm:num_requests_waiting{model_name="m"} 3.0\n'
        'vllm:admission_queued_tokens{model_name="m"} 128.0\n'
        "vllm:other 9\n"
    )
    gauges = parse_load_gauges(text)
    assert gauges["vllm:num_requests_waiting"] == 3.0
    assert gauges["vllm:admission_queued_tokens"] == 128.0
    assert "vllm:other" not in gauges


# ---------------------------------------------------------------------
# replica-side surfaces (mock uniproc engine behind the real app)
# ---------------------------------------------------------------------
def _mk_engine(model_dir: str, **kw) -> AsyncLLM:
    args = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=64,
        max_model_len=128,
        num_decode_steps=1,
        distributed_executor_backend=MockUniProcExecutor,
    )
    args.update(kw)
    return AsyncLLM.from_engine_args(EngineArgs(**args))


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return write_llama_config(
        str(tmp_path_factory.mktemp("router") / "m")
    )


def _sse_chunks(body: str) -> list[dict]:
    out = []
    for line in body.splitlines():
        if line.startswith("data: ") and line[6:] != "[DONE]":
            out.append(json.loads(line[6:]))
    return out


def test_replica_id_and_stream_metadata(model_dir, monkeypatch):
    """ISSUE 10 satellites on the replica: /health body + response
    header carry the replica id, the vllm:replica_info gauge renders,
    and vdt_* stream metadata appears ONLY under the router header."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    engine = _mk_engine(model_dir)
    state = init_app_state(
        engine, served_model_name="meta", replica_id="replica-7"
    )

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.get("/health")
            assert r.status == 200
            assert (await r.json())["replica_id"] == "replica-7"
            assert r.headers["X-VDT-Replica-Id"] == "replica-7"
            metrics_text = await (await client.get("/metrics")).text()
            assert 'replica_id="replica-7"' in metrics_text
            assert "vllm:replica_info" in metrics_text

            body = {
                "prompt": [1, 2, 3],
                "max_tokens": 4,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            r = await client.post(
                "/v1/completions", json=body,
                headers={"X-VDT-Router": "1"},
            )
            assert r.headers["X-VDT-Replica-Id"] == "replica-7"
            chunks = _sse_chunks(await r.text())
            ids = [
                t
                for c in chunks
                for ch in c.get("choices") or ()
                for t in ch.get("vdt_token_ids") or ()
            ]
            assert ids == [3, 4, 5, 6]
            assert chunks[0]["choices"][0]["vdt_prompt_token_ids"] == [
                1, 2, 3,
            ]
            # Without the router header the wire format is untouched.
            r = await client.post("/v1/completions", json=body)
            for c in _sse_chunks(await r.text()):
                for ch in c.get("choices") or ():
                    assert "vdt_token_ids" not in ch
                    assert "vdt_prompt_token_ids" not in ch
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


def test_internal_resume_bit_identical(model_dir, monkeypatch):
    """The migration primitive in isolation: a resume with k delivered
    tokens restored continues with EXACTLY the tokens an uninterrupted
    run produces after position k (VDT_MOCK_TOKEN_SEQ: token i = i)."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    engine = _mk_engine(model_dir)
    state = init_app_state(engine, served_model_name="resume")
    body = {
        "prompt": [1, 2, 3],
        "max_tokens": 6,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }
    expected = list(range(3, 9))  # positions 3..8

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.post(
                "/internal/resume",
                json={
                    "request_id": "mig-1",
                    "kind": "completions",
                    "body": body,
                    "prompt_token_ids": [1, 2, 3],
                    "emitted_token_ids": expected[:2],
                    "slo_class": "interactive",
                },
            )
            assert r.status == 200
            frames = _sse_chunks(await r.text())
            new_ids = [
                t for f in frames for t in f.get("token_ids") or ()
            ]
            assert new_ids == expected[2:]
            assert frames[0]["prompt_token_ids"] == [1, 2, 3]
            final = frames[-1]
            assert final["finish_reason"] == "length"
            assert final["usage"]["completion_tokens"] == 6
            # The migrated request kept its QoS standing (ISSUE 16):
            # the destination replica billed the journaled class even
            # though the replayed body never named it.
            r = await client.get("/slo")
            assert r.status == 200
            classes = (await r.json())["classes"]
            assert classes["interactive"]["requests"] >= 1
            # A draining replica refuses migrations (503).
            await engine.drain(0.0)
            r = await client.post(
                "/internal/resume",
                json={
                    "request_id": "mig-2",
                    "kind": "completions",
                    "body": body,
                    "prompt_token_ids": [1, 2, 3],
                    "emitted_token_ids": [],
                },
            )
            assert r.status == 503
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


def test_internal_resume_duplicate_takeover(model_dir, monkeypatch):
    """/internal/resume is idempotent per request id (ISSUE 17): a
    router that crashed mid-hand-off replays the SAME id after restart
    without knowing whether the first POST landed.  The replay must win
    cleanly — the original handler is torn down, the replay streams the
    full bit-identical continuation, and the engine never wedges on a
    double-registered id."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.05")
    engine = _mk_engine(model_dir)
    state = init_app_state(engine, served_model_name="resume")
    body = {
        "prompt": [1, 2, 3],
        "max_tokens": 8,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }
    expected = list(range(3, 11))
    payload = {
        "request_id": "mig-dup",
        "kind": "completions",
        "body": body,
        "prompt_token_ids": [1, 2, 3],
        "emitted_token_ids": expected[:2],
    }

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r1 = await client.post("/internal/resume", json=payload)
            assert r1.status == 200
            # Read up to the first data frame so the original handler
            # is demonstrably live mid-stream when the replay lands.
            saw_frame = False
            async for raw in r1.content:
                if raw.strip().startswith(b"data:"):
                    saw_frame = True
                    break
            assert saw_frame
            # The replay: same id, same journal checkpoint.  Must not
            # hang or 409 — it takes over and delivers the whole
            # continuation from the checkpoint.
            r2 = await asyncio.wait_for(
                client.post("/internal/resume", json=payload), timeout=30
            )
            assert r2.status == 200
            frames = _sse_chunks(
                await asyncio.wait_for(r2.text(), timeout=30)
            )
            new_ids = [
                t for f in frames for t in f.get("token_ids") or ()
            ]
            assert new_ids == expected[2:]
            assert frames[-1]["finish_reason"] == "length"
            r1.close()
            # No takeover bookkeeping leaks once the winner finishes.
            assert state.resume_takeovers == {}
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


def test_trace_header_parents_replica_span(model_dir, monkeypatch):
    """PR 4 trace context through the router hop: a request arriving
    with X-VDT-Trace-Id '<trace>-<span>' parents the replica's
    api.request span under it instead of rooting a new trace."""
    from vllm_distributed_tpu.tracing import get_tracer

    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    tracer = get_tracer()
    # Engine boot reconfigures the global tracer from its config, so
    # tracing must be enabled THROUGH the engine args.
    engine = _mk_engine(model_dir, enable_tracing=True)
    state = init_app_state(engine, served_model_name="trace")
    trace_id, span_id = "ab" * 16, "cd" * 8

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.post(
                "/v1/completions",
                json={
                    "prompt": [1, 2, 3],
                    "max_tokens": 2,
                    "temperature": 0.0,
                    "ignore_eos": True,
                },
                headers={"X-VDT-Trace-Id": f"{trace_id}-{span_id}"},
            )
            assert r.status == 200
            assert r.headers["X-VDT-Trace-Id"] == trace_id
        finally:
            await client.close()

    try:
        _run(go())
        trace = tracer.get_trace(trace_id)
        assert trace is not None
        api_spans = [
            s for s in trace["spans"] if s["name"] == "api.request"
        ]
        assert api_spans and api_spans[0]["parent_id"] == span_id
    finally:
        engine.shutdown()
        tracer.reset()
        tracer.configure(enabled=False)


# ---------------------------------------------------------------------
# mocked 2-replica e2e: the acceptance criteria
# ---------------------------------------------------------------------
async def _boot_replicas(model_dir, n=2, **engine_kw):
    """N mock-uniproc replicas on real loopback ports (hard-kill-able
    via runner.cleanup with a tiny shutdown timeout)."""
    engines, runners, urls = [], [], []
    for i in range(n):
        engine = _mk_engine(model_dir, **engine_kw)
        state = init_app_state(
            engine, served_model_name="e2e", replica_id=f"replica-{i}"
        )
        port = get_open_port()
        runner = await serve_http(
            build_app(state),
            host="127.0.0.1",
            port=port,
            shutdown_timeout=0.05,
        )
        engines.append(engine)
        runners.append(runner)
        urls.append(f"http://127.0.0.1:{port}")
    return engines, runners, urls


async def _teardown(client, runners, engines):
    if client is not None:
        await client.close()
    for runner in runners:
        if runner is not None:
            try:
                await runner.cleanup()
            except Exception:  # noqa: BLE001 — already torn down
                pass
    for engine in engines:
        try:
            engine.shutdown()
        except Exception:  # noqa: BLE001 — already torn down
            pass


async def _stream_tokens(client, body, on_chunk=None):
    """Stream a completion through the router (debug passthrough on);
    returns (token_ids, finish_reason, serving_replica_id, error)."""
    toks: list[int] = []
    finish = None
    error = None
    r = await client.post(
        "/v1/completions", json=body, headers={"X-VDT-Router": "1"}
    )
    assert r.status == 200, await r.text()
    served = r.headers.get("X-VDT-Replica-Id")
    async for raw in r.content:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        payload = line[5:].strip()
        if payload == "[DONE]":
            break
        obj = json.loads(payload)
        if "error" in obj and not obj.get("choices"):
            error = obj
            break
        for ch in obj.get("choices") or ():
            toks += ch.get("vdt_token_ids") or []
            if ch.get("finish_reason"):
                finish = ch["finish_reason"]
        if on_chunk is not None:
            await on_chunk(toks)
    return toks, finish, served, error


def _migration_case(model_dir, monkeypatch, mode: str):
    """Shared body of the two acceptance tests: start a stream through
    the router, kill/drain the serving replica after 3 tokens, assert
    the stream finishes with the exact uninterrupted greedy sequence."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.05")
    max_tokens = 12
    expected = list(range(3, 3 + max_tokens))
    body = {
        "prompt": [1, 2, 3],
        "max_tokens": max_tokens,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }

    async def go():
        import aiohttp

        engines, runners, urls = await _boot_replicas(model_dir)
        state = RouterState(
            urls,
            policy="round_robin",
            health_interval=0.3,
            connect_timeout=2.0,
            read_timeout=20.0,
        )
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()
        fired = {"done": False}

        async def chaos(toks):
            if fired["done"] or len(toks) < 3:
                return
            fired["done"] = True
            victim = int(served["id"].rsplit("-", 1)[1])
            if mode == "kill":
                runner, runners[victim] = runners[victim], None
                await runner.cleanup()
                engines[victim].shutdown()
            else:
                async with aiohttp.ClientSession() as s:
                    async with s.post(
                        f"{urls[victim]}/drain", params={"timeout": "0"}
                    ) as dr:
                        assert dr.status == 200
                        await dr.read()

        served: dict = {}
        try:
            # Wrap to capture the serving replica id before chaos.
            r = await client.post(
                "/v1/completions", json=body,
                headers={"X-VDT-Router": "1"},
            )
            assert r.status == 200
            served["id"] = r.headers["X-VDT-Replica-Id"]
            toks: list[int] = []
            finish = None
            async for raw in r.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                obj = json.loads(payload)
                assert "error" not in obj or obj.get("choices"), obj
                for ch in obj.get("choices") or ():
                    toks += ch.get("vdt_token_ids") or []
                    if ch.get("finish_reason"):
                        finish = ch["finish_reason"]
                await chaos(toks)
            # Bit-identical across the switch: no token dropped,
            # duplicated, or recomputed from the wrong boundary.
            assert toks == expected, (toks, expected)
            assert finish == "length"
            assert fired["done"], "chaos never fired"
            router_state = await (
                await client.get("/router/state")
            ).json()
            migrated = {
                k: v
                for k, v in router_state["counters"].items()
                if k.startswith("migrations.")
            }
            assert sum(migrated.values()) >= 1, router_state
            assert (
                router_state["counters"].get(
                    "requests.completions.migrated_completed"
                )
                == 1
            )
        finally:
            await _teardown(client, runners, engines)

    _run(go())


def test_kill_mid_stream_migrates_bit_identical(model_dir, monkeypatch):
    _migration_case(model_dir, monkeypatch, "kill")


def test_drain_mid_stream_migrates_bit_identical(model_dir, monkeypatch):
    _migration_case(model_dir, monkeypatch, "drain")


def test_migration_waits_out_backed_off_survivor(model_dir, monkeypatch):
    """A replica in 429 Retry-After backoff is busy, not failed: when
    the serving replica dies and the only survivor is backed off, the
    migration loop waits one backoff beat and still completes the
    stream there (regression for conflating busy-once with
    failed-for-this-request)."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.05")
    max_tokens = 10
    expected = list(range(3, 3 + max_tokens))
    body = {
        "prompt": [1, 2, 3],
        "max_tokens": max_tokens,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }

    async def go():
        engines, runners, urls = await _boot_replicas(model_dir)
        state = RouterState(
            urls,
            policy="least_loaded",
            health_interval=0.3,
            connect_timeout=2.0,
            read_timeout=20.0,
        )
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()
        fired = {"done": False}

        async def chaos(toks):
            if fired["done"] or len(toks) < 3:
                return
            fired["done"] = True
            victim = int(served["id"].rsplit("-", 1)[1])
            survivor = state.pool.replicas[1 - victim]
            # Emulate a just-received 429 from the survivor: it is in
            # Retry-After backoff when the migration needs it.
            state.pool.note_backoff(survivor, 0.8)
            runner, runners[victim] = runners[victim], None
            await runner.cleanup()
            engines[victim].shutdown()

        served: dict = {}
        try:
            r = await client.post(
                "/v1/completions", json=body,
                headers={"X-VDT-Router": "1"},
            )
            assert r.status == 200
            served["id"] = r.headers["X-VDT-Replica-Id"]
            toks: list[int] = []
            async for raw in r.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                obj = json.loads(payload)
                assert "error" not in obj or obj.get("choices"), obj
                for ch in obj.get("choices") or ():
                    toks += ch.get("vdt_token_ids") or []
                await chaos(toks)
            assert fired["done"]
            assert toks == expected, (toks, expected)
        finally:
            await _teardown(client, runners, engines)

    _run(go())


def test_router_health_and_metrics_aggregation(model_dir, monkeypatch):
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")

    async def go():
        engines, runners, urls = await _boot_replicas(model_dir)
        state = RouterState(
            urls,
            policy="least_loaded",
            health_interval=0.2,
            connect_timeout=2.0,
            read_timeout=10.0,
        )
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.get("/health")
            body = await r.json()
            assert r.status == 200
            assert body["status"] == "ok"
            assert body["replicas_routable"] == 2
            ids = {rep["replica_id"] for rep in body["replicas"]}
            assert ids == {"replica-0", "replica-1"}

            # One request so per-replica engine metrics exist.
            resp = await client.post(
                "/v1/completions",
                json={
                    "prompt": [1, 2, 3],
                    "max_tokens": 2,
                    "temperature": 0.0,
                    "ignore_eos": True,
                },
            )
            assert resp.status == 200
            assert resp.headers["X-VDT-Replica-Id"] in ids

            metrics_text = await (await client.get("/metrics")).text()
            # Replica families present once, samples labeled per
            # replica, and the router's own families alongside.
            assert metrics_text.count(
                "# TYPE vllm:num_requests_running gauge"
            ) == 1
            assert 'replica="replica-0"' in metrics_text
            assert 'replica="replica-1"' in metrics_text
            assert "vdt_router:placements" in metrics_text

            # /v1/models proxies from a live replica.
            models = await (await client.get("/v1/models")).json()
            assert models["data"][0]["id"] == "e2e"

            # Kill one replica: /health degrades but stays 200.
            runner, runners[0] = runners[0], None
            await runner.cleanup()
            engines[0].shutdown()
            for _ in range(40):
                body = await (await client.get("/health")).json()
                if body["replicas_routable"] == 1:
                    break
                await asyncio.sleep(0.1)
            assert body["replicas_routable"] == 1
            assert body["status"] == "degraded"
        finally:
            await _teardown(client, runners, engines)

    _run(go())


def test_affinity_routing_sticks_and_beats_round_robin(
    model_dir, monkeypatch
):
    """Affinity A/B (acceptance): on a shared-prefix workload with
    prefix caching enabled on the replicas, affinity routing yields a
    strictly higher vllm:prefix_cache_hits total than round_robin —
    and repeat prompts stick to the warm replica even when it looks
    more loaded."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    shared = [(7 * j) % 900 + 1 for j in range(32)]

    async def run_policy(policy: str) -> float:
        engines, runners, urls = await _boot_replicas(
            model_dir, enable_prefix_caching=True
        )
        state = RouterState(
            urls,
            policy=policy,
            health_interval=0.2,
            affinity_block_tokens=16,
            affinity_min_tokens=16,
            connect_timeout=2.0,
            read_timeout=10.0,
        )
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            served_by = []
            for i in range(6):
                body = {
                    "prompt": shared + [900 + i] * 4,
                    "max_tokens": 2,
                    "temperature": 0.0,
                    "ignore_eos": True,
                }
                r = await client.post("/v1/completions", json=body)
                assert r.status == 200, await r.text()
                await r.read()
                served_by.append(r.headers["X-VDT-Replica-Id"])
                if policy == "affinity" and i == 0:
                    # Make the warm replica LOOK more loaded: affinity
                    # must still prefer it over the idle cold one.
                    state.pool.by_id(served_by[0]).waiting = 5.0
            metrics_text = await (await client.get("/metrics")).text()
            hits = 0.0
            for line in metrics_text.splitlines():
                if line.startswith("vllm:prefix_cache_hits_total{"):
                    hits += float(line.rsplit(" ", 1)[1])
            if policy == "affinity":
                # Sticky: every request after the first followed the
                # warm cache.
                assert len(set(served_by)) == 1, served_by
            else:
                assert len(set(served_by)) == 2, served_by
            return hits
        finally:
            await _teardown(client, runners, engines)

    async def go():
        hits_affinity = await run_policy("affinity")
        hits_rr = await run_policy("round_robin")
        assert hits_affinity > hits_rr, (hits_affinity, hits_rr)

    _run(go())


def test_chat_kill_mid_stream_real_model_bit_identical(
    tmp_path_factory, monkeypatch
):
    """Migration on the REAL text path: two tiny-llama replicas (same
    weights, real tokenizer), a streaming CHAT request killed
    mid-stream — the migrated stream's concatenated text must equal an
    unmigrated run's exactly (the router's cumulative-text dedupe and
    the replica's detokenizer pre-feed must agree on the boundary)."""
    import time as _time

    from tests.utils import add_tiny_tokenizer, make_tiny_llama

    # vocab_size matches the 30-word tokenizer so every greedy token
    # decodes to a real word — the text-dedupe path must carry actual
    # characters across the migration boundary.
    model = make_tiny_llama(
        str(tmp_path_factory.mktemp("router-real") / "m"), vocab_size=30
    )
    add_tiny_tokenizer(model)
    body = {
        "messages": [
            {"role": "system", "content": "the cat"},
            {"role": "user", "content": "hello world the cat sat"},
        ],
        "max_tokens": 16,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }

    async def go():
        engines, runners, urls = [], [], []
        for i in range(2):
            engine = AsyncLLM.from_engine_args(
                EngineArgs(
                    model=model,
                    num_kv_pages=128,
                    max_model_len=256,
                    max_num_seqs=8,
                    num_decode_steps=1,
                )
            )
            state = init_app_state(
                engine,
                served_model_name="tiny",
                replica_id=f"replica-{i}",
            )
            port = get_open_port()
            runner = await serve_http(
                build_app(state),
                host="127.0.0.1",
                port=port,
                shutdown_timeout=0.05,
            )
            engines.append(engine)
            runners.append(runner)
            urls.append(f"http://127.0.0.1:{port}")
        # Slow both engines so the kill reliably lands mid-stream.
        for engine in engines:
            real_step = engine.engine.step

            def slow_step(_real=real_step):
                _time.sleep(0.05)
                return _real()

            engine.engine.step = slow_step
        state = RouterState(
            urls,
            policy="round_robin",
            health_interval=0.3,
            connect_timeout=2.0,
            read_timeout=20.0,
        )
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()

        async def stream_chat(chaos=None):
            r = await client.post("/v1/chat/completions", json=body)
            assert r.status == 200, await r.text()
            served = r.headers["X-VDT-Replica-Id"]
            text = ""
            finish = None
            async for raw in r.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                obj = json.loads(payload)
                assert "error" not in obj or obj.get("choices"), obj
                for ch in obj.get("choices") or ():
                    text += (ch.get("delta") or {}).get("content") or ""
                    if ch.get("finish_reason"):
                        finish = ch["finish_reason"]
                if chaos is not None:
                    await chaos(served, text)
            return text, finish

        try:
            baseline_text, baseline_finish = await stream_chat()
            assert baseline_finish == "length" and baseline_text
            fired = {"done": False}

            async def chaos(served, text):
                # Kill after a few characters of content arrived.
                if fired["done"] or len(text) < 2:
                    return
                fired["done"] = True
                victim = int(served.rsplit("-", 1)[1])
                runner, runners[victim] = runners[victim], None
                await runner.cleanup()
                engines[victim].shutdown()

            migrated_text, migrated_finish = await stream_chat(chaos)
            assert fired["done"], "kill never fired"
            assert migrated_text == baseline_text
            assert migrated_finish == "length"
            counters = (
                await (await client.get("/router/state")).json()
            )["counters"]
            assert (
                counters.get("requests.chat.migrated_completed") == 1
            ), counters
        finally:
            await _teardown(client, runners, engines)

    _run(go())


def test_router_soak_smoke(model_dir):
    """2-cycle --replicas smoke of tools/chaos_soak.py (one kill cycle,
    one drain cycle, background load): zero lost admitted work, zero
    token mismatches, bounded client stall."""
    from tools.chaos_soak import run_router_soak

    report = run_router_soak(
        replicas=2, cycles=2, load_concurrency=2
    )
    assert report["bounded"], report
    assert report["lost"] == 0 and report["mismatches"] == 0
    assert report["migrations"] >= 1, report
