"""Weight-only quantization (--quantization int8/int4): roundtrip
accuracy, engine integration, memory halving, and sharded bit-equality.

The reference serves AWQ 4-bit checkpoints via vLLM's CUDA kernels
(/root/reference/.env.server:11); the TPU-native design quantizes on
load and dequantizes in-graph (ops/quant.py).
"""

import numpy as np
import pytest

from tests.utils import make_tiny_llama, make_tiny_mixtral
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    pick_group_size,
    quantize,
)
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [[1, 5, 9, 23, 77, 41, 3], [7, 2, 88, 14]]


@pytest.fixture(scope="module")
def tiny_llama(tmp_path_factory):
    return make_tiny_llama(
        str(tmp_path_factory.mktemp("llama_q")), heads=8, kv_heads=4
    )


@pytest.fixture(scope="module")
def tiny_mixtral(tmp_path_factory):
    return make_tiny_mixtral(
        str(tmp_path_factory.mktemp("mixtral_q")), heads=8, kv_heads=4
    )


# ---- kernel-level roundtrips ----
def test_int8_roundtrip():
    rng = np.random.default_rng(0)
    w = (rng.standard_normal((64, 48)) * 0.1).astype(np.float32)
    qt = quantize(w, 8)
    got = np.asarray(dequantize(qt, np.float32))
    assert np.abs(got - w).max() / np.abs(w).max() < 0.01
    assert qt.nbytes < 0.3 * w.nbytes


def test_int4_roundtrip_grouped():
    rng = np.random.default_rng(1)
    w = (rng.standard_normal((128, 32)) * 0.1).astype(np.float32)
    qt = quantize(w, 4, group=32)
    got = np.asarray(dequantize(qt, np.float32))
    assert np.abs(got - w).max() / np.abs(w).max() < 0.12
    assert qt.q.shape == (64, 32)  # two nibbles per byte
    assert qt.scale.shape == (4, 32)
    assert qt.nbytes < 0.2 * w.nbytes


def test_int4_stacked_experts_roundtrip():
    rng = np.random.default_rng(2)
    w = (rng.standard_normal((4, 64, 32)) * 0.1).astype(np.float32)
    qt = quantize(w, 4)
    got = np.asarray(dequantize(qt, np.float32))
    assert np.abs(got - w).max() / np.abs(w).max() < 0.12


def test_group_size_respects_shards():
    assert pick_group_size(11008, 8) <= 11008 // 8
    assert (11008 // 8) % pick_group_size(11008, 8) == 0
    assert pick_group_size(4096, 1) == 128


def test_rejects_unknown_method(tiny_llama):
    with pytest.raises(ValueError, match="unsupported quantization"):
        EngineArgs(model=tiny_llama, quantization="fp8").create_engine_config()


# ---- engine integration ----
def _greedy(model_dir, quantization=None, tp=1, ep=False, max_tokens=6):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=256,
            quantization=quantization,
            tensor_parallel_size=tp,
            enable_expert_parallel=ep,
        )
    )
    done = {}
    for i, p in enumerate(PROMPTS):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
    return engine, [done[f"r{i}"] for i in range(len(PROMPTS))]


def _param_bytes(engine):
    import jax

    return sum(
        x.nbytes
        for x in jax.tree.leaves(engine.executor.worker.runner.params)
    )


def test_int8_engine_memory_and_logits(tiny_llama):
    eng_fp, _ = _greedy(tiny_llama)
    eng_q, _ = _greedy(tiny_llama, quantization="int8")
    # Attn+MLP weights dominate this tiny model less than a real one
    # (embed/lm_head stay fp32), so just require a real reduction.
    assert _param_bytes(eng_q) < 0.75 * _param_bytes(eng_fp)
    # Logit agreement on a prefill: loose tolerance, quantization noise.
    import jax.numpy as jnp

    from vllm_distributed_tpu.ops.attention import AttentionMetadata

    def prefill_logits(eng):
        runner = eng.executor.worker.runner
        prompt = PROMPTS[0]
        t = len(prompt)
        meta = AttentionMetadata(
            q_seq_ids=jnp.zeros(t, jnp.int32),
            q_positions=jnp.arange(t, dtype=jnp.int32),
            slot_mapping=16 + jnp.arange(t, dtype=jnp.int32),
            block_tables=jnp.ones((1, 4), jnp.int32),
            seq_lens=jnp.full(1, t, jnp.int32),
            logits_indices=jnp.full(1, t - 1, jnp.int32),
            chunk_starts=jnp.zeros(1, jnp.int32),
        )
        logits, _ = runner.model.forward(
            runner.params,
            jnp.asarray(prompt, jnp.int32),
            runner.kv_caches,
            meta,
        )
        return np.asarray(logits)[0]

    lf, lq = prefill_logits(eng_fp), prefill_logits(eng_q)
    scale = np.abs(lf).max()
    assert np.abs(lf - lq).max() / scale < 0.05


def test_int8_tp4_matches_tp1(tiny_llama):
    _, base = _greedy(tiny_llama, quantization="int8")
    _, tp4 = _greedy(tiny_llama, quantization="int8", tp=4)
    assert tp4 == base


def test_int4_engine_runs(tiny_llama):
    eng_q, toks = _greedy(tiny_llama, quantization="int4")
    assert all(len(t) == 6 for t in toks)
    eng_fp, _ = _greedy(tiny_llama)
    assert _param_bytes(eng_q) < 0.7 * _param_bytes(eng_fp)


def test_int4_tp4_deterministic(tiny_llama):
    """int4 grouping follows the tp layout (shard-aligned groups), so
    tp=4 is compared against itself (determinism), not bit-against tp=1
    — int8 is the layout-independent scheme (see test above)."""
    _, a = _greedy(tiny_llama, quantization="int4", tp=4)
    _, b = _greedy(tiny_llama, quantization="int4", tp=4)
    assert a == b


def test_int8_mixtral_ep(tiny_mixtral):
    """Quantized experts through the HF load path (per-expert tensors
    quantized in-stream, stacked by finalize_params) under EP."""
    _, base = _greedy(tiny_mixtral, quantization="int8")
    _, ep4 = _greedy(tiny_mixtral, quantization="int8", tp=4, ep=True)
    assert ep4 == base
    # Quantized params flow as pytrees with int8 leaves.
    eng, _ = _greedy(tiny_mixtral, quantization="int8")
    layer = eng.executor.worker.runner.params["layers"][0]
    assert isinstance(layer["w1"], QuantizedTensor)
    assert layer["w1"].q.dtype == np.int8


def test_int8_matmul_kernel_interpret():
    """Pallas weight-streaming matmul vs dequant-in-graph (interpret
    mode; the bench re-checks on the live chip)."""
    import jax.numpy as jnp

    from vllm_distributed_tpu.ops.pallas.quant_matmul import int8_matmul

    rng = np.random.default_rng(3)
    for (t, i, o, blk) in [(32, 2048, 512, 512), (16, 256, 1024, 512),
                           (8, 128, 640, 128)]:
        x = jnp.asarray(rng.standard_normal((t, i)) * 0.5, jnp.float32)
        qt = quantize((rng.standard_normal((i, o)) * 0.1).astype(np.float32), 8)
        want = np.asarray(x @ dequantize(qt, jnp.float32))
        got = np.asarray(int8_matmul(
            x, jnp.asarray(qt.q), jnp.asarray(qt.scale),
            block_out=min(blk, o), interpret=True))
        assert np.abs(got - want).max() / np.abs(want).max() < 1e-5


def test_int8_engine_pallas_interpret_path(tiny_llama):
    """The quant-matmul 'pallas' mode end to end via VDT_USE_PALLAS
    (interpret kernels on CPU), vs the dequant path: same tokens."""
    import os
    from unittest import mock

    _, base = _greedy(tiny_llama, quantization="int8")
    with mock.patch.dict(os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}):
        eng, via_kernel = _greedy(tiny_llama, quantization="int8")
    # The loader stamps the backend on each tensor at load time; on the
    # single-chip kernel path Q|K|V and gate|up fuse into one streaming
    # call each (bit-identical: per-out-block computation independent).
    layer = eng.executor.worker.runner.params["layers"][0]
    assert layer["wqkv"].matmul == "pallas_interpret"
    assert "wgu" in layer and "wq" not in layer and "gate" not in layer
    assert via_kernel == base


def test_int4_matmul_kernel_interpret():
    """Weight-streaming int4 kernel (permuted-contraction nibble
    unpack) vs dequantize-in-graph, multiple group sizes."""
    import jax.numpy as jnp
    import numpy as np

    from vllm_distributed_tpu.ops.pallas.quant_matmul import int4_matmul
    from vllm_distributed_tpu.ops.quant import dequantize, quantize

    rng = np.random.default_rng(0)
    for in_dim, out_dim, group, blk in (
        (256, 512, 128, 256),
        (256, 256, 64, 128),
        (128, 128, 2, 128),
    ):
        x = jnp.asarray(
            rng.standard_normal((8, in_dim)) * 0.3, jnp.float32
        )
        w = rng.standard_normal((in_dim, out_dim)).astype(np.float32) * 0.1
        qt = quantize(w, 4, group=group)
        want = np.asarray(x @ dequantize(qt, jnp.float32))
        got = np.asarray(
            int4_matmul(
                x, jnp.asarray(qt.q), jnp.asarray(qt.scale),
                group=group, block_out=blk, interpret=True,
            )
        )
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_int4_engine_pallas_interpret_path(tiny_llama):
    """Engine e2e on the int4 streaming path must match the int4
    dequant-in-graph path token-for-token (identical quantized values,
    different execution backend)."""
    import os
    from unittest import mock

    _, base = _greedy(tiny_llama, quantization="int4")
    with mock.patch.dict(
        os.environ, {"VDT_USE_PALLAS": "pallas_interpret"}
    ):
        eng, via_kernel = _greedy(tiny_llama, quantization="int4")
    layer = eng.executor.worker.runner.params["layers"][0]
    # int4 projections fuse like int8 on the kernel path (same concat
    # along the out dim preserves packing and group layout).
    assert layer["wqkv"].matmul == "pallas_interpret"
    assert layer["wqkv"].bits == 4
    assert via_kernel == base
