"""Resilient DCN data-plane suite (ISSUE 19).

Pure state-machine units on synthetic clocks — breaker transitions,
retry-budget amplification bounds, adaptive-deadline clamps, hedge
outcomes with an injected sleep (no real timers) — plus the default-off
A/B pins: with no resilience env set the manager is disabled and
``request()`` is a pure passthrough that preserves the caller's timeout
object, hedging runs its factory exactly once, and the KV-transfer
begin frame carries no ``resume_from``.  The resumable-transfer
protocol and the inbound ``/internal/kv`` frame-size bound are pinned
over the real replica HTTP surface with mock-uniproc engines.
"""

from __future__ import annotations

import asyncio

import aiohttp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockUniProcExecutor
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.router.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerOpen,
    CircuitBreaker,
    LatencyTracker,
    ResilienceConfig,
    ResilienceManager,
)
from vllm_distributed_tpu.testing import write_llama_config

pytestmark = pytest.mark.resilience

PAGE = 16

# Every resilience knob, for the clean-env A/B fixtures.
RESILIENCE_ENVS = [
    "VDT_ROUTER_BREAKER_FAILURES",
    "VDT_ROUTER_BREAKER_COOLDOWN_SECONDS",
    "VDT_ROUTER_BREAKER_TIMEOUT_RATE",
    "VDT_ROUTER_BREAKER_WINDOW_SECONDS",
    "VDT_ROUTER_RETRY_BUDGET_RATIO",
    "VDT_ROUTER_RETRY_BUDGET_MIN",
    "VDT_ROUTER_ADAPTIVE_DEADLINE",
    "VDT_ROUTER_DEADLINE_FLOOR_SECONDS",
    "VDT_ROUTER_DEADLINE_CEILING_SECONDS",
    "VDT_ROUTER_DEADLINE_MULTIPLIER",
    "VDT_ROUTER_HEDGE",
    "VDT_ROUTER_HEDGE_MIN_DELAY_MS",
    "VDT_ROUTER_KV_CHUNK_RETRIES",
]


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class FakeClock:
    def __init__(self) -> None:
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


async def _noop_sleep(_delay: float) -> None:
    return None


def _mgr(clock=None, **cfg_kw) -> ResilienceManager:
    return ResilienceManager(
        ResilienceConfig(**cfg_kw),
        clock=clock or FakeClock(),
        sleep=_noop_sleep,
    )


# ---------------------------------------------------------------------
# circuit breaker state machine (synthetic clock)
# ---------------------------------------------------------------------
def test_breaker_trips_cools_probes_and_closes():
    clk = FakeClock()
    br = CircuitBreaker(
        failures=3, cooldown=5.0, timeout_rate=0.0, window=30.0, clock=clk
    )
    assert br.state == CLOSED and br.acquire()
    br.record_failure(timeout=False)
    br.record_failure(timeout=False)
    assert br.state == CLOSED  # two of three
    br.record_failure(timeout=True)
    assert br.state == OPEN
    # Rejections during cooldown never extend it.
    assert not br.acquire()
    clk.advance(4.9)
    assert not br.acquire() and not br.can_route()
    clk.advance(0.2)  # past the cooldown armed at the trip
    assert br.can_route()
    assert br.acquire()  # THE half-open probe
    assert br.state == HALF_OPEN
    assert not br.acquire()  # single probe: second caller rejected
    br.record_success()
    assert br.state == CLOSED and br.acquire()


def test_breaker_probe_failure_reopens_with_fresh_cooldown():
    clk = FakeClock()
    br = CircuitBreaker(
        failures=1, cooldown=5.0, timeout_rate=0.0, window=30.0, clock=clk
    )
    br.record_failure(timeout=False)
    assert br.state == OPEN
    clk.advance(5.0)
    assert br.acquire() and br.state == HALF_OPEN
    br.record_failure(timeout=True)
    assert br.state == OPEN
    clk.advance(4.0)
    assert not br.acquire()  # the re-trip re-armed the full cooldown
    clk.advance(1.1)
    assert br.acquire()
    br.record_success()
    assert br.state == CLOSED


def test_breaker_success_resets_consecutive_count():
    clk = FakeClock()
    br = CircuitBreaker(
        failures=3, cooldown=5.0, timeout_rate=0.0, window=30.0, clock=clk
    )
    for _ in range(4):
        br.record_failure(timeout=False)
        br.record_failure(timeout=False)
        br.record_success()
    assert br.state == CLOSED


def test_breaker_timeout_rate_trip_needs_min_samples_and_window():
    clk = FakeClock()
    br = CircuitBreaker(
        failures=0, cooldown=5.0, timeout_rate=0.5, window=30.0, clock=clk
    )
    # Nine timeouts: below the 10-sample floor, no trip.
    for _ in range(9):
        br.record_failure(timeout=True)
    assert br.state == CLOSED
    br.record_failure(timeout=True)  # tenth sample, rate 1.0 >= 0.5
    assert br.state == OPEN

    # Events older than the window are pruned before the rate check.
    clk2 = FakeClock()
    br2 = CircuitBreaker(
        failures=0, cooldown=5.0, timeout_rate=0.5, window=30.0, clock=clk2
    )
    for _ in range(9):
        br2.record_failure(timeout=True)
    clk2.advance(31.0)
    for _ in range(9):
        br2.record_failure(timeout=False)
    assert br2.state == CLOSED  # stale timeouts gone; rate now 0/9
    br2.record_failure(timeout=False)
    assert br2.state == CLOSED


def test_breaker_placement_filter_and_forget():
    clk = FakeClock()
    mgr = _mgr(clock=clk, breaker_failures=1, breaker_cooldown=5.0)
    assert mgr.replica_available("r1")  # no breaker yet
    br = mgr._breaker("r1")
    br.record_failure(timeout=False)
    assert not mgr.replica_available("r1")
    clk.advance(5.0)
    assert mgr.replica_available("r1")  # cooldown elapsed: probeable
    mgr.forget_replica("r1")
    assert mgr.breakers == {} and mgr.replica_available("r1")


# ---------------------------------------------------------------------
# retry budget: granted <= min + ratio * attempts over any horizon
# ---------------------------------------------------------------------
def test_budget_off_always_grants():
    mgr = _mgr()  # ratio 0 = off
    assert all(mgr.try_spend_retry() for _ in range(1000))
    assert mgr.retries_denied == 0


def test_budget_amplification_bound_holds():
    mgr = _mgr(retry_ratio=0.2, retry_min=5.0)
    # No attempts yet: only the fixed reserve is spendable.
    granted = sum(1 for _ in range(50) if mgr.try_spend_retry())
    assert granted == 5
    assert mgr.retries_denied == 45
    # Every 10 first-attempts buy ratio*10 = 2 more retries.
    for _ in range(10):
        mgr.first_attempts += 1
    assert mgr.try_spend_retry() and mgr.try_spend_retry()
    assert not mgr.try_spend_retry()
    assert (
        mgr.retries_granted
        <= mgr.cfg.retry_min + mgr.cfg.retry_ratio * mgr.first_attempts
    )


def test_budget_per_replica_bound_is_tighter():
    mgr = _mgr(retry_ratio=0.5, retry_min=8.0)
    mgr.first_attempts = 1000  # global allowance is huge
    # Per-replica: max(1, 8/4)=2 reserve + 0.5 * replica attempts(0).
    assert mgr.try_spend_retry("r1")
    assert mgr.try_spend_retry("r1")
    assert not mgr.try_spend_retry("r1")
    # Another replica has its own reserve; replica-less spends only
    # check the global bound.
    assert mgr.try_spend_retry("r2")
    assert mgr.try_spend_retry(None)


# ---------------------------------------------------------------------
# adaptive deadlines
# ---------------------------------------------------------------------
def test_latency_tracker_needs_min_samples():
    tr = LatencyTracker()
    for _ in range(7):
        tr.observe(0.1)
    assert tr.p95() is None
    tr.observe(0.1)
    assert tr.p95() is not None and tr.p95() >= 0.1


def test_deadline_clamps_floor_ceiling_and_gates():
    mgr = _mgr(
        adaptive_deadline=True,
        deadline_floor=1.0,
        deadline_ceiling=4.0,
        deadline_multiplier=3.0,
    )
    assert mgr.deadline("cold") is None  # no samples yet
    for _ in range(8):
        mgr.observe_latency("fast", 0.01)
    assert mgr.deadline("fast") == 1.0  # 3*p95 << floor
    for _ in range(8):
        mgr.observe_latency("slow", 10.0)
    assert mgr.deadline("slow") == 4.0  # clamped to ceiling
    # Ceiling 0 falls back to the router read timeout.
    mgr2 = _mgr(
        adaptive_deadline=True,
        deadline_floor=1.0,
        deadline_ceiling=0.0,
        read_timeout=7.0,
    )
    for _ in range(8):
        mgr2.observe_latency("slow", 10.0)
    assert mgr2.deadline("slow") == 7.0
    # Off = None regardless of samples.
    mgr3 = _mgr()
    for _ in range(8):
        mgr3.observe_latency("ep", 10.0)
    assert mgr3.deadline("ep") is None


# ---------------------------------------------------------------------
# request(): passthrough identity and breaker/deadline integration
# ---------------------------------------------------------------------
class FakeSession:
    """Records request() kwargs; returns or raises per script."""

    def __init__(self, results=None) -> None:
        self.calls: list[dict] = []
        self.results = list(results or [])

    async def request(self, method, url, *, timeout=None, **kw):
        self.calls.append(
            {"method": method, "url": url, "timeout": timeout, **kw}
        )
        if self.results:
            r = self.results.pop(0)
            if isinstance(r, Exception):
                raise r
            return r
        return "resp"


def test_from_env_clean_environment_is_disabled(monkeypatch):
    for k in RESILIENCE_ENVS:
        monkeypatch.delenv(k, raising=False)
    mgr = ResilienceManager.from_env()
    assert not mgr.enabled
    assert not mgr.cfg.breaker_on and not mgr.cfg.budget_on


def test_disabled_request_is_pure_passthrough():
    mgr = _mgr()  # all defaults: disabled
    assert not mgr.enabled
    sess = FakeSession()
    timeout = aiohttp.ClientTimeout(total=12.5, connect=3.0)
    out = _run(
        mgr.request(
            sess, "GET", "http://r/health", endpoint="health",
            replica_id="r1", timeout=timeout,
        )
    )
    assert out == "resp"
    # The caller's timeout OBJECT reaches the wire unchanged, and no
    # resilience state moves — byte-identical to the pre-ISSUE router.
    assert sess.calls[0]["timeout"] is timeout
    assert mgr.first_attempts == 0
    assert mgr.breakers == {} and mgr.latency == {}


def test_enabled_request_keeps_fixed_timeout_until_adaptive_on():
    mgr = _mgr(breaker_failures=3)  # enabled, adaptive off
    sess = FakeSession()
    timeout = aiohttp.ClientTimeout(total=9.0)
    _run(
        mgr.request(
            sess, "GET", "http://r/health", endpoint="health",
            replica_id="r1", timeout=timeout,
        )
    )
    assert sess.calls[0]["timeout"] is timeout
    assert mgr.first_attempts == 1


def test_adaptive_request_replaces_unary_total_only():
    mgr = _mgr(
        adaptive_deadline=True, deadline_floor=2.0, deadline_ceiling=8.0
    )
    for _ in range(8):
        mgr.observe_latency("health", 0.05)
    sess = FakeSession()
    fixed = aiohttp.ClientTimeout(total=60.0, connect=3.0)
    _run(
        mgr.request(
            sess, "GET", "http://r/health", endpoint="health",
            timeout=fixed,
        )
    )
    sent = sess.calls[0]["timeout"]
    assert sent is not fixed
    assert sent.total == 2.0  # clamped to floor
    assert sent.connect == 3.0  # connect survives the rebuild

    # Streaming (total=None) and adaptive=False opt-outs stay fixed.
    streaming = aiohttp.ClientTimeout(total=None, sock_read=600)
    _run(
        mgr.request(
            sess, "POST", "http://r/v1/completions", endpoint="proxy",
            timeout=streaming,
        )
    )
    assert sess.calls[1]["timeout"] is streaming
    drain = aiohttp.ClientTimeout(total=40.0)
    _run(
        mgr.request(
            sess, "POST", "http://r/drain", endpoint="health",
            adaptive=False, timeout=drain,
        )
    )
    assert sess.calls[2]["timeout"] is drain


def test_request_failures_trip_breaker_and_reject_before_io():
    clk = FakeClock()
    mgr = _mgr(clock=clk, breaker_failures=2, breaker_cooldown=5.0)

    async def go():
        sess = FakeSession(
            results=[ConnectionError("boom"), ConnectionError("boom")]
        )
        for _ in range(2):
            with pytest.raises(ConnectionError):
                await mgr.request(
                    sess, "GET", "http://r/health", endpoint="health",
                    replica_id="r1",
                )
        assert mgr.breakers["r1"].state == OPEN
        with pytest.raises(BreakerOpen):
            await mgr.request(
                sess, "GET", "http://r/health", endpoint="health",
                replica_id="r1",
            )
        assert len(sess.calls) == 2  # the rejection never hit the wire
        assert mgr.transitions["r1:open"] == 1
        # Cooldown elapses: the probe goes through and closes.
        clk.advance(5.0)
        ok = FakeSession()
        assert (
            await mgr.request(
                ok, "GET", "http://r/health", endpoint="health",
                replica_id="r1",
            )
            == "resp"
        )
        assert mgr.breakers["r1"].state == CLOSED
        assert mgr.transitions["r1:half_open"] == 1
        assert mgr.transitions["r1:closed"] == 1

    _run(go())


# ---------------------------------------------------------------------
# hedged requests (injected sleep; no real timers)
# ---------------------------------------------------------------------
def _warm(mgr: ResilienceManager, endpoint: str = "ep") -> None:
    for _ in range(8):
        mgr.observe_latency(endpoint, 0.05)


def test_hedge_off_or_cold_runs_factory_once():
    calls = []

    async def factory():
        calls.append(1)
        return "v"

    mgr = _mgr()  # hedge off
    assert _run(mgr.hedged("ep", None, factory)) == "v"
    assert len(calls) == 1

    mgr2 = _mgr(hedge=True)  # on, but the endpoint is cold
    assert _run(mgr2.hedged("cold", None, factory)) == "v"
    assert len(calls) == 2


def test_hedge_primary_wins_without_spending():
    mgr = _mgr(hedge=True, retry_ratio=0.5, retry_min=4.0)
    _warm(mgr)
    calls = []

    async def fast():
        calls.append(1)
        return "p"

    assert _run(mgr.hedged("ep", None, fast)) == "p"
    assert len(calls) == 1
    assert mgr.retries_granted == 0  # no hedge fired, nothing spent


def test_hedge_fires_after_delay_and_wins():
    mgr = _mgr(hedge=True)  # budget off: hedges always granted
    _warm(mgr)
    calls = []

    async def factory():
        calls.append(len(calls))
        if len(calls) == 1:
            await asyncio.Event().wait()  # primary hangs; cancelled later
        return "h"

    assert _run(mgr.hedged("ep", None, factory)) == "h"
    assert len(calls) == 2


def test_hedge_denied_by_budget_falls_back_to_primary():
    mgr = _mgr(hedge=True, retry_ratio=0.5, retry_min=0.0)
    _warm(mgr)  # allowance = 0 + 0.5 * 0 attempts = 0: always denied
    ev = asyncio.Event()
    calls = []

    async def factory():
        calls.append(1)
        await ev.wait()
        return "p"

    async def go():
        task = asyncio.ensure_future(mgr.hedged("ep", None, factory))
        for _ in range(10):
            await asyncio.sleep(0)  # timer (no-op sleep) fires, denial lands
        ev.set()
        return await task

    assert _run(go()) == "p"
    assert len(calls) == 1
    assert mgr.retries_denied == 1


def test_hedge_survives_failed_primary():
    """A primary that fails AFTER the hedge launched must not discard
    a hedge that is about to succeed — the hedge's success is the
    outcome."""
    mgr = _mgr(hedge=True)
    _warm(mgr)
    primary_fail = asyncio.Event()
    hedge_go = asyncio.Event()
    calls = []

    async def factory():
        calls.append(len(calls))
        if len(calls) == 1:
            await primary_fail.wait()
            raise ConnectionError("primary died")
        await hedge_go.wait()
        return "h"

    async def go():
        task = asyncio.ensure_future(mgr.hedged("ep", None, factory))
        for _ in range(10):
            await asyncio.sleep(0)  # timer fires, hedge launches
        primary_fail.set()
        for _ in range(10):
            await asyncio.sleep(0)  # primary dies with the hedge live
        hedge_go.set()
        return await task

    assert _run(go()) == "h"
    assert len(calls) == 2


def test_hedge_both_failed_raises_primary_error():
    mgr = _mgr(hedge=True)
    _warm(mgr)
    go_ev = asyncio.Event()
    calls = []

    async def factory():
        me = len(calls)
        calls.append(me)
        await go_ev.wait()  # hold both past the hedge launch
        if me == 0:
            raise ValueError("primary error")
        raise ConnectionError("hedge error")

    async def go():
        task = asyncio.ensure_future(mgr.hedged("ep", None, factory))
        for _ in range(10):
            await asyncio.sleep(0)  # timer fires, hedge launches
        go_ev.set()
        return await task

    with pytest.raises(ValueError, match="primary error"):
        _run(go())
    assert len(calls) == 2


# ---------------------------------------------------------------------
# resumable KV transfer protocol + inbound frame bound (replica surface)
# ---------------------------------------------------------------------
def _mk_engine(model_dir: str, **kw) -> AsyncLLM:
    args = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=96,
        max_model_len=1024,
        num_decode_steps=1,
        enable_prefix_caching=True,
        distributed_executor_backend=MockUniProcExecutor,
    )
    args.update(kw)
    return AsyncLLM.from_engine_args(EngineArgs(**args))


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return write_llama_config(
        str(tmp_path_factory.mktemp("resilience") / "m")
    )


def test_kv_begin_resume_protocol(model_dir, monkeypatch):
    """Default begin responses carry no resume fields (wire-identical
    to the pre-ISSUE protocol); a resume_from begin returns the live
    reservation's received-layer set, and a mismatched prompt or
    unknown id is rejected with transfer_id=None."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    prompt = [(i % 300) + 1 for i in range(3 * PAGE)]
    engine = _mk_engine(model_dir)
    state = init_app_state(engine, served_model_name="m", role="decode")

    async def go():
        client = TestClient(TestServer(build_app(state)))
        await client.start_server()
        try:
            r = await client.post(
                "/internal/kv",
                json={"op": "begin", "prompt_token_ids": prompt},
            )
            begin = await r.json()
            assert r.status == 200 and begin["transfer_id"]
            # A/B pin: the NORMAL begin frame has exactly the
            # pre-ISSUE keys — resume adds fields only when asked for.
            assert set(begin) == {"transfer_id", "num_pages"}
            tid = begin["transfer_id"]

            r = await client.post(
                "/internal/kv",
                json={
                    "op": "begin",
                    "prompt_token_ids": prompt,
                    "resume_from": tid,
                },
            )
            resumed = await r.json()
            assert r.status == 200
            assert resumed["transfer_id"] == tid
            assert resumed["received"] == []  # nothing landed yet
            assert resumed["num_pages"] == len(prompt) // PAGE

            # Mismatched prompt prefix: resume refused.
            r = await client.post(
                "/internal/kv",
                json={
                    "op": "begin",
                    "prompt_token_ids": [9] * len(prompt),
                    "resume_from": tid,
                },
            )
            assert (await r.json())["transfer_id"] is None
            # Unknown transfer id: refused, nothing implicitly created.
            r = await client.post(
                "/internal/kv",
                json={
                    "op": "begin",
                    "prompt_token_ids": prompt,
                    "resume_from": "kvimp-nope",
                },
            )
            assert (await r.json())["transfer_id"] is None
            # The real reservation is still live and abortable.
            r = await client.post(
                "/internal/kv", json={"op": "abort", "transfer_id": tid}
            )
            assert r.status == 200
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


def test_kv_frame_size_bound_413(model_dir, monkeypatch):
    """Frames above VDT_KV_MAX_FRAME_BYTES get a typed 413 before
    buffering; frames under the bound (and any frame with the bound
    disabled) proceed."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    prompt = [(i % 300) + 1 for i in range(2 * PAGE)]
    engine = _mk_engine(model_dir)
    state = init_app_state(engine, served_model_name="m", role="decode")

    async def go():
        client = TestClient(TestServer(build_app(state)))
        await client.start_server()
        try:
            monkeypatch.setenv("VDT_KV_MAX_FRAME_BYTES", "256")
            r = await client.post(
                "/internal/kv",
                json={
                    "op": "chunk",
                    "transfer_id": "t",
                    "layers": [{"pad": "x" * 4096}],
                },
            )
            assert r.status == 413
            err = await r.json()
            assert "VDT_KV_MAX_FRAME_BYTES" in err["message"]
            # Small frames still serve under the same bound.
            r = await client.post(
                "/internal/kv",
                json={"op": "begin", "prompt_token_ids": prompt},
            )
            begin = await r.json()
            assert r.status == 200 and begin["transfer_id"]
            await client.post(
                "/internal/kv",
                json={"op": "abort", "transfer_id": begin["transfer_id"]},
            )
            # 0 disables the check entirely.
            monkeypatch.setenv("VDT_KV_MAX_FRAME_BYTES", "0")
            r = await client.post(
                "/internal/kv",
                json={
                    "op": "chunk",
                    "transfer_id": "t",
                    "layers": [{"pad": "x" * 4096}],
                },
            )
            assert r.status != 413
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()
