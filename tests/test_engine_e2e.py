"""End-to-end engine tests on CPU against the transformers oracle
(SURVEY.md §4 items 1-3: the reference ships no tests; this is the test
pyramid the TPU build adds)."""

import numpy as np
import pytest

from tests.utils import (
    hf_greedy_generate,
    hf_logits,
    make_tiny_llama,
    make_tiny_opt,
)
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def tiny_llama(tmp_path_factory):
    return make_tiny_llama(str(tmp_path_factory.mktemp("llama")))


@pytest.fixture(scope="module")
def tiny_opt(tmp_path_factory):
    return make_tiny_opt(str(tmp_path_factory.mktemp("opt")))


def _make_engine(model_dir, **kw):
    defaults = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        num_kv_pages=128,
        page_size=16,
        max_num_seqs=8,
        max_model_len=256,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


def _run_greedy(engine, prompts, max_tokens=8):
    for i, p in enumerate(prompts):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    done = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
    return [done[f"r{i}"].outputs[0].token_ids for i in range(len(prompts))]


def test_llama_greedy_matches_hf(tiny_llama):
    prompt = [1, 5, 9, 23, 77, 41, 3]
    expected = hf_greedy_generate(tiny_llama, prompt, 8)
    engine = _make_engine(tiny_llama)
    got = _run_greedy(engine, [prompt])[0]
    assert got == expected


def test_llama_prefill_logits_match_hf(tiny_llama):
    """Single prefill step's last-token logits vs transformers."""
    from vllm_distributed_tpu.config import ModelConfig
    from vllm_distributed_tpu.engine.scheduler import (
        NewRequestData,
        SchedulerOutput,
    )

    prompt = [2, 4, 8, 16, 32, 64]
    ref = hf_logits(tiny_llama, prompt)[-1]

    engine = _make_engine(tiny_llama)
    worker = engine.executor.worker
    runner = worker.runner
    so = SchedulerOutput(
        step_id=0,
        new_requests=[
            NewRequestData(
                req_id="x",
                prompt_token_ids=prompt,
                num_prompt_tokens=len(prompt),
                page_ids=[1],
                num_computed_tokens=0,
                num_new_tokens=len(prompt),
                sampling_params=SamplingParams(temperature=0.0),
            )
        ],
        num_scheduled_tokens={"x": len(prompt)},
        total_num_scheduled_tokens=len(prompt),
    )
    # Capture logits by running the model forward directly.
    import jax.numpy as jnp

    from vllm_distributed_tpu.ops.attention import AttentionMetadata

    t_pad, s_pad, pages = 16, 8, 8
    tokens = np.zeros(t_pad, np.int32)
    tokens[: len(prompt)] = prompt
    positions = np.zeros(t_pad, np.int32)
    positions[: len(prompt)] = np.arange(len(prompt))
    seq_ids = np.full(t_pad, s_pad - 1, np.int32)
    seq_ids[: len(prompt)] = 0
    slots = np.zeros(t_pad, np.int32)
    slots[: len(prompt)] = 16 + np.arange(len(prompt))  # page 1
    bt = np.zeros((s_pad, pages), np.int32)
    bt[0, 0] = 1
    seq_lens = np.zeros(s_pad, np.int32)
    seq_lens[0] = len(prompt)
    li = np.zeros(s_pad, np.int32)
    li[0] = len(prompt) - 1
    meta = AttentionMetadata(
        q_seq_ids=jnp.asarray(seq_ids),
        q_positions=jnp.asarray(positions),
        slot_mapping=jnp.asarray(slots),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.asarray(seq_lens),
        logits_indices=jnp.asarray(li),
        chunk_starts=jnp.zeros(s_pad, jnp.int32),
    )
    logits, _ = runner.model.forward(
        runner.params, jnp.asarray(tokens), runner.kv_caches, meta
    )
    got = np.asarray(logits[0])
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_chunked_prefill_consistency(tiny_llama):
    """Chunked prefill (tiny token budget) must give identical greedy
    output to unchunked."""
    prompt = list(range(1, 41))  # 40-token prompt
    big = _make_engine(tiny_llama, max_num_batched_tokens=2048)
    small = _make_engine(
        tiny_llama, max_num_batched_tokens=16, max_num_seqs=8
    )
    out_big = _run_greedy(big, [prompt])[0]
    out_small = _run_greedy(small, [prompt])[0]
    assert out_big == out_small


def test_batched_requests_match_individual(tiny_llama):
    prompts = [
        [1, 5, 9],
        [7, 2, 88, 14, 3, 9, 55],
        [100, 3],
        [42, 42, 42, 42, 42, 42, 42, 42, 42, 42, 42],
    ]
    batched_engine = _make_engine(tiny_llama)
    batched = _run_greedy(batched_engine, prompts, max_tokens=6)
    for i, p in enumerate(prompts):
        solo = _run_greedy(_make_engine(tiny_llama), [p], max_tokens=6)[0]
        assert batched[i] == solo, f"prompt {i} diverged"


def test_opt_greedy_matches_hf(tiny_opt):
    prompt = [1, 9, 17, 33, 65]
    expected = hf_greedy_generate(tiny_opt, prompt, 8)
    engine = _make_engine(tiny_opt)
    got = _run_greedy(engine, [prompt])[0]
    assert got == expected


def test_preemption_recovers(tiny_llama):
    """Starve the page pool so preemption kicks in; outputs must still
    match the unconstrained run."""
    prompts = [list(range(1, 20)), list(range(20, 40)), list(range(3, 17))]
    rich = _run_greedy(_make_engine(tiny_llama), prompts, max_tokens=6)
    poor_engine = _make_engine(tiny_llama, num_kv_pages=8, page_size=16)
    poor = _run_greedy(poor_engine, prompts, max_tokens=6)
    assert rich == poor


def test_sampling_seed_determinism(tiny_llama):
    def run(seed):
        engine = _make_engine(tiny_llama)
        engine.add_request(
            "s",
            prompt_token_ids=[1, 2, 3, 4],
            sampling_params=SamplingParams(
                temperature=0.8,
                top_p=0.9,
                seed=seed,
                max_tokens=8,
                ignore_eos=True,
            ),
        )
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    return out.outputs[0].token_ids

    a = run(1234)
    b = run(1234)
    c = run(999)
    assert a == b
    assert a != c or len(a) == 0  # overwhelmingly likely to differ


def test_pipeline_parallel_rejected(tiny_llama):
    """PP is deliberately unsupported on TPU (see README rationale);
    the flag errors loudly instead of being accepted and ignored."""
    with pytest.raises(ValueError, match="pipeline parallelism"):
        _make_engine(tiny_llama, pipeline_parallel_size=2)


def test_kv_cache_dtype_honored(tiny_llama):
    """cache_dtype narrows the KV pool (doubling capacity) while the
    model stays in its own dtype."""
    import jax.numpy as jnp

    engine = _make_engine(tiny_llama, kv_cache_dtype="bfloat16")
    runner = engine.executor.worker.runner
    assert runner.kv_caches[0][0].dtype == jnp.bfloat16
    toks = _run_greedy(engine, [[1, 5, 9, 23]], max_tokens=4)[0]
    assert len(toks) == 4


def test_qwen2_greedy_matches_hf(tmp_path):
    """Attention-bias variant (Qwen2) vs transformers."""
    from tests.utils import make_tiny_qwen2

    model_dir = make_tiny_qwen2(str(tmp_path / "q2"))
    prompt = [1, 5, 9, 23, 77]
    expected = hf_greedy_generate(model_dir, prompt, 8)
    got = _run_greedy(_make_engine(model_dir), [prompt])[0]
    assert got == expected


def test_qwen3_greedy_matches_hf(tmp_path):
    """Per-head QK RMS-norm variant (Qwen3 dense) vs transformers."""
    from tests.utils import make_tiny_qwen3

    model_dir = make_tiny_qwen3(str(tmp_path / "q3"))
    prompt = [2, 4, 8, 16, 32]
    expected = hf_greedy_generate(model_dir, prompt, 8)
    got = _run_greedy(_make_engine(model_dir), [prompt])[0]
    assert got == expected
