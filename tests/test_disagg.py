"""Disaggregated prefill/decode suite (ISSUE 15).

Layered like the feature: worker-level export/import round trips with
checksum verification against the mock worker's page-content store;
engine-level hold/TTL + import lifecycle over the replica HTTP surface;
router-side crossover gating and role-aware placement units; fleet
role-spawn units; and the mocked 2-replica acceptance runs — a long
prompt streamed through a prefill-role + decode-role pool completes
bit-identically to a cold run (VDT_MOCK_TOKEN_SEQ position tokens) with
the KV pages actually transferred (decode-side prefix hits, zero
migrations burned), the prefill-kill fallback recovers via
recompute-resume, and the interference A/B shows role separation
holding the decode ITL flat under a concurrent long prefill.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockUniProcExecutor, MockWorker
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
    serve_http,
)
from vllm_distributed_tpu.router import disagg
from vllm_distributed_tpu.router.app import RouterState, build_router_app
from vllm_distributed_tpu.router.journal import RouterJournal
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port

pytestmark = pytest.mark.disagg

PAGE = 16  # default EngineArgs page_size


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _mk_engine(model_dir: str, **kw) -> AsyncLLM:
    args = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=96,
        max_model_len=1024,
        num_decode_steps=1,
        enable_prefix_caching=True,
        distributed_executor_backend=MockUniProcExecutor,
    )
    args.update(kw)
    return AsyncLLM.from_engine_args(EngineArgs(**args))


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return write_llama_config(
        str(tmp_path_factory.mktemp("disagg") / "m")
    )


def _sse_chunks(body: str) -> list[dict]:
    out = []
    for line in body.splitlines():
        if line.startswith("data: ") and line[6:] != "[DONE]":
            out.append(json.loads(line[6:]))
    return out


# ---------------------------------------------------------------------
# worker-level export/import round trip + checksum verification
# ---------------------------------------------------------------------
def test_mock_export_import_roundtrip_and_checksum(model_dir):
    cfg = EngineArgs(
        model=model_dir, skip_tokenizer_init=True, load_format="dummy"
    ).create_engine_config()
    src = MockWorker(cfg)
    dst = MockWorker(cfg)
    rows = {
        5: list(range(100, 100 + PAGE)),
        9: list(range(200, 200 + PAGE)),
    }
    src._kv_pages.update({p: list(r) for p, r in rows.items()})

    out = src.export_kv_pages([5, 9], 0, 8)
    assert out["num_layers"] == MockWorker.MOCK_KV_LAYERS
    assert len(out["layers"]) == MockWorker.MOCK_KV_LAYERS
    # Import into fresh pages on the destination store.
    res = dst.import_kv_pages([3, 7], out["layers"])
    assert res == {"ok": True}
    assert dst._kv_pages[3] == rows[5]
    assert dst._kv_pages[7] == rows[9]

    # A corrupted payload is rejected BEFORE anything lands.
    bad = [dict(layer) for layer in out["layers"]]
    bad[0] = dict(bad[0], data=bad[0]["data"] + b"x")
    dst2 = MockWorker(cfg)
    res = dst2.import_kv_pages([3, 7], bad)
    assert res["ok"] is False and "checksum" in res["error"]
    assert 3 not in dst2._kv_pages and 7 not in dst2._kv_pages

    # Chunked export (one layer at a time) covers the same content.
    one = src.export_kv_pages([5, 9], 1, 1)
    assert [layer["index"] for layer in one["layers"]] == [1]
    assert one["layers"][0]["checksum"] == out["layers"][1]["checksum"]


# ---------------------------------------------------------------------
# replica HTTP surface: prefill-only hold -> export -> import -> resume
# ---------------------------------------------------------------------
async def _prefill_only(client, prompt, max_tokens=8):
    """Drive the disagg hop on a replica; returns (kv_handle,
    first_token_ids, chunks)."""
    r = await client.post(
        "/v1/completions",
        json={
            "prompt": list(prompt),
            "max_tokens": max_tokens,
            "temperature": 0.0,
            "ignore_eos": True,
            "stream": True,
        },
        headers={"X-VDT-Router": "1", "X-VDT-Disagg": "prefill"},
    )
    assert r.status == 200
    chunks = _sse_chunks(await r.text())
    handle = None
    toks: list[int] = []
    for c in chunks:
        for ch in c.get("choices") or ():
            toks += ch.get("vdt_token_ids") or []
            if ch.get("vdt_kv_handle"):
                handle = ch["vdt_kv_handle"]
    return handle, toks, chunks


def test_export_hold_import_resume_bit_identical(model_dir, monkeypatch):
    """The full hand-off machinery without a router: prefill-only on A
    holds pages; export chunks checksum-verify into B; after commit the
    resume on B attaches the imported chain as computed (decode-side
    prefix hits, mock page-content verification) and continues with the
    exact cold-run token sequence.  Above the crossover the hand-off
    resume is also measurably faster than recompute-resume (the mock
    charges VDT_MOCK_TOKEN_SECONDS per prefilled token)."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_TOKEN_SECONDS", "0.002")
    n_prompt = 12 * PAGE  # 192 tokens -> 12 full pages held, ~0.4s prefill
    prompt = [(i % 500) + 1 for i in range(n_prompt)]
    rec_prompt = [(i % 500) + 2 for i in range(n_prompt)]
    max_tokens = 6
    expected = list(range(n_prompt, n_prompt + max_tokens))
    a = _mk_engine(model_dir)
    b = _mk_engine(model_dir)
    state_a = init_app_state(a, served_model_name="a", role="prefill")
    state_b = init_app_state(b, served_model_name="b", role="decode")

    async def resume_first_frame(cb, rid, p, emitted):
        t0 = time.perf_counter()
        r = await cb.post(
            "/internal/resume",
            json={
                "request_id": rid,
                "kind": "completions",
                "body": {
                    "prompt": list(p),
                    "max_tokens": max_tokens,
                    "temperature": 0.0,
                    "ignore_eos": True,
                    "stream": True,
                },
                "prompt_token_ids": list(p),
                "emitted_token_ids": list(emitted),
            },
        )
        assert r.status == 200
        frames = _sse_chunks(await r.text())
        ids = [t for f in frames for t in f.get("token_ids") or ()]
        return ids, time.perf_counter() - t0

    async def go():
        ca = TestClient(TestServer(build_app(state_a)))
        cb = TestClient(TestServer(build_app(state_b)))
        await ca.start_server()
        await cb.start_server()
        try:
            # Baseline: recompute-resume of a same-length cold prompt.
            rec_ids, t_recompute = await resume_first_frame(
                cb, "rec-1", rec_prompt, []
            )
            assert rec_ids == expected

            handle, first, _ = await _prefill_only(ca, prompt)
            assert handle and first == [n_prompt]
            kvt_a = a.engine.kv_transfer
            assert list(kvt_a.holds) == [handle]
            assert len(kvt_a.holds[handle].pages) == n_prompt // PAGE

            # Transfer: begin -> per-layer chunks -> commit.
            t0 = time.perf_counter()
            r = await cb.post(
                "/internal/kv",
                json={"op": "begin", "prompt_token_ids": prompt},
            )
            begin = await r.json()
            assert r.status == 200 and begin["transfer_id"]
            tid = begin["transfer_id"]
            layer, num_layers = 0, None
            while num_layers is None or layer < num_layers:
                r = await ca.post(
                    "/internal/kv/export",
                    json={
                        "handle": handle,
                        "layer_start": layer,
                        "layer_count": 1,
                    },
                )
                chunk = await r.json()
                assert r.status == 200, chunk
                num_layers = chunk["num_layers"]
                assert chunk["token_ids"] == prompt
                r = await cb.post(
                    "/internal/kv",
                    json={
                        "op": "chunk",
                        "transfer_id": tid,
                        "layers": chunk["layers"],
                    },
                )
                assert r.status == 200, await r.text()
                layer += len(chunk["layers"])
            r = await cb.post(
                "/internal/kv", json={"op": "commit", "transfer_id": tid}
            )
            commit = await r.json()
            assert r.status == 200
            assert commit["adopted_tokens"] == n_prompt
            transfer_s = time.perf_counter() - t0
            r = await ca.post(
                "/internal/kv/release", json={"handle": handle}
            )
            assert (await r.json())["released"] is True
            assert kvt_a.holds == {}
            # Every page on A is free again (cached-free counts free).
            alloc_a = a.engine.scheduler.allocator
            assert alloc_a.num_free_pages == alloc_a.num_pages - 1

            # Resume on B: the imported chain attaches as computed.
            hits_before = b.engine.scheduler.prefix_cache_hits
            ids, t_resume = await resume_first_frame(
                cb, "mig-1", prompt, first
            )
            assert ids == expected[1:]  # first token restored, not resent
            hit = b.engine.scheduler.prefix_cache_hits - hits_before
            assert hit >= (n_prompt // PAGE - 1) * PAGE
            assert b.engine.kv_transfer.imports == {}
            # Crossover: hand-off (transfer + warm resume) beats
            # recompute-resume at this length.
            assert transfer_s + t_resume < t_recompute, (
                transfer_s, t_resume, t_recompute,
            )
        finally:
            await ca.close()
            await cb.close()

    try:
        _run(go())
    finally:
        a.shutdown()
        b.shutdown()


def test_import_checksum_mismatch_aborts(model_dir, monkeypatch):
    """A corrupted chunk 409s, frees the reservation, and the transfer
    id is dead from then on — garbage KV can never be committed."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    prompt = [(i % 300) + 1 for i in range(3 * PAGE)]
    a = _mk_engine(model_dir)
    b = _mk_engine(model_dir)
    state_a = init_app_state(a, served_model_name="a", role="prefill")
    state_b = init_app_state(b, served_model_name="b", role="decode")

    async def go():
        ca = TestClient(TestServer(build_app(state_a)))
        cb = TestClient(TestServer(build_app(state_b)))
        await ca.start_server()
        await cb.start_server()
        try:
            handle, _, _ = await _prefill_only(ca, prompt)
            r = await ca.post(
                "/internal/kv/export",
                json={"handle": handle, "layer_start": 0, "layer_count": 8},
            )
            chunk = await r.json()
            r = await cb.post(
                "/internal/kv",
                json={"op": "begin", "prompt_token_ids": prompt},
            )
            tid = (await r.json())["transfer_id"]
            free_before = (
                b.engine.scheduler.allocator.num_free_pages
            )
            layers = chunk["layers"]
            raw = bytearray(base64.b64decode(layers[0]["data"]))
            raw[0] ^= 0xFF
            layers[0]["data"] = base64.b64encode(bytes(raw)).decode()
            r = await cb.post(
                "/internal/kv",
                json={"op": "chunk", "transfer_id": tid, "layers": layers},
            )
            assert r.status == 409
            # Reservation freed, transfer dead, no pages leaked.
            assert b.engine.kv_transfer.imports == {}
            alloc = b.engine.scheduler.allocator
            assert (
                alloc.num_free_pages
                == free_before + len(prompt) // PAGE
            )
            r = await cb.post(
                "/internal/kv", json={"op": "commit", "transfer_id": tid}
            )
            assert r.status == 409
            # An unknown export handle is a clean 404-class error too.
            r = await ca.post(
                "/internal/kv/export",
                json={"handle": "nope", "layer_start": 0, "layer_count": 1},
            )
            assert r.status == 409
        finally:
            await ca.close()
            await cb.close()

    try:
        _run(go())
    finally:
        a.shutdown()
        b.shutdown()


def test_export_hold_ttl_expires(model_dir, monkeypatch):
    """A hold the router never collects (it died mid-hand-off) is swept
    at schedule time after VDT_DISAGG_EXPORT_TTL_SECONDS — pool pages
    can never leak."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_DISAGG_EXPORT_TTL_SECONDS", "0.05")
    prompt = [(i % 300) + 1 for i in range(3 * PAGE)]
    engine = _mk_engine(model_dir)
    state = init_app_state(engine, served_model_name="a", role="prefill")

    async def go():
        client = TestClient(TestServer(build_app(state)))
        await client.start_server()
        try:
            handle, _, _ = await _prefill_only(client, prompt)
            kvt = engine.engine.kv_transfer
            assert handle in kvt.holds
            await asyncio.sleep(0.1)
            # Any scheduled step runs the sweep.
            r = await client.post(
                "/v1/completions",
                json={
                    "prompt": [1, 2, 3],
                    "max_tokens": 2,
                    "temperature": 0.0,
                    "ignore_eos": True,
                },
            )
            assert r.status == 200
            assert kvt.holds == {}
            alloc = engine.engine.scheduler.allocator
            assert alloc.num_free_pages == alloc.num_pages - 1
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------
# router units: crossover gating + role-aware placement
# ---------------------------------------------------------------------
def _disagg_state(roles: list[str]) -> RouterState:
    state = RouterState(
        [f"http://r{i}" for i in range(len(roles))],
        policy="least_loaded",
        health_interval=60.0,
        max_migrations=3,
    )
    for replica, role in zip(state.pool.replicas, roles):
        replica.state = "healthy"
        replica.role = role
    return state


def _journal(prompt, **body_kw) -> RouterJournal:
    body = {"prompt": prompt, "stream": True, "max_tokens": 8}
    body.update(body_kw)
    return RouterJournal("rtr-1", "completions", body)


def test_crossover_and_pool_gating():
    state = _disagg_state(["prefill", "decode"])
    state.disagg_min_prompt_tokens = 64
    long, short = list(range(80)), list(range(32))
    assert disagg.plan_handoff(state, _journal(long), []) is not None
    # Below the crossover: serve normally.
    assert disagg.plan_handoff(state, _journal(short), []) is None
    # Text prompts estimate at ~4 chars/token.
    assert (
        disagg.plan_handoff(state, _journal("x" * 400), []) is not None
    )
    assert disagg.plan_handoff(state, _journal("x" * 100), []) is None
    # Not plannable: non-streaming, multi-choice, one-token budgets.
    assert (
        disagg.plan_handoff(state, _journal(long, stream=False), [])
        is None
    )
    assert disagg.plan_handoff(state, _journal(long, n=2), []) is None
    assert (
        disagg.plan_handoff(state, _journal(long, max_tokens=1), [])
        is None
    )
    # No prefill pool (all mixed) or no decode pool: never planned.
    assert (
        disagg.plan_handoff(
            _mixed := _disagg_state(["mixed", "mixed"]), _journal(long), []
        )
        is None
    )
    only_prefill = _disagg_state(["prefill", "prefill"])
    only_prefill.disagg_min_prompt_tokens = 64
    assert disagg.plan_handoff(only_prefill, _journal(long), []) is None


def test_role_aware_placement():
    state = _disagg_state(["prefill", "decode", "mixed"])
    # Serve placement never lands on the prefill replica while any
    # decode-capable candidate exists.
    for _ in range(8):
        replica, _how = state.place([], set())
        assert replica.role != "prefill"
    # The prefill pool picks only prefill-role replicas.
    replica, _how = state.place([], set(), pool="prefill")
    assert replica.role == "prefill"
    # Availability over purity: with every decode candidate excluded,
    # serve placement falls back to the prefill replica.
    exclude = {r.url for r in state.pool.replicas if r.role != "prefill"}
    replica, _how = state.place([], exclude)
    assert replica is not None and replica.role == "prefill"
    # And an all-excluded prefill pool yields none.
    assert state.place([], set(), pool="prefill")[1] != "none"
    all_prefill = {
        r.url for r in state.pool.replicas if r.role == "prefill"
    }
    assert state.place([], all_prefill, pool="prefill")[0] is None


# ---------------------------------------------------------------------
# fleet role-spawn units
# ---------------------------------------------------------------------
class _FakeHandle:
    def __init__(self, pid):
        self.pid = pid
        self._exit = None

    def poll(self):
        return self._exit

    def terminate(self):
        self._exit = -15

    def kill(self):
        self._exit = -9

    def wait(self, timeout=None):
        return self._exit


class _RoleLauncher:
    def __init__(self):
        self.spawned: list[tuple[str, int, str]] = []

    def spawn(self, replica_id, port, role="mixed"):
        self.spawned.append((replica_id, port, role))
        return _FakeHandle(pid=4000 + len(self.spawned))


def test_fleet_role_spawn_units():
    """Per-role targets converge alongside the mixed fleet: spawns
    carry the role (launcher + pool), victims retire within their own
    role, and legacy 2-arg launchers keep working for the mixed pool."""
    from vllm_distributed_tpu.router.fleet import ReplicaManager
    from vllm_distributed_tpu.router.metrics import RouterMetrics
    from vllm_distributed_tpu.router.pool import ReplicaPool

    async def health_check(url):
        return True

    async def drainer(url, timeout):
        return None

    async def go():
        pool = ReplicaPool([], allow_empty=True)
        launcher = _RoleLauncher()
        manager = ReplicaManager(
            pool,
            RouterMetrics(enabled=False),
            launcher,
            target=1,
            role_targets={"prefill": 1, "decode": 2},
            warmup_timeout=5.0,
            drain_timeout=1.0,
            check_interval=0.01,
            max_restarts=3,
            restart_window=300.0,
            backoff_base=0.0,
            backoff_cap=0.0,
            health_check=health_check,
            drainer=drainer,
        )
        # One spawn per tick across roles: four ticks to converge.
        for _ in range(6):
            await manager._reconcile()
            await asyncio.sleep(0.02)
        assert manager.ready_count() == 4
        roles = sorted(role for _, _, role in launcher.spawned)
        assert roles == ["decode", "decode", "mixed", "prefill"]
        # Role-tagged ids + pool roles line up.
        by_role = {}
        for r in pool.replicas:
            by_role.setdefault(r.role, []).append(r.replica_id)
        assert len(by_role["prefill"]) == 1
        assert "prefill" in by_role["prefill"][0]
        assert len(by_role["decode"]) == 2
        assert len(by_role["mixed"]) == 1
        # Shrinking one role retires only that role's replicas.
        manager.role_targets["decode"] = 1
        for _ in range(5):
            await manager._reconcile()
            await asyncio.sleep(0.02)
        assert len(manager.active("decode")) == 1
        assert len(manager.active("prefill")) == 1
        assert len(manager.active("mixed")) == 1
        await manager.stop(drain=False)

    _run(go())


def test_fleet_legacy_launcher_compat():
    """A pre-role launcher (2-arg spawn) still serves the mixed pool."""
    from vllm_distributed_tpu.router.fleet import ReplicaManager
    from vllm_distributed_tpu.router.metrics import RouterMetrics
    from vllm_distributed_tpu.router.pool import ReplicaPool

    class LegacyLauncher:
        def __init__(self):
            self.spawned = []

        def spawn(self, replica_id, port):
            self.spawned.append((replica_id, port))
            return _FakeHandle(pid=5000 + len(self.spawned))

    async def health_check(url):
        return True

    async def go():
        pool = ReplicaPool([], allow_empty=True)
        manager = ReplicaManager(
            pool,
            RouterMetrics(enabled=False),
            LegacyLauncher(),
            target=1,
            warmup_timeout=5.0,
            check_interval=0.01,
            backoff_base=0.0,
            backoff_cap=0.0,
            health_check=health_check,
        )
        await manager._reconcile()
        (mr,) = manager.replicas
        await asyncio.wait_for(mr.task, timeout=5)
        assert mr.state == "ready" and mr.role == "mixed"
        await manager.stop(drain=False)

    _run(go())


# ---------------------------------------------------------------------
# 2-replica acceptance: hand-off bit-identity + journal fix + fallback
# ---------------------------------------------------------------------
async def _boot_role_replicas(model_dir, roles, **engine_kw):
    engines, runners, urls = [], [], []
    for i, role in enumerate(roles):
        engine = _mk_engine(model_dir, **engine_kw)
        state = init_app_state(
            engine,
            served_model_name="e2e",
            replica_id=f"replica-{i}",
            role=role,
        )
        port = get_open_port()
        runner = await serve_http(
            build_app(state),
            host="127.0.0.1",
            port=port,
            shutdown_timeout=0.05,
        )
        engines.append(engine)
        runners.append(runner)
        urls.append(f"http://127.0.0.1:{port}")
    return engines, runners, urls


async def _teardown(client, runners, engines):
    if client is not None:
        await client.close()
    for runner in runners:
        if runner is not None:
            try:
                await runner.cleanup()
            except Exception:  # noqa: BLE001 — already torn down
                pass
    for engine in engines:
        try:
            engine.shutdown()
        except Exception:  # noqa: BLE001 — already torn down
            pass


async def _stream_via_router(client, body):
    """Stream through the router (debug passthrough); returns
    (token_ids, finish_reason, raw_chunks, error)."""
    toks: list[int] = []
    finish = None
    error = None
    chunks: list[dict] = []
    r = await client.post(
        "/v1/completions", json=body, headers={"X-VDT-Router": "1"}
    )
    assert r.status == 200, await r.text()
    async for raw in r.content:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        payload = line[5:].strip()
        if payload == "[DONE]":
            break
        obj = json.loads(payload)
        if "error" in obj and not obj.get("choices"):
            error = obj
            break
        chunks.append(obj)
        for ch in obj.get("choices") or ():
            toks += ch.get("vdt_token_ids") or []
            if ch.get("finish_reason"):
                finish = ch["finish_reason"]
    return toks, finish, chunks, error


def _handoff_case(model_dir, monkeypatch, kill_mode: str | None):
    """Shared body of the hand-off acceptance tests: stream one long
    prompt through a prefill+decode pool.  kill_mode None = happy path
    (planned hand-off); "before_transfer"/"mid_export" SIGKILL the
    prefill replica at the deterministic seam and assert the recompute
    fallback still completes bit-identically."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    n_prompt = 3 * PAGE
    max_tokens = 8
    prompt = [(i % 200) + 1 for i in range(n_prompt)]
    expected = list(range(n_prompt, n_prompt + max_tokens))
    body = {
        "prompt": prompt,
        "max_tokens": max_tokens,
        "temperature": 0.0,
        "ignore_eos": True,
        "stream": True,
    }

    async def go():
        engines, runners, urls = await _boot_role_replicas(
            model_dir, ("prefill", "decode")
        )
        state = RouterState(
            urls,
            policy="least_loaded",
            health_interval=0.3,
            connect_timeout=2.0,
            read_timeout=20.0,
        )
        state.disagg_min_prompt_tokens = 32
        state.disagg_chunk_layers = 1  # 2 mock layers -> 2 chunks
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()

        async def kill_prefill():
            runner, runners[0] = runners[0], None
            await runner.cleanup()
            engines[0].shutdown()

        if kill_mode == "before_transfer":

            async def seam():
                await kill_prefill()

            monkeypatch.setattr(disagg, "_test_before_transfer", seam)
        elif kill_mode == "mid_export":

            async def seam(idx):
                if idx == 1:
                    await kill_prefill()

            monkeypatch.setattr(disagg, "_test_after_chunk", seam)
        try:
            toks, finish, chunks, error = await _stream_via_router(
                client, body
            )
            assert error is None, error
            # Bit-identical to a cold single-replica run.
            assert toks == expected, (toks, expected)
            assert finish == "length"
            # The export handle never reaches the client.
            for c in chunks:
                for ch in c.get("choices") or ():
                    assert "vdt_kv_handle" not in ch
            counters = (
                await (await client.get("/router/state")).json()
            )["counters"]
            migrations = {
                k: v
                for k, v in counters.items()
                if k.startswith("migrations.")
            }
            if kill_mode is None:
                assert counters.get("handoffs.planned") == 1, counters
                # The journal fix (ISSUE 15 satellite): a planned
                # hand-off is the happy path — zero migrations counted,
                # zero budget burned.
                assert not migrations, counters
                # KV really moved: the decode replica admitted the
                # resume on transferred pages, not recompute.
                assert engines[1].engine.scheduler.prefix_cache_hits >= (
                    (n_prompt // PAGE - 1) * PAGE
                )
                # Hold released, transfer settled.
                assert engines[0].engine.kv_transfer.holds == {}
                assert engines[1].engine.kv_transfer.imports == {}
                a1 = engines[0].engine.scheduler.allocator
                assert a1.num_free_pages == a1.num_pages - 1
            else:
                assert counters.get("handoffs.fallback") == 1, counters
                assert not migrations, counters
                assert engines[1].engine.kv_transfer.imports == {}
            # Decode-side allocator accounts for every page.
            ad = engines[1].engine.scheduler.allocator
            assert ad.num_free_pages == ad.num_pages - 1
        finally:
            await _teardown(client, runners, engines)

    _run(go())


def test_handoff_planned_bit_identical(model_dir, monkeypatch):
    _handoff_case(model_dir, monkeypatch, None)


def test_handoff_fallback_on_kill_before_transfer(model_dir, monkeypatch):
    _handoff_case(model_dir, monkeypatch, "before_transfer")


def test_handoff_fallback_on_kill_mid_export(model_dir, monkeypatch):
    _handoff_case(model_dir, monkeypatch, "mid_export")


# ---------------------------------------------------------------------
# interference A/B smoke (the tentpole's judge, on mock replicas)
# ---------------------------------------------------------------------
def _interference_run(model_dir, roles) -> tuple[float, float]:
    """Two steady decode streams + one long prompt on a 2-replica pool.
    Returns (worst decode inter-chunk gap during the long prefill,
    long-prompt TTFT)."""
    n_long = 24 * PAGE  # 384 tokens x 4ms/token ≈ 1.5s mock prefill

    async def go():
        engines, runners, urls = await _boot_role_replicas(
            model_dir, roles
        )
        state = RouterState(
            urls,
            policy="round_robin",
            health_interval=0.3,
            connect_timeout=2.0,
            read_timeout=30.0,
        )
        state.disagg_min_prompt_tokens = 64
        server = TestServer(build_router_app(state))
        client = TestClient(server)
        await client.start_server()
        arrivals: list[list[float]] = [[], []]
        marks: dict[str, float] = {}

        async def decode_stream(i: int):
            body = {
                "prompt": [7 * i + 1, 7 * i + 2, 7 * i + 3],
                "max_tokens": 300,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            r = await client.post(
                "/v1/completions", json=body,
                headers={"X-VDT-Router": "1"},
            )
            assert r.status == 200
            async for raw in r.content:
                line = raw.decode().strip()
                if line.startswith("data:") and line[5:].strip() not in (
                    "",
                    "[DONE]",
                ):
                    arrivals[i].append(time.perf_counter())

        async def long_stream():
            body = {
                "prompt": [(j % 700) + 1 for j in range(n_long)],
                "max_tokens": 2,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            marks["start"] = time.perf_counter()
            r = await client.post(
                "/v1/completions", json=body,
                headers={"X-VDT-Router": "1"},
            )
            assert r.status == 200
            async for raw in r.content:
                line = raw.decode().strip()
                if line.startswith("data:") and line[5:].strip() not in (
                    "",
                    "[DONE]",
                ):
                    marks.setdefault("first", time.perf_counter())
            marks["end"] = time.perf_counter()

        try:
            tasks = [
                asyncio.get_running_loop().create_task(decode_stream(i))
                for i in range(2)
            ]
            deadline = time.perf_counter() + 20
            while time.perf_counter() < deadline:
                if all(len(a) >= 3 for a in arrivals):
                    break
                await asyncio.sleep(0.01)
            await long_stream()
            await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
        finally:
            await _teardown(client, runners, engines)
        start, end = marks["start"], marks["end"]
        worst = 0.0
        for a in arrivals:
            for prev, cur in zip(a, a[1:]):
                if cur >= start and prev <= end:
                    worst = max(worst, cur - prev)
        ttft = marks.get("first", end) - start
        return worst, ttft

    return _run(go())


def test_interference_ab_separated_beats_mixed(model_dir, monkeypatch):
    """The ISSUE 15 acceptance A/B on mock replicas: with the mock
    charging per-prefilled-token device time, a long prompt sharing a
    mixed replica with a decode stream stalls that stream for the whole
    prefill; role-separated pools keep the decode pool's worst
    inter-token gap an order of magnitude lower."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_TOKEN_SECONDS", "0.004")
    # Floor per-step device time so the decode streams are still
    # running while the long prompt prefills (no false pass from a
    # decode stream that finished before the interference window).
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.005")
    mixed_worst, _ = _interference_run(model_dir, ("mixed", "mixed"))
    sep_worst, _sep_ttft = _interference_run(
        model_dir, ("prefill", "decode")
    )
    # Strictly lower, with margin: the mixed pool eats the ~1.5s
    # prefill stall on a decode stream; the separated pool never does.
    assert sep_worst < mixed_worst, (sep_worst, mixed_worst)
    assert mixed_worst > 3 * sep_worst, (sep_worst, mixed_worst)
