"""Multi-host executor over loopback with mock workers (SURVEY.md §4
item 4: the reference's own topology is fully exercisable on one machine;
cf. launch.py:549 connecting over loopback)."""

import multiprocessing
import os
import time

import pytest

from tests.mock_worker import MockWorker  # noqa: F401 (import check)
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.distributed.agent import remote_main
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port


class MockedMultiHostExecutor(MultiHostExecutor):
    worker_cls = "tests.mock_worker.MockWorker"


def _spawn_agent(port):
    proc = multiprocessing.Process(
        target=remote_main, args=("127.0.0.1", port), daemon=True
    )
    proc.start()
    return proc


@pytest.fixture
def deployment(tmp_path, monkeypatch):
    """A 2-host mocked deployment: executor (host 0) + one agent proc."""
    port = get_open_port()
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "20")
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    agent = _spawn_agent(port)
    model_dir = write_llama_config(str(tmp_path / "m"))
    config = EngineArgs(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_hosts=2,
    ).create_engine_config()
    executor = MockedMultiHostExecutor(config)
    yield executor, agent
    executor.shutdown()
    if agent.is_alive():
        agent.terminate()
    agent.join(timeout=5)


def test_boot_and_lifecycle_order(deployment):
    executor, _ = deployment
    # Local + remote both ran init_device then load_model, in order.
    lifecycles = executor.collective_rpc("get_lifecycle")
    assert len(lifecycles) == 2
    for lc in lifecycles:
        assert lc == ["init_device", "load_model"]


def test_num_pages_min_aggregation(deployment):
    executor, _ = deployment
    # host0 reports 100, host1 reports 101 → min wins.
    assert executor.determine_num_pages() == 100


def test_env_replication(deployment, monkeypatch):
    executor, _ = deployment
    # VDT_EXECUTE_MODEL_TIMEOUT_SECONDS was set pre-boot and is in the
    # registry → must exist on the remote host; ranks must be 0 and 1.
    replies = executor.collective_rpc(
        "get_rank_and_env", ("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS",)
    )
    assert sorted(r[0] for r in replies) == [0, 1]
    for rank, value in replies:
        assert value == "20", f"rank {rank} missing replicated env"


def test_execute_model_replies_from_host0_only(deployment):
    executor, _ = deployment
    so = SchedulerOutput(
        step_id=0,
        num_scheduled_tokens={"r1": 1},
        total_num_scheduled_tokens=1,
    )
    out = executor.execute_model(so)
    assert out.sampled_token_ids == {"r1": [42]}
    # Fan-out to all, reply only from designated rank:
    replies = executor.collective_rpc("execute_model", (so,))
    assert replies[0] is not None and replies[1] is None


def test_agent_loss_fails_executor(deployment):
    executor, agent = deployment
    failed = []
    executor.register_failure_callback(lambda: failed.append(True))
    agent.terminate()
    agent.join(timeout=5)
    deadline = time.time() + 10
    while not executor.is_failed and time.time() < deadline:
        time.sleep(0.1)
    assert executor.is_failed
    assert failed == [True]
    with pytest.raises(RuntimeError, match="Executor failed"):
        executor.collective_rpc("check_health")
