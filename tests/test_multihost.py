"""Multi-host executor over loopback with mock workers (SURVEY.md §4
item 4: the reference's own topology is fully exercisable on one machine;
cf. launch.py:549 connecting over loopback)."""

import multiprocessing
import os
import time

import pytest

from tests.mock_worker import MockWorker  # noqa: F401 (import check)
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.distributed.agent import remote_main
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port


class MockedMultiHostExecutor(MultiHostExecutor):
    worker_cls = "tests.mock_worker.MockWorker"


def _agent_with_env(port, env):
    for k, v in (env or {}).items():
        os.environ[k] = v
    remote_main("127.0.0.1", port)


def _spawn_agent(port, env=None):
    proc = multiprocessing.Process(
        target=_agent_with_env, args=(port, env or {}), daemon=True
    )
    proc.start()
    return proc


@pytest.fixture
def deployment(tmp_path, monkeypatch):
    """A 2-host mocked deployment: executor (host 0) + one agent proc."""
    port = get_open_port()
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "20")
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    # Pin the advertisement so mocked boots skip the jax chip probe.
    agent = _spawn_agent(
        port,
        {"VDT_ADVERTISE_NUM_CHIPS": "4", "VDT_ADVERTISE_PLATFORM": "cpu"},
    )
    model_dir = write_llama_config(str(tmp_path / "m"))
    config = EngineArgs(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_hosts=2,
    ).create_engine_config()
    executor = MockedMultiHostExecutor(config)
    yield executor, agent
    executor.shutdown()
    if agent.is_alive():
        agent.terminate()
    agent.join(timeout=5)


def test_boot_and_lifecycle_order(deployment):
    executor, _ = deployment
    # Local + remote both ran init_device then load_model, in order.
    lifecycles = executor.collective_rpc("get_lifecycle")
    assert len(lifecycles) == 2
    for lc in lifecycles:
        assert lc == ["init_device", "load_model"]


def test_num_pages_min_aggregation(deployment):
    executor, _ = deployment
    # host0 reports 100, host1 reports 101 → min wins.
    assert executor.determine_num_pages() == 100


def test_env_replication(deployment, monkeypatch):
    executor, _ = deployment
    # VDT_EXECUTE_MODEL_TIMEOUT_SECONDS was set pre-boot and is in the
    # registry → must exist on the remote host; ranks must be 0 and 1.
    replies = executor.collective_rpc(
        "get_rank_and_env", ("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS",)
    )
    assert sorted(r[0] for r in replies) == [0, 1]
    for rank, value in replies:
        assert value == "20", f"rank {rank} missing replicated env"


def test_execute_model_replies_from_host0_only(deployment):
    executor, _ = deployment
    so = SchedulerOutput(
        step_id=0,
        num_scheduled_tokens={"r1": 1},
        total_num_scheduled_tokens=1,
    )
    out = executor.execute_model(so)
    assert out.sampled_token_ids == {"r1": [42]}
    # Fan-out to all, reply only from designated rank:
    replies = executor.collective_rpc("execute_model", (so,))
    assert replies[0] is not None and replies[1] is None


def test_pipelined_dispatch_overlaps_cross_rpc(deployment):
    """Two in-flight dispatches across RPC (VERDICT r2 weak #4): the
    remote worker must receive dispatch N+1 BEFORE fetch N completes —
    i.e. multihost steady state overlaps the DCN round trip with device
    time instead of serializing dispatch-then-resolve."""
    executor, _ = deployment

    def so(step):
        return SchedulerOutput(
            step_id=step,
            num_scheduled_tokens={f"r{step}": 1},
            total_num_scheduled_tokens=1,
        )

    t0 = time.monotonic()
    fut_a = executor.execute_model(so(0), non_block=True)
    fut_b = executor.execute_model(so(1), non_block=True)
    out_a = fut_a.result(timeout=15)
    out_b = fut_b.result(timeout=15)
    elapsed = time.monotonic() - t0
    assert out_a.sampled_token_ids == {"r0": [42]}
    assert out_b.sampled_token_ids == {"r1": [42]}

    # Both workers (local + remote) saw dispatch(1) before fetch_done(0).
    for timeline in executor.collective_rpc("get_timeline"):
        events = {(e, s): t for e, s, t in timeline}
        assert events[("dispatch", 1)] < events[("fetch_done", 0)], timeline
    # And the engine-visible latency amortizes: ~2 x step time when the
    # round trips overlap, far under the serialized 2 x (rtt + step).
    from tests.mock_worker import MOCK_STEP_SECONDS

    assert elapsed < 2 * MOCK_STEP_SECONDS + 1.0


def test_dispatch_microbench():
    """ISSUE 7 acceptance gate: on the mock-worker microbench the
    overlapped protocol must (a) produce bit-identical greedy outputs,
    (b) cut per-step dispatch time >= 5x at p50, (c) finish its wall
    under the blocking path's summed dispatch time, and (d) record zero
    steady-state stall windows."""
    from tools.dispatch_microbench import run_microbench

    report = run_microbench(batch=4, prompt_len=8, max_tokens=12)
    assert report["ok"], report


def _engine_run(tmp_path, monkeypatch, *, streams: str,
                decode_steps: int, spec_k: int = 0, seq: str = "1"):
    """Boot a full LLMEngine over the mocked 2-host deployment and run
    three staggered greedy requests to completion; returns
    req_id -> tokens."""
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    port = get_open_port()
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_STEP_STREAMS", streams)
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", seq)
    monkeypatch.setenv("VDT_MOCK_STEP_SECONDS", "0.01")
    monkeypatch.setenv("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "30")
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    agent = _spawn_agent(
        port,
        {
            "VDT_ADVERTISE_NUM_CHIPS": "4",
            "VDT_ADVERTISE_PLATFORM": "cpu",
            "VDT_MOCK_TOKEN_SEQ": seq,
            "VDT_MOCK_STEP_SECONDS": "0.01",
            "VDT_STEP_STREAMS": streams,
        },
    )
    engine = None
    try:
        engine = LLMEngine.from_engine_args(
            EngineArgs(
                model=write_llama_config(
                    str(tmp_path / f"m-{streams}-{decode_steps}-{spec_k}")
                ),
                skip_tokenizer_init=True,
                load_format="dummy",
                num_hosts=2,
                num_decode_steps=decode_steps,
                speculative_ngram_k=spec_k,
                max_model_len=512,
                distributed_executor_backend=MockedMultiHostExecutor,
            )
        )
        # Staggered prompt lengths: requests finish on different steps,
        # forcing mid-window finishes (reconciliation) and held notices.
        for i in range(3):
            engine.add_request(
                f"r{i}",
                prompt_token_ids=list(range(1, 4 + 2 * i)),
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=9 + i, ignore_eos=True
                ),
            )
        tokens: dict[str, list[int]] = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                tokens[out.request_id] = list(out.outputs[0].token_ids)
        return tokens
    finally:
        if engine is not None:
            engine.shutdown()
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)


def test_pipelined_vs_blocking_engine_outputs_bit_identical(
    tmp_path, monkeypatch
):
    """ISSUE 7: the overlapped protocol (step streams + async fused
    scheduling, two steps in flight) must be invisible in the outputs —
    greedy tokens bit-identical to the blocking per-step RPC path."""
    blocking = _engine_run(
        tmp_path, monkeypatch, streams="0", decode_steps=1
    )
    overlapped = _engine_run(
        tmp_path, monkeypatch, streams="1", decode_steps=4
    )
    # Mock seq mode: token i == absolute position, so request i
    # (prompt 3+2i, max_tokens 9+i) must be exactly this range — both
    # protocols are checked against the ORACLE, not just each other.
    expected = {
        f"r{i}": list(range(3 + 2 * i, 3 + 2 * i + 9 + i))
        for i in range(3)
    }
    assert blocking == expected
    assert overlapped == expected


def test_spec_decode_over_step_streams_bit_identical(
    tmp_path, monkeypatch
):
    """ISSUE 11: speculative verify frames (per-request drafts out,
    realized spec_advance back) over the REAL persistent step-stream
    protocol against a mocked 2-host deployment — outputs must match
    the non-speculative run and the deterministic stream oracle, and
    drafts must actually be accepted (the mirrors stayed in lockstep
    through variable-advance windows or decode would have diverged)."""
    seq = "seq:5,6,7,8"
    base = _engine_run(
        tmp_path, monkeypatch, streams="1", decode_steps=4, seq=seq
    )
    spec = _engine_run(
        tmp_path, monkeypatch, streams="1", decode_steps=4, spec_k=3,
        seq=seq,
    )
    expected = {
        f"r{i}": [
            (5, 6, 7, 8)[p % 4]
            for p in range(3 + 2 * i, 3 + 2 * i + 9 + i)
        ]
        for i in range(3)
    }
    assert base == expected
    assert spec == expected


def test_short_host_rejected(tmp_path, monkeypatch):
    """A TPU host advertising fewer chips than the deployment needs per
    host is skipped with a warning (reference: launch.py:226-231); a
    healthy agent then fills the slot and boot completes."""
    port = get_open_port()
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "20")

    # A "TPU host" with zero chips: must be rejected, never fill a slot.
    bad = _spawn_agent(
        port,
        {"VDT_ADVERTISE_NUM_CHIPS": "0", "VDT_ADVERTISE_PLATFORM": "tpu"},
    )
    good = None
    model_dir = write_llama_config(str(tmp_path / "m"))
    config = EngineArgs(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_hosts=2,
    ).create_engine_config()
    try:
        import threading

        boot: dict = {}

        def build():
            try:
                boot["executor"] = MockedMultiHostExecutor(config)
            except Exception as e:  # noqa: BLE001
                boot["error"] = e

        t = threading.Thread(target=build, daemon=True)
        t.start()
        time.sleep(3)
        # Bad agent alone must not complete boot.
        assert "executor" not in boot, "zero-chip host was accepted"
        good = _spawn_agent(
            port,
            {"VDT_ADVERTISE_NUM_CHIPS": "4", "VDT_ADVERTISE_PLATFORM": "tpu"},
        )
        t.join(timeout=30)
        assert "executor" in boot, boot.get("error")
        boot["executor"].shutdown()
    finally:
        for proc in (bad, good):
            if proc is not None and proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)


def test_real_model_two_process_world(tmp_path):
    """SURVEY §4 item 4 at full depth (VERDICT r2 weak #5): a REAL tiny
    Llama served by MultiHostExecutor + agent over loopback — real
    StreamRpcTransport, real Worker on both sides, and a real 2-process
    jax.distributed CPU world (tp=2, one device per process).  Output
    must match the single-process uniproc run bit-for-bit."""
    import subprocess
    import sys

    from tests.utils import make_tiny_llama

    model_dir = write_llama_config(str(tmp_path / "m"), heads=8, kv_heads=4)
    port = get_open_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        VDT_SERVER_PORT=str(port),
        VDT_HOST_IP="127.0.0.1",
        VDT_EXECUTE_MODEL_TIMEOUT_SECONDS="60",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    repo = env["PYTHONPATH"]
    driver = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tests", "multihost_driver.py"),
         model_dir],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # Agent output goes to a file, not a PIPE: nobody drains the pipe
    # concurrently, and XLA's chatty stderr would fill it and deadlock.
    agent_log = open(tmp_path / "agent.log", "w")
    agent = subprocess.Popen(
        [sys.executable, "-c",
         "from vllm_distributed_tpu.distributed.agent import remote_main; "
         f"remote_main('127.0.0.1', {port})"],
        env=env,
        stdout=agent_log,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        # Generous timeout: two jax processes compiling concurrently on
        # a small CI box are slow (observed ~330s on one core).
        out, _ = driver.communicate(timeout=570)
    finally:
        agent.terminate()
        if driver.poll() is None:
            driver.kill()
        agent_log.close()
    assert driver.returncode == 0, out[-4000:]
    line = [l for l in out.splitlines() if l.startswith("TOKENS=")]
    assert line, out[-4000:]
    import json as _json

    got = _json.loads(line[0][len("TOKENS="):])

    # Single-process oracle on the same dummy weights.
    from vllm_distributed_tpu.config import EngineArgs as EA
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    eng = LLMEngine.from_engine_args(
        EA(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=32,
            max_model_len=64,
            num_decode_steps=4,
        )
    )
    eng.add_request(
        "x",
        prompt_token_ids=[1, 5, 9],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=6, ignore_eos=True
        ),
    )
    want = None
    while eng.has_unfinished_requests():
        for o in eng.step():
            want = o.outputs[0].token_ids
    assert got == want, (got, want)


def test_agent_loss_fails_executor(deployment):
    executor, agent = deployment
    failed = []
    executor.register_failure_callback(lambda: failed.append(True))
    agent.terminate()
    agent.join(timeout=5)
    deadline = time.time() + 10
    while not executor.is_failed and time.time() < deadline:
        time.sleep(0.1)
    assert executor.is_failed
    assert failed == [True]
    with pytest.raises(RuntimeError, match="Executor failed"):
        executor.collective_rpc("check_health")
