"""Deployment harness contract (SURVEY.md §2 C9/L5): the .env +
`docker compose up` flow with the crash-restart loop.  docker isn't
available in CI, so the compose file is validated structurally (the
fields `docker compose config` would check) plus the env contract."""

import os
import re

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _compose():
    with open(os.path.join(REPO, "docker-compose.yml")) as f:
        return yaml.safe_load(f)


def test_compose_parses_with_service():
    doc = _compose()
    assert "vdt" in doc["services"]


def test_crash_restart_loop():
    # restart: unless-stopped + agent/executor fail-fast exits form the
    # recovery loop (reference docker-compose.yml:8; SURVEY.md §3.5).
    svc = _compose()["services"]["vdt"]
    assert svc["restart"] == "unless-stopped"


def test_command_env_contract():
    svc = _compose()["services"]["vdt"]
    assert svc["command"] == "${COMMAND}"
    # Both role files define COMMAND and agree on the harness contract.
    roles = {}
    for name in (".env.server", ".env.client"):
        text = open(os.path.join(REPO, name)).read()
        cmd = re.search(r"^COMMAND=(.+)$", text, re.M)
        assert cmd, f"{name} must set COMMAND"
        roles[name] = cmd.group(1)
    assert roles[".env.server"].startswith("serve ")
    assert roles[".env.client"].startswith("remote ")


def test_host_network_and_cache_volumes():
    svc = _compose()["services"]["vdt"]
    assert svc["network_mode"] == "host"
    vols = " ".join(svc["volumes"])
    assert "ROOT_CACHE_PATH" in vols and "/root/.cache" in vols


def test_env_commands_parse_with_cli():
    """The COMMANDs in the role files must parse with the real CLI parser
    (catches drift between the harness and the arg surface)."""
    from vllm_distributed_tpu.entrypoints.cli import make_parser

    parser = make_parser()
    for name in (".env.server", ".env.client"):
        text = open(os.path.join(REPO, name)).read()
        cmd = re.search(r"^COMMAND=(.+)$", text, re.M).group(1)
        args = parser.parse_args(cmd.split())
        assert args.command in ("serve", "remote")


def test_dockerfile_entrypoint_matches():
    text = open(os.path.join(REPO, "Dockerfile")).read()
    assert '"-m", "vllm_distributed_tpu"' in text
