"""OpenAI API server integration tests over a real AsyncLLM engine on CPU
(SURVEY.md §4 item 3: serve a tiny model and hit the OpenAI API)."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.utils import add_tiny_tokenizer, make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)


@pytest.fixture(scope="module")
def served_app(tmp_path_factory):
    """Shared engine/state; a FRESH app per call (TestServer freezes the
    app it serves, so apps are single-use)."""
    model_dir = make_tiny_llama(str(tmp_path_factory.mktemp("srv")))
    add_tiny_tokenizer(model_dir)
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            num_kv_pages=128,
            max_model_len=256,
            max_num_seqs=8,
        )
    )
    state = init_app_state(
        engine,
        served_model_name="tiny-llama",
        tool_call_parser="hermes",
    )
    yield lambda: build_app(state)
    engine.shutdown()


def _client_call(make_app, coro_fn):
    async def go():
        server = TestServer(make_app())
        client = TestClient(server)
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.new_event_loop().run_until_complete(go())


def test_health_version_models(served_app):
    async def go(client):
        r = await client.get("/health")
        assert r.status == 200
        r = await client.get("/version")
        assert "version" in await r.json()
        r = await client.get("/v1/models")
        data = await r.json()
        assert data["data"][0]["id"] == "tiny-llama"
        assert data["data"][0]["max_model_len"] == 256
        r = await client.get("/metrics")
        assert r.status == 200

    _client_call(served_app, go)


def test_tokenize_roundtrip(served_app):
    async def go(client):
        r = await client.post(
            "/tokenize", json={"prompt": "hello world the cat"}
        )
        data = await r.json()
        assert data["count"] == 4
        r = await client.post(
            "/detokenize", json={"tokens": data["tokens"]}
        )
        text = (await r.json())["prompt"]
        assert "hello" in text and "cat" in text

    _client_call(served_app, go)


def test_completions_basic(served_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": "hello world the cat sat",
                "max_tokens": 6,
                "temperature": 0,
                "ignore_eos": True,
            },
        )
        assert r.status == 200, await r.text()
        data = await r.json()
        assert data["object"] == "text_completion"
        assert data["usage"]["completion_tokens"] == 6
        assert data["choices"][0]["finish_reason"] == "length"
        assert isinstance(data["choices"][0]["text"], str)
        return data

    _client_call(served_app, go)


def test_completions_token_ids_and_n(served_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": [3, 4, 5],
                "n": 2,
                "max_tokens": 4,
                "temperature": 0,
                "ignore_eos": True,
            },
        )
        data = await r.json()
        assert len(data["choices"]) == 2
        # Greedy: both samples identical.
        assert data["choices"][0]["text"] == data["choices"][1]["text"]

    _client_call(served_app, go)


def test_completions_streaming(served_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": "hello world",
                "max_tokens": 5,
                "temperature": 0,
                "ignore_eos": True,
                "stream": True,
            },
        )
        assert r.headers["Content-Type"].startswith("text/event-stream")
        body = await r.text()
        events = [
            line[len("data: ") :]
            for line in body.splitlines()
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        finals = [json.loads(e) for e in events[:-1]]
        assert any(
            c["finish_reason"] == "length"
            for e in finals
            for c in e["choices"]
        )

    _client_call(served_app, go)


def test_chat_completions_and_streaming(served_app):
    async def go(client):
        r = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [
                    {"role": "system", "content": "the cat"},
                    {"role": "user", "content": "hello world"},
                ],
                "max_tokens": 5,
                "temperature": 0,
                "ignore_eos": True,
            },
        )
        assert r.status == 200, await r.text()
        data = await r.json()
        assert data["object"] == "chat.completion"
        msg = data["choices"][0]["message"]
        assert msg["role"] == "assistant"
        non_stream_text = msg["content"]

        r = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [
                    {"role": "system", "content": "the cat"},
                    {"role": "user", "content": "hello world"},
                ],
                "max_tokens": 5,
                "temperature": 0,
                "ignore_eos": True,
                "stream": True,
                "stream_options": {"include_usage": True},
            },
        )
        body = await r.text()
        events = [
            line[len("data: ") :]
            for line in body.splitlines()
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        streamed = "".join(
            c["choices"][0]["delta"].get("content") or ""
            for c in chunks
            if c["choices"]
        )
        assert streamed == non_stream_text
        usage_chunks = [c for c in chunks if c.get("usage")]
        assert usage_chunks and usage_chunks[-1]["usage"]["completion_tokens"] == 5

    _client_call(served_app, go)


def test_prompt_too_long_rejected(served_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={"prompt": [3] * 300, "max_tokens": 4},
        )
        assert r.status == 400
        assert "max_model_len" in (await r.json())["message"]

    _client_call(served_app, go)


def test_stop_string(served_app):
    async def go(client):
        # Find what greedy produces, then stop on its first word.
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": "hello world the cat sat",
                "max_tokens": 8,
                "temperature": 0,
                "ignore_eos": True,
            },
        )
        full = (await r.json())["choices"][0]["text"]
        first_word = full.split()[0] if full.split() else None
        if first_word is None:
            return
        r = await client.post(
            "/v1/completions",
            json={
                "prompt": "hello world the cat sat",
                "max_tokens": 8,
                "temperature": 0,
                "ignore_eos": True,
                "stop": [first_word],
            },
        )
        data = await r.json()
        assert data["choices"][0]["finish_reason"] == "stop"
        assert first_word not in data["choices"][0]["text"]

    _client_call(served_app, go)


def test_chat_streaming_tool_call_deltas(served_app, monkeypatch):
    """Tool-call fragments must stream in SSE chunks AS the text
    arrives (VERDICT r4 missing #3), not only after the request
    finishes: with a stubbed generation that emits qwen3_coder tool
    syntax across several outputs, tool_calls deltas appear in chunks
    BEFORE the final one, and the reassembled arguments match."""
    from vllm_distributed_tpu.entrypoints.openai import api_server
    from vllm_distributed_tpu.outputs import (
        CompletionOutput,
        RequestOutput,
    )

    pieces = [
        "checking ",
        "<tool_call>\n<function=get_weather>\n",
        "<parameter=city>SF</parameter>\n",
        "</function>\n</tool_call>",
    ]

    async def fake_generate(request_id, **kw):
        text = ""
        for j, piece in enumerate(pieces):
            text += piece
            finished = j == len(pieces) - 1
            yield RequestOutput(
                request_id=request_id,
                prompt=None,
                prompt_token_ids=[1, 2],
                outputs=[
                    CompletionOutput(
                        index=0,
                        text=text,
                        token_ids=list(range(j + 1)),
                        finish_reason="stop" if finished else None,
                    )
                ],
                finished=finished,
            )

    async def go(client):
        state = client.server.app["state"]
        monkeypatch.setattr(state, "tool_call_parser", "qwen3_coder")
        monkeypatch.setattr(state, "enable_auto_tool_choice", True)
        monkeypatch.setattr(
            type(state.engine), "generate", lambda self, rid, **kw:
            fake_generate(rid, **kw),
        )
        r = await client.post(
            "/v1/chat/completions",
            json={
                "model": "tiny-llama",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
                "max_tokens": 8,
            },
        )
        assert r.status == 200
        chunks = []
        async for line in r.content:
            line = line.decode().strip()
            if line.startswith("data:") and line[5:].strip() != "[DONE]":
                chunks.append(json.loads(line[5:]))
        return chunks

    chunks = _client_call(served_app, go)
    tool_chunks = [
        (n, c)
        for n, c in enumerate(chunks)
        if c["choices"] and c["choices"][0]["delta"].get("tool_calls")
    ]
    assert tool_chunks, chunks
    # Fragments arrived before the final chunk (true streaming).
    assert tool_chunks[0][0] < len(chunks) - 1
    args = ""
    name = None
    for _, c in tool_chunks:
        for frag in c["choices"][0]["delta"]["tool_calls"]:
            fn = frag.get("function", {})
            name = fn.get("name", name)
            args += fn.get("arguments", "")
    assert name == "get_weather"
    assert json.loads(args) == {"city": "SF"}
    finals = [
        c for c in chunks
        if c["choices"] and c["choices"][0].get("finish_reason")
    ]
    assert finals and finals[-1]["choices"][0]["finish_reason"] == (
        "tool_calls"
    )
