"""Code-hygiene AST lints.

- ISSUE 2 satellite: the distributed/ package is the layer whose job is
  failure DETECTION, so broad exception-swallowing there hides exactly
  the signals the fault-tolerance layer exists to surface.  Fails on any
  new ``except Exception: pass`` / bare ``except: pass`` block in
  ``vllm_distributed_tpu/distributed/`` — swallowed teardown errors must
  at least be logged at debug (see rpc_transport close()).
- ISSUE 5 satellite: every span opened in ``vllm_distributed_tpu/`` must
  use the context-manager form (``with tracer.span(...)``) — a manual
  ``start_span`` call outside a ``with`` item or a try/finally that
  ``.end()``s it is orphanable (the span leaks open if the code between
  open and close raises).
"""

import ast
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "vllm_distributed_tpu"
DISTRIBUTED = PACKAGE / "distributed"

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def test_no_silent_broad_except_in_distributed():
    offenders = []
    for path in sorted(DISTRIBUTED.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                offenders.append(f"{path.name}:{node.lineno}")
    assert not offenders, (
        "silent broad except blocks in distributed/ (log at debug "
        f"instead of swallowing): {offenders}"
    )


def _calls_named(node: ast.AST, name: str):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            callee = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else getattr(fn, "id", None)
            )
            if callee == name:
                yield sub


def _guarded_start_spans(tree: ast.AST) -> set[int]:
    """start_span calls that cannot leak open: used as a `with` item, or
    assigned immediately before a try whose finally calls .end()."""
    ok: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for call in _calls_named(item.context_expr, "start_span"):
                    ok.add(id(call))
        body = getattr(node, "body", None)
        if not isinstance(body, list):
            continue
        for stmt, nxt in zip(body, body[1:]):
            if not (
                isinstance(stmt, (ast.Assign, ast.AnnAssign))
                and isinstance(nxt, ast.Try)
                and nxt.finalbody
            ):
                continue
            if any(
                True
                for fin in nxt.finalbody
                for _ in _calls_named(fin, "end")
            ):
                for call in _calls_named(stmt, "start_span"):
                    ok.add(id(call))
    return ok


def test_spans_use_context_manager_form():
    """ISSUE 5 satellite: no orphanable manual start_span anywhere in
    the package — use `with tracer.span(...)` (or try/finally + .end())
    so a raise between open and close can never leak an open span."""
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        guarded = _guarded_start_spans(tree)
        for call in _calls_named(tree, "start_span"):
            # The definition site (tracing.py's `start_span = span`
            # alias) is an assignment, not a call, so it never trips.
            if id(call) not in guarded:
                offenders.append(
                    f"{path.relative_to(PACKAGE)}:{call.lineno}"
                )
    assert not offenders, (
        "manual start_span without with/try-finally (orphanable open "
        f"span): {offenders}"
    )
