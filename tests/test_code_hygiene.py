"""Tier-1 lint gate: a thin driver over tools/vdt_lint (ISSUE 6).

The two original AST checks that lived here (silent broad excepts in
distributed/ — ISSUE 2 satellite; orphanable manual start_span —
ISSUE 5 satellite) are now VDT006/VDT007 in the framework, alongside
five more checkers encoding the engine's concurrency, registry, and
failure-handling invariants.  This file just runs the whole catalog
over the package — one shared parse pass per file — and fails on any
new unwaived, un-baselined finding, printing rule id and file:line.

Checker unit tests (fixture corpus, waiver/baseline round-trips, CLI)
live in tests/test_vdt_lint.py.
"""

import pytest

from tools.vdt_lint import (
    DEFAULT_BASELINE_PATH,
    load_baseline,
    run_lint,
)

pytestmark = pytest.mark.lint


def test_package_has_no_new_findings():
    report = run_lint()
    assert not report.new, (
        "new vdt-lint findings (fix, or waive at the site with "
        "`# vdt-lint: disable=<rule>` plus a justification):\n"
        + "\n".join(f.render() for f in report.new)
    )


def test_control_plane_carries_no_baseline_debt():
    """ISSUE 6 satellite (extended by ISSUE 7 to worker/ and ISSUE 10
    to router/): the committed baseline must stay empty for
    distributed/, executor/, worker/, and router/ — control-plane and
    run-loop findings are fixed or waived with a justification at the
    site, never grandfathered."""
    entries = load_baseline(DEFAULT_BASELINE_PATH)
    offenders = [
        e
        for e in entries
        if "/distributed/" in e.get("path", "")
        or "/executor/" in e.get("path", "")
        or "/worker/" in e.get("path", "")
        or "/router/" in e.get("path", "")
    ]
    assert not offenders, offenders
