"""Control-plane code hygiene (ISSUE 2 satellite): the distributed/
package is the layer whose job is failure DETECTION, so broad
exception-swallowing there hides exactly the signals the fault-tolerance
layer exists to surface.  This AST lint fails on any new
``except Exception: pass`` / bare ``except: pass`` block in
``vllm_distributed_tpu/distributed/`` — swallowed teardown errors must
at least be logged at debug (see rpc_transport close()).
"""

import ast
from pathlib import Path

DISTRIBUTED = (
    Path(__file__).resolve().parent.parent
    / "vllm_distributed_tpu"
    / "distributed"
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def test_no_silent_broad_except_in_distributed():
    offenders = []
    for path in sorted(DISTRIBUTED.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                offenders.append(f"{path.name}:{node.lineno}")
    assert not offenders, (
        "silent broad except blocks in distributed/ (log at debug "
        f"instead of swallowing): {offenders}"
    )
