"""TP/DP sharding on the 8-device virtual CPU mesh (SURVEY.md §4 item 4):
sharded execution must be bit-compatible with single-device greedy."""

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [[1, 5, 9, 23, 77, 41, 3], [7, 2, 88, 14], [100, 3, 9]]


@pytest.fixture(scope="module")
def tiny_llama(tmp_path_factory):
    # heads=8 / kv_heads=4 so tp up to 4 divides both.
    return make_tiny_llama(
        str(tmp_path_factory.mktemp("llama_shard")), heads=8, kv_heads=4
    )


def _greedy(
    model_dir, tp=1, dp=1, env=None, quantization=None, kv_cache_dtype="auto"
):
    import os
    from unittest import mock

    with mock.patch.dict(os.environ, env or {}):
        return _greedy_inner(model_dir, tp, dp, quantization, kv_cache_dtype)


def _greedy_inner(
    model_dir, tp=1, dp=1, quantization=None, kv_cache_dtype="auto"
):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=256,
            tensor_parallel_size=tp,
            data_parallel_size=dp,
            quantization=quantization,
            kv_cache_dtype=kv_cache_dtype,
        )
    )
    for i, p in enumerate(PROMPTS):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=6, ignore_eos=True
            ),
        )
    done = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out.outputs[0].token_ids
    return [done[f"r{i}"] for i in range(len(PROMPTS))]


@pytest.fixture(scope="module")
def baseline(tiny_llama):
    return _greedy(tiny_llama, tp=1)


def test_tp4_matches_single_device(tiny_llama, baseline):
    assert _greedy(tiny_llama, tp=4) == baseline


def test_tp2_dp2_matches_single_device(tiny_llama, baseline):
    assert _greedy(tiny_llama, tp=2, dp=2) == baseline


def test_tp4_pallas_matches_single_device(tiny_llama, baseline):
    """The PRODUCTION kernel path (interpret-mode Pallas attention +
    in-place KV writer) under shard_map on a real tp=4 mesh must be
    bit-identical to single-device greedy — the partitioning the real
    chip mesh relies on (GSPMD cannot partition the custom calls)."""
    assert (
        _greedy(
            tiny_llama, tp=4, env={"VDT_USE_PALLAS": "pallas_interpret"}
        )
        == baseline
    )


def test_pallas_dp_rejected(tiny_llama):
    """dp>1 would diverge the replicated KV pool under per-shard in-place
    writes; the runner must refuse loudly."""
    with pytest.raises(Exception, match="dp>1"):
        _greedy(
            tiny_llama,
            tp=2,
            dp=2,
            env={"VDT_USE_PALLAS": "pallas_interpret"},
        )


def test_pallas_dp_rejected_at_tp1(tiny_llama):
    """tp=1 must not bypass the dp rejection (the kernels would run
    unwrapped under a dp-sharded GSPMD mesh)."""
    with pytest.raises(Exception, match="dp>1"):
        _greedy(
            tiny_llama,
            tp=1,
            dp=2,
            env={"VDT_USE_PALLAS": "pallas_interpret"},
        )


def test_tp8_rejected_when_kv_heads_insufficient(tiny_llama):
    # kv_heads=4 cannot shard 8 ways; the mesh builds but XLA sharding of
    # the KV cache must fail loudly, not silently misshard.  (tp=8 also
    # equals the device count, so this documents the boundary.)
    with pytest.raises(Exception):
        _greedy(tiny_llama, tp=8)


def test_tp4_int8_kv_cache_matches_single_device(tiny_llama):
    """Quantized KV pool under tp=4 shard_map: per-shard per-head
    quantization at flush is the SAME reduction as single-device
    (scales are per kv head and heads shard whole), so greedy tokens
    must be bit-identical to the single-device int8-KV run."""
    env = {"VDT_USE_PALLAS": "pallas_interpret"}
    single = _greedy(tiny_llama, tp=1, env=env, kv_cache_dtype="int8")
    assert (
        _greedy(tiny_llama, tp=4, env=env, kv_cache_dtype="int8")
        == single
    )


def test_tp4_int8_pallas_matches_single_device(tiny_llama):
    """Sharded int8 weight streaming (VERDICT r3 #5): the Pallas int8
    matmul under shard_map at tp=4 must be bit-identical to the
    single-device int8 Pallas path (per-shard streaming changes neither
    quantization grouping nor accumulation order per output column)."""
    env = {"VDT_USE_PALLAS": "pallas_interpret"}
    single = _greedy(tiny_llama, tp=1, env=env, quantization="int8")
    assert (
        _greedy(tiny_llama, tp=4, env=env, quantization="int8") == single
    )
