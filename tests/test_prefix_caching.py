"""Prefix caching: allocator unit tests + cached-vs-cold engine parity.

The adversarial bar (ISSUE 1): with --enable-prefix-caching the engine's
greedy outputs must be BIT-IDENTICAL to a cold engine for the same
prompts, including under eviction pressure on a tiny page pool.
"""

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.block_manager import (
    NoFreePagesError,
    PageAllocator,
    PrefixCachingAllocator,
    hash_page_tokens,
)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.sampling_params import SamplingParams

PS = 4  # page size for the unit tests


def make_req(rid, tokens):
    return Request(
        request_id=rid,
        prompt_token_ids=list(tokens),
        sampling_params=SamplingParams(),
    )


def computed(alloc, rid, tokens):
    """Allocate + mark every token computed + register full pages."""
    req = make_req(rid, tokens)
    alloc.allocate(req, len(tokens))
    req.num_computed_tokens = len(tokens)
    alloc.register_computed(req)
    return req


_query_seq = iter(range(10**6))


def query(alloc, tokens):
    """Query the cache for a fresh request with this prompt."""
    return alloc.query_prefix(make_req(f"q{next(_query_seq)}", tokens))


# ---- allocator unit tests ----
def test_refcount_shared_pages_survive_one_free():
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    prompt = list(range(1, 9))  # 2 full pages
    r1 = computed(alloc, "r1", prompt)
    shared = list(r1.page_ids)
    alloc.free(r1)

    hit, pages = query(alloc, prompt + [50])
    assert hit == 8 and pages == shared
    r2 = make_req("r2", prompt + [50])
    alloc.attach_prefix(r2, pages)
    r2.num_computed_tokens = hit
    r3 = make_req("r3", prompt + [60])
    alloc.attach_prefix(r3, pages)
    r3.num_computed_tokens = hit
    assert r2.page_ids == shared and r3.page_ids == shared

    # Free one sharer: the pages must survive for the other.
    alloc.free(r2)
    assert r3.page_ids == shared
    # They are NOT reusable garbage: exhaust the plain free list and the
    # shared pages must never be handed out.
    grabbed = []
    while True:
        r = make_req(f"g{len(grabbed)}", [1])
        try:
            grabbed.extend(alloc.allocate(r, 1))
        except NoFreePagesError:
            break
    assert not set(shared) & set(grabbed)
    # Free the last owner: now they become evictable (and allocatable).
    alloc.free(r3)
    r = make_req("last", list(range(8)))
    got = alloc.allocate(r, 8)
    assert set(got) == set(shared)


def test_lru_eviction_order():
    alloc = PrefixCachingAllocator(num_pages=9, page_size=PS)  # 8 usable
    a = computed(alloc, "a", [1, 2, 3, 4])
    b = computed(alloc, "b", [5, 6, 7, 8])
    page_a, page_b = a.page_ids[0], b.page_ids[0]
    alloc.free(a)  # freed first -> least recently used
    alloc.free(b)
    assert alloc.num_free_pages == 8
    # Drain the 6 plain-free pages; the next two allocations must evict
    # a's page before b's.
    filler = make_req("f", list(range(6 * PS)))
    alloc.allocate(filler, 6 * PS)
    first = alloc.allocate(make_req("x", [9]), 1)
    second = alloc.allocate(make_req("y", [9]), 1)
    assert first == [page_a]
    assert second == [page_b]
    # Both registrations are gone.
    assert query(alloc, [1, 2, 3, 4, 90]) == (0, [])
    assert query(alloc, [5, 6, 7, 8, 90]) == (0, [])


def test_lru_refreshes_on_reuse():
    alloc = PrefixCachingAllocator(num_pages=9, page_size=PS)
    a = computed(alloc, "a", [1, 2, 3, 4])
    b = computed(alloc, "b", [5, 6, 7, 8])
    page_a = a.page_ids[0]
    alloc.free(a)
    alloc.free(b)
    # Touch a's page: re-attach and free again -> now most recent.
    _, pages = query(alloc, [1, 2, 3, 4, 9])
    r = make_req("r", [1, 2, 3, 4, 9])
    alloc.attach_prefix(r, pages)
    r.num_computed_tokens = 4
    alloc.free(r)
    filler = make_req("f", list(range(6 * PS)))
    alloc.allocate(filler, 6 * PS)
    # b's page (now LRU) is evicted first.
    assert alloc.allocate(make_req("x", [9]), 1) != [page_a]
    assert alloc.allocate(make_req("y", [9]), 1) == [page_a]


def test_hash_chain_keying_no_cross_parent_collision():
    # Same page content under different parents must NOT collide.
    assert hash_page_tokens(b"", [7, 7, 7, 7]) != hash_page_tokens(
        hash_page_tokens(b"", [1, 2, 3, 4]), [7, 7, 7, 7]
    )
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    r = computed(alloc, "r", [1, 2, 3, 4, 7, 7, 7, 7])
    alloc.free(r)
    # Identical second page under a different first page: no hit beyond
    # page granularity, and crucially no FALSE hit on page 2's content.
    assert query(alloc, [9, 9, 9, 9, 7, 7, 7, 7]) == (0, [])
    # The true chain hits both pages.
    hit, pages = query(alloc, [1, 2, 3, 4, 7, 7, 7, 7, 9])
    assert hit == 8 and len(pages) == 2


def test_partial_page_never_matches():
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    r = computed(alloc, "r", [1, 2, 3, 4, 5, 6])  # page 2 only half full
    alloc.free(r)
    hit, pages = query(alloc, [1, 2, 3, 4, 5, 6, 8, 8])
    assert hit == 4 and len(pages) == 1  # full page only
    # A prompt shorter than one page can never hit.
    assert query(alloc, [1, 2, 3]) == (0, [])


def test_full_prompt_hit_drops_tail_page():
    """A fully cached prompt recomputes its whole last page into a fresh
    page: logits need at least one computed token, and a shared page must
    never be written (KV recompute is not bit-stable across shapes)."""
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    r = computed(alloc, "r", prompt)
    alloc.free(r)
    hit, pages = query(alloc, prompt)
    assert hit == len(prompt) - PS and len(pages) == 1
    # Single fully-cached page: no usable hit at all.
    assert query(alloc, [1, 2, 3, 4]) == (0, [])


def test_register_computed_is_incremental_and_dedups():
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    req = make_req("r", list(range(1, 9)))
    alloc.allocate(req, 8)
    req.num_computed_tokens = 4  # only page 0 computed so far
    alloc.register_computed(req)
    assert query(alloc, list(range(1, 6))) == (
        4,
        [req.page_ids[0]],
    )
    req.num_computed_tokens = 8
    alloc.register_computed(req)
    # A second request computing the same content does not re-register.
    dup = computed(alloc, "dup", list(range(1, 9)))
    hit, pages = query(alloc, list(range(1, 9)) + [9])
    assert pages == req.page_ids
    assert set(dup.page_ids).isdisjoint(pages)


def test_register_ignores_computed_overrun():
    """Early stop in a fused-decode dispatch advances num_computed_tokens
    past the surviving token list; pages past the real tokens must not be
    registered under truncated-slice hashes."""
    alloc = PrefixCachingAllocator(num_pages=16, page_size=PS)
    req = make_req("r", [1, 2, 3, 4, 5])  # 5 real tokens
    alloc.allocate(req, 5 + 7)  # room for the discarded tail
    req.num_computed_tokens = 12  # overran: tail tokens were discarded
    alloc.register_computed(req)
    assert query(alloc, [1, 2, 3, 4, 9]) == (4, [req.page_ids[0]])
    # Page 1 (tokens 4..7, only token 4 real) stayed unregistered: it
    # returns to the plain free list, not the LRU.
    alloc.free(req)
    assert len(alloc._lru) == 1


def test_allocate_rollback_under_true_exhaustion():
    alloc = PrefixCachingAllocator(num_pages=4, page_size=PS)  # 3 usable
    r1 = computed(alloc, "r1", list(range(2 * PS)))
    with pytest.raises(NoFreePagesError):
        alloc.allocate(make_req("r2", list(range(3 * PS))), 3 * PS)
    assert alloc.num_free_pages == 1
    # Cached pages count as free and get evicted when needed.
    alloc.free(r1)
    assert alloc.num_free_pages == 3
    r3 = make_req("r3", list(range(3 * PS)))
    assert len(alloc.allocate(r3, 3 * PS)) == 3


def test_flag_off_uses_seed_allocator():
    from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
    from vllm_distributed_tpu.engine.block_manager import (
        RadixPrefixCachingAllocator,
    )
    from vllm_distributed_tpu.engine.scheduler import Scheduler

    sched = Scheduler(SchedulerConfig(), CacheConfig(), num_pages=64)
    assert type(sched.allocator) is PageAllocator
    # The radix index (ISSUE 14) is the prefix-caching default; the
    # PR 1 hash-chain stays reachable as the "flat" ablation baseline.
    on = Scheduler(
        SchedulerConfig(),
        CacheConfig(enable_prefix_caching=True),
        num_pages=64,
    )
    assert type(on.allocator) is RadixPrefixCachingAllocator
    flat = Scheduler(
        SchedulerConfig(),
        CacheConfig(enable_prefix_caching=True, prefix_cache_index="flat"),
        num_pages=64,
    )
    assert type(flat.allocator) is PrefixCachingAllocator


# ---- engine-level parity (adversarial) ----
@pytest.fixture(scope="module")
def tiny_llama(tmp_path_factory):
    return make_tiny_llama(str(tmp_path_factory.mktemp("llama_pc")))


def _make_engine(model_dir, **kw):
    defaults = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        num_kv_pages=128,
        page_size=16,
        max_num_seqs=8,
        max_model_len=256,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


def _run_greedy(engine, prompts, max_tokens=8, tag="r"):
    for i, p in enumerate(prompts):
        engine.add_request(
            f"{tag}{i}",
            prompt_token_ids=p,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    done = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
    return [
        done[f"{tag}{i}"].outputs[0].token_ids for i in range(len(prompts))
    ]


def test_cached_outputs_bit_identical_to_cold(tiny_llama):
    shared = list(range(1, 33))  # two full shared pages
    prompts = [
        shared + [40 + i, 41, 42 + i, 43, 44 + i] for i in range(4)
    ] + [shared[:20]]
    cold = _run_greedy(_make_engine(tiny_llama), prompts)
    cached_engine = _make_engine(tiny_llama, enable_prefix_caching=True)
    round1 = _run_greedy(cached_engine, prompts, tag="a")
    round2 = _run_greedy(cached_engine, prompts, tag="b")
    assert round1 == cold  # flag on, cache cold: seed behaviour
    assert round2 == cold  # cache warm: bit-identical reuse
    sched = cached_engine.scheduler
    assert sched.prefix_cache_hits > 0
    assert sched.prefix_cache_queries >= sched.prefix_cache_hits
    # Hit rate is visible through /metrics (acceptance criterion).
    rendered = cached_engine.metrics.render().decode()
    assert "vllm:prefix_cache_queries_total" in rendered
    hits = [
        float(ln.rsplit(" ", 1)[1])
        for ln in rendered.splitlines()
        if ln.startswith("vllm:prefix_cache_hits_total")
    ]
    # Per-tier series (ISSUE 14) sum to the scheduler's total.
    assert hits and sum(hits) == float(sched.prefix_cache_hits)


def test_cached_outputs_identical_under_eviction_pressure(tiny_llama):
    """Tiny page pool: eviction and preemption churn the cache while
    requests repeat; outputs must still match the unconstrained cold
    engine bit-for-bit."""
    prompts = [
        list(range(1, 20)),
        list(range(1, 17)) + [60, 61, 62],
        list(range(20, 40)),
        list(range(1, 20)),
    ]
    cold = _run_greedy(_make_engine(tiny_llama), prompts, max_tokens=6)
    poor = _make_engine(
        tiny_llama,
        enable_prefix_caching=True,
        num_kv_pages=8,
        page_size=16,
    )
    for rnd in range(3):
        got = _run_greedy(poor, prompts, max_tokens=6, tag=f"e{rnd}")
        assert got == cold, f"round {rnd} diverged under eviction"


def test_multi_turn_reuses_generated_tokens(tiny_llama):
    """Chat pattern: turn 2's prompt extends turn 1's prompt+completion,
    so pages containing GENERATED tokens are reused too."""
    engine = _make_engine(tiny_llama, enable_prefix_caching=True)
    turn1 = list(range(1, 30))
    out1 = _run_greedy(engine, [turn1], max_tokens=8, tag="t1")[0]
    turn2 = turn1 + list(out1) + [50, 51, 52]
    hits_before = engine.scheduler.prefix_cache_hits
    out2 = _run_greedy(engine, [turn2], max_tokens=8, tag="t2")[0]
    hit = engine.scheduler.prefix_cache_hits - hits_before
    assert hit >= 32  # beyond turn1's 29 prompt tokens -> generated KV
    cold = _run_greedy(_make_engine(tiny_llama), [turn2], max_tokens=8)[0]
    assert out2 == cold
