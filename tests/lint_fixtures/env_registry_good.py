"""Negative corpus for VDT004: registry reads and non-VDT vars."""

import os

from vllm_distributed_tpu import envs

level = envs.VDT_LOG_LEVEL
home = os.environ.get("HF_HOME", "")
path = os.environ["PATH"]
# Writes (env replication onto a worker) are not reads.
os.environ["VDT_TRACING"] = "1"
