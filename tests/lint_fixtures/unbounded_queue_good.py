"""Negative corpus for VDT008: explicit bounds and waived sites."""

import asyncio
import collections
import queue
from collections import deque
from queue import Queue, SimpleQueue

DEPTH = 8


class Intake:
    def __init__(self):
        self.q = queue.Queue(maxsize=DEPTH)
        self.q2 = Queue(DEPTH)
        self.aq = asyncio.Queue(maxsize=16)
        self.window = deque(maxlen=32)
        self.window2 = collections.deque([1, 2], 4)
        # vdt-lint: disable=unbounded-queue — producers bounded by admission caps
        self.waived = SimpleQueue()


class RouterResumeFanIn:
    # The ISSUE 10 router pattern done right: a bounded frame queue
    # backpressures the per-choice resume pumps when the client reads
    # slowly.
    def __init__(self):
        self.frames = asyncio.Queue(maxsize=64)


class KVTransferInbox:
    # The ISSUE 15 transfer pattern done right: a bounded chunk buffer
    # backpressures the sending replica when the local scatter lags.
    def __init__(self):
        self.chunks = asyncio.Queue(maxsize=8)
        self.pending_imports = deque(maxlen=64)
