"""VDT011 negative corpus: registered-kind emissions, waived legacy
rings, and appends that are not event rings.  Parsed, never imported."""

from collections import deque


class RegisteredKinds:
    def __init__(self, log, sentinel):
        self.log = log
        self.sentinel = sentinel

    def shed(self, n):
        # Literal kind registered in engine/sentinel.py EVENT_KINDS.
        self.log.emit("qos_shed", count=n)

    def breaker(self, rid, state):
        self.sentinel.emit("breaker_transition", replica_id=rid, state=state)

    def dynamic(self, kind, **attrs):
        # Dynamic kinds defer to SentinelLog.emit's runtime check.
        self.log.emit(kind, **attrs)


class WaivedLegacyRing:
    def __init__(self):
        self.events = deque(maxlen=128)

    def record(self, kind, detail):
        # vdt-lint: disable=sentinel-emitter — legacy ring mirrored into the sentinel by the caller
        self.events.append((kind, detail))


class NotAnEventRing:
    def __init__(self):
        self.samples = deque(maxlen=64)
        self.pending = []

    def observe(self, value):
        # Plain data buffers are not timeline rings.
        self.samples.append(value)
        self.pending.append(value)


def emitter_helper(emitter):
    # .emit on a receiver that is not a sentinel log / timeline.
    return emitter.emit("whatever_signal_name")
