"""Positive corpus for VDT007 orphan-span."""


def orphan(tracer, work):
    span = tracer.start_span("stage")  # EXPECT
    work()
    span.end()


def no_finally(tracer, work):
    span = tracer.start_span("stage")  # EXPECT
    try:
        work()
    except ValueError:
        span.end()


def finally_without_end(tracer, work):
    span = tracer.start_span("stage")  # EXPECT
    try:
        work()
    finally:
        work()
