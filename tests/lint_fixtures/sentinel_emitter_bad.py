"""VDT011 positive corpus: ad-hoc event-ring appends and unregistered
timeline kinds.  Parsed, never imported."""

from collections import deque


class AdHocRing:
    def __init__(self):
        self.events = deque(maxlen=128)
        self._audit_events = deque(maxlen=64)

    def record(self, kind, **detail):
        self.events.append({"kind": kind, **detail})  # EXPECT

    def audit(self, entry):
        self._audit_events.append(entry)  # EXPECT


class BadKinds:
    def __init__(self, log):
        self.log = log
        self.sentinel = None

    def note(self):
        self.log.emit("totally_made_up_kind", answer=42)  # EXPECT

    def warn(self, events):
        events.emit("another_unregistered_kind")  # EXPECT

    def flag(self):
        self.sentinel.emit("misspelled_qos_shedd", count=1)  # EXPECT
