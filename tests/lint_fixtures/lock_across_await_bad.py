"""Positive corpus for VDT002 lock-across-await."""

import asyncio
import threading

_lock = threading.Lock()


async def critical(peer):
    with _lock:  # EXPECT
        await peer.call()


class Guarded:
    def __init__(self):
        self._state_lock = threading.Lock()

    async def update(self):
        with self._state_lock:  # EXPECT
            await asyncio.sleep(0.1)


async def inline_constructor():
    with threading.RLock():  # EXPECT
        await asyncio.sleep(0)


async def suspends_in_async_for(stream):
    with _lock:  # EXPECT
        async for _ in stream:
            pass


async def suspends_in_async_with(peer):
    with _lock:  # EXPECT
        async with peer:
            pass
