"""Negative corpus for VDT009 bounded-cardinality: every label value
here is drawn from a bounded space (enum-like reasons, sanitized class
names, host ranks, replica identities)."""


class Metrics:
    def __init__(self, counter, gauge, model_name):
        self.counter = counter
        self.gauge = gauge
        self._model_name = model_name

    def record(self, reason, slo_class, host_rank, replica_id, kind):
        self.counter.labels(
            model_name=self._model_name, reason=reason
        ).inc()
        # slo_class is sanitized + capped by engine/slo.py — bounded.
        self.counter.labels(slo_class=slo_class).inc()
        self.gauge.labels(host_rank=str(host_rank)).set(1)
        self.gauge.labels(replica_id=replica_id).set(1)
        self.counter.labels(kind=kind).inc()
        label = {"model_name": self._model_name}
        self.counter.labels(**label).inc()

    def record_qos(self, registry, slo_class, direction):
        # ISSUE 16: QoS series key on the REGISTRY-RESOLVED class name
        # — bounded by MAX_CLASSES whatever strings requests carry.
        name = registry.resolve(slo_class).name
        self.counter.labels(qos_class=name).inc()
        self.gauge.labels(direction=direction, reason="goodput").set(1)

    def not_a_metric(self, request_id):
        # .labels() is the only surface the rule watches; other calls
        # may mention request ids freely (logs, journals, traces).
        return {"request_id": request_id}
