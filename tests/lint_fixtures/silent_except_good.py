"""Negative corpus for VDT006: narrow excepts may pass; broad ones
must at least log."""

import logging

logger = logging.getLogger(__name__)


def teardown(x):
    try:
        x.close()
    except OSError:
        pass  # narrow: fine
    try:
        x.flush()
    except Exception as e:  # noqa: BLE001
        logger.debug("teardown flush failed: %s", e)
