"""VDT010 positive corpus: raw session HTTP calls in router/ that
bypass the resilience wrapper.  Parsed, never imported."""


async def unary(state, url):
    async with state.session.get(url) as resp:  # EXPECT
        return await resp.json()


async def post_json(state, url, payload):
    resp = await state.session.post(url, json=payload)  # EXPECT
    return resp.status


class Probe:
    async def health(self, url, timeout):
        return await self.session.request("GET", url, timeout=timeout)  # EXPECT


async def websocket(session, url):
    return await session.ws_connect(url)  # EXPECT


async def private_session(self, url):
    return await self._kv_session.put(url, data=b"")  # EXPECT
