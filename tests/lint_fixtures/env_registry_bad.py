"""Positive corpus for VDT004 env-registry (per-file half)."""

import os

level = os.environ.get("VDT_LOG_LEVEL", "INFO")  # EXPECT
port = os.getenv("VDT_SERVER_PORT")  # EXPECT
ip = os.environ["VDT_HOST_IP"]  # EXPECT
