"""Negative corpus for VDT001: the sanctioned patterns."""

import asyncio
import time


def blocking_helper(path):
    # Sync helpers may block: they run on executor threads.
    time.sleep(1)
    return open(path).read()


async def handler(path):
    await asyncio.sleep(1)
    loop = asyncio.get_running_loop()
    # The blocking call is handed to a pool, not made on the loop.
    return await loop.run_in_executor(None, blocking_helper, path)


async def nested_sync_def_is_exempt(path):
    def inner():
        return open(path).read()

    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, inner)
