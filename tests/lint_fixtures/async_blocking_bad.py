"""Positive corpus for VDT001 async-blocking (never imported, only
parsed).  Lines that must be flagged carry the EXPECT marker."""

import socket
import time


async def handler(fut, conn, path):
    time.sleep(1)  # EXPECT
    sock = socket.create_connection(("host", 80))  # EXPECT
    fut.result(timeout=5)  # EXPECT
    conn.send_bytes(b"x")  # EXPECT
    data = open(path).read()  # EXPECT
    text = path.read_text()  # EXPECT
    return sock, data, text
