"""Negative corpus for VDT005: daemons, joined threads, late daemon=,
and reaped child processes."""

import multiprocessing
import subprocess
import threading


def work():
    pass


class Owner:
    def start(self):
        self._daemon = threading.Thread(target=work, daemon=True)
        self._daemon.start()
        self._joined = threading.Thread(target=work)
        self._joined.start()
        self._late = threading.Thread(target=work)
        self._late.daemon = True
        self._late.start()

    def spawn_children(self):
        # Reaped children: a bounded wait()/join()/communicate() is
        # reachable in this file (boundedness itself is VDT003's half).
        self._proc = subprocess.Popen(["sleep", "1"])
        self._worker = multiprocessing.Process(target=work)
        self._worker.start()
        self._sidecar = multiprocessing.Process(target=work, daemon=True)
        self._sidecar.start()
        self._piped = subprocess.Popen(["true"])

    def run_managed(self):
        # The context-manager form reaps on __exit__.
        with subprocess.Popen(["true"]) as managed:
            managed.poll()

    def shutdown(self):
        self._joined.join(timeout=5)
        self._proc.wait(timeout=5)
        self._worker.join(timeout=5)
        self._piped.communicate(timeout=5)
