"""Negative corpus for VDT005: daemons, joined threads, late daemon=."""

import threading


def work():
    pass


class Owner:
    def start(self):
        self._daemon = threading.Thread(target=work, daemon=True)
        self._daemon.start()
        self._joined = threading.Thread(target=work)
        self._joined.start()
        self._late = threading.Thread(target=work)
        self._late.daemon = True
        self._late.start()

    def shutdown(self):
        self._joined.join(timeout=5)
