"""VDT010 negative corpus: wrapped, waived, or non-session calls that
must produce zero NEW resilient-http findings.  Parsed, never
imported."""


async def wrapped_unary(state, rz, url):
    # The wrapper itself: the session is an argument, not the receiver.
    async with await rz.request(
        state.session, "GET", url, endpoint="health"
    ) as resp:
        return await resp.json()


async def hedged_read(rz, fetch):
    return await rz.hedged("metrics", None, fetch)


async def waived_bootstrap(state, url):
    # A probe that runs before the manager exists carries the reason.
    async with state.session.get(url) as resp:  # vdt-lint: disable=resilient-http — bootstrap probe predates the resilience manager
        return resp.status


def not_http(cache, url):
    # dict.get on a non-session receiver is not an outbound call.
    return cache.get(url)


async def other_client(downloader, url):
    # Receiver does not look like an aiohttp session.
    return await downloader.get(url)
