"""Negative corpus for VDT007, including the old checker's blind spot
(ISSUE 6 satellite): tuple-unpacked and walrus bindings before a
try/finally are guarded — the finally is what matters."""


def with_form(tracer, work):
    with tracer.start_span("stage"):
        work()


def with_as(tracer, work):
    with tracer.start_span("stage") as span:
        work(span)


def try_finally(tracer, work):
    span = tracer.start_span("stage")
    try:
        work()
    finally:
        span.end()


def tuple_unpacked(tracer, work, clock):
    t0, span = clock(), tracer.start_span("stage")
    try:
        work()
    finally:
        span.end(t0)


def walrus(tracer, work):
    if (span := tracer.start_span("stage")) is not None:
        work()
    try:
        work()
    finally:
        span.end()
