"""Positive corpus for VDT003 unbounded-wait."""

import asyncio


async def waits(fut, peer, reader, proc, ev):
    await fut  # EXPECT
    await peer.get_param("ping")  # EXPECT
    await asyncio.wait({fut})  # EXPECT
    await reader.readexactly(4)  # EXPECT
    await proc.communicate()  # EXPECT
    await ev.wait()  # EXPECT exactly one finding: the await path owns
    # this leaf; the sync .wait() branch must not double-count it.


def sync_result(fut):
    return fut.result()  # EXPECT


def step_queue_loop(inbox, stop_event):
    # The step-queue wait pattern gone wrong: unbounded queue get and
    # event wait park the loop thread past stop().
    frame = inbox.get()  # EXPECT
    stop_event.wait()  # EXPECT
    return frame


async def router_forwarding_loop(session, frames, resp):
    # The ISSUE 10 router patterns gone wrong: a silently dead replica
    # wedges the client stream instead of triggering migration.
    body = await resp.read()  # EXPECT
    frame = await frames.get()  # EXPECT
    await asyncio.gather(one(), two())  # EXPECT
    return body, frame


def reap_child(proc):
    # The ISSUE 13 fleet reap gone wrong: an unbounded child-process
    # wait wedges the router's scale-down/shutdown on one stuck
    # replica instead of escalating TERM -> KILL.
    return proc.wait()  # EXPECT


def kv_export_collective(executor, pages):
    # The ISSUE 15 hand-off pattern gone wrong: an unbounded export
    # collective parks the engine thread (and every stream on the
    # replica) behind one wedged device gather.
    fut = executor.collective_rpc("export_kv_pages", (pages, 0, 4))
    return fut.result()  # EXPECT


async def kv_handoff_transfer(session, decode_url):
    # ...and the unbounded import read on the router side of the hop.
    resp = await session.post(decode_url, json={"op": "chunk"})
    body = await resp.read()  # EXPECT
    return body


def wal_rotate_barrier(fsync_done, pending_records):
    # The ISSUE 17 WAL pattern gone wrong: segment rotation blocking on
    # an unbounded flusher handshake parks the router control plane
    # (and every checkpoint behind it) on one stuck fsync.
    fsync_done.wait()  # EXPECT
    return pending_records.get()  # EXPECT


async def wal_replay_gather(segments):
    # ...and the recovery replay awaiting every segment read with no
    # deadline: one unreadable segment wedges router startup forever.
    await asyncio.gather(*segments)  # EXPECT
