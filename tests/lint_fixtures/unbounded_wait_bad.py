"""Positive corpus for VDT003 unbounded-wait."""

import asyncio


async def waits(fut, peer, reader, proc):
    await fut  # EXPECT
    await peer.get_param("ping")  # EXPECT
    await asyncio.wait({fut})  # EXPECT
    await reader.readexactly(4)  # EXPECT
    await proc.communicate()  # EXPECT


def sync_result(fut):
    return fut.result()  # EXPECT
