"""Positive corpus for VDT005 thread-leak."""

import threading


def work():
    pass


class Owner:
    def start(self):
        self._t = threading.Thread(target=work)  # EXPECT
        self._t.start()
        threading.Thread(target=work).start()  # EXPECT
        explicit = threading.Thread(target=work, daemon=False)  # EXPECT
        explicit.start()
