"""Positive corpus for VDT005 thread-leak (threads and, since
ISSUE 13, orphanable child processes)."""

import multiprocessing
import subprocess
import threading


def work():
    pass


class Owner:
    def start(self):
        self._t = threading.Thread(target=work)  # EXPECT
        self._t.start()
        threading.Thread(target=work).start()  # EXPECT
        explicit = threading.Thread(target=work, daemon=False)  # EXPECT
        explicit.start()

    def spawn_children(self):
        # Child processes with no reachable wait()/join(): unreaped,
        # each lingers as a zombie holding its port.
        self._proc = subprocess.Popen(["sleep", "1"])  # EXPECT
        subprocess.Popen(["sleep", "1"])  # EXPECT
        self._worker = multiprocessing.Process(target=work)  # EXPECT
        self._worker.start()
