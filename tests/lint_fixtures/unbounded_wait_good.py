"""Negative corpus for VDT003: every wait carries a deadline (or is
composition, whose callee owns it)."""

import asyncio


async def bounded(fut, peer, reader, ev):
    await asyncio.wait_for(fut, 5)
    await asyncio.sleep(1)
    await asyncio.wait({fut}, timeout=5)
    await reader.readexactly(4, timeout=5)
    # The canonical bounded-event pattern: asyncio.Event.wait takes no
    # timeout kwarg, the wait_for wrapper IS the deadline — the sync
    # .wait() branch must not flag it.
    await asyncio.wait_for(ev.wait(), timeout=5)
    # Composition: awaiting an ordinary coroutine call is the callee's
    # (or its orchestrator's) deadline to own.
    await helper(peer)


async def helper(peer):
    await asyncio.wait_for(peer.get_param("ping"), 5)


async def nested_wait_for(fut, msg, send):
    # The rpc.py send_and_wait pattern: every call of the nested def is
    # wrapped in wait_for, so its inner awaits are bounded.
    async def send_and_wait():
        await send(msg)
        return await fut

    return await asyncio.wait_for(send_and_wait(), 5)


def sync_result(fut):
    return fut.result(timeout=5)


async def router_forwarding_loop(session, frames, resp, read_timeout):
    # The ISSUE 10 router patterns done right: every upstream read and
    # every queue wait is deadline-bounded, so a silently dead replica
    # triggers migration instead of wedging the client stream.
    body = await asyncio.wait_for(resp.read(), timeout=read_timeout)
    frame = await asyncio.wait_for(frames.get(), timeout=read_timeout)
    await asyncio.wait_for(
        asyncio.gather(helper(session), helper(session)),
        timeout=read_timeout,
    )
    return body, frame


def step_queue_loop(inbox, stop, results):
    # The step-queue wait pattern (worker/step_stream.py): bounded poll
    # plus stop-flag re-check, so stop() always wins within one tick.
    import queue

    while not stop.is_set():
        try:
            frame = inbox.get(timeout=0.5)
        except queue.Empty:
            continue
        results.append(frame)
    stop.wait(timeout=5)
    # dict.get always takes a key — a positional arg is not a queue wait.
    return {"a": 1}.get("a")


def reap_child(proc):
    # The ISSUE 13 fleet reap done right: the child wait is
    # deadline-bounded so a stuck replica escalates to KILL instead of
    # wedging the router.
    try:
        return proc.wait(timeout=5)
    except Exception:
        proc.kill()
        return proc.wait(timeout=5)


async def kv_handoff_transfer(executor, session, pages, decode_url):
    # The ISSUE 15 hand-off patterns done right: the export collective
    # and the import read both carry deadlines, so a wedged transfer
    # fails the hand-off (router falls back to recompute) instead of
    # parking the engine thread.
    chunk = executor.collective_rpc(
        "export_kv_pages", (pages, 0, 4), timeout=60.0
    )
    resp = await session.post(decode_url, json={"op": "chunk"})
    body = await asyncio.wait_for(resp.read(), timeout=30)
    return chunk, body


def wal_rotate_barrier(fsync_done, pending_records, stop):
    # The ISSUE 17 WAL pattern done right: the rotation handshake and
    # the record drain both poll with a deadline and re-check the stop
    # flag, so one stuck fsync degrades a checkpoint instead of
    # wedging the router control plane.
    import queue

    while not stop.is_set():
        if fsync_done.wait(timeout=0.5):
            break
    try:
        return pending_records.get(timeout=0.5)
    except queue.Empty:
        return None


async def wal_replay_gather(segments):
    # ...and the recovery replay bounded end to end: one unreadable
    # segment fails startup loudly instead of wedging it forever.
    await asyncio.wait_for(asyncio.gather(*segments), timeout=30)
