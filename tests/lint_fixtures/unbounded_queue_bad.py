"""Positive corpus for VDT008 unbounded-queue."""

import asyncio
import collections
import queue
from collections import deque
from queue import Queue, SimpleQueue


class Intake:
    def __init__(self):
        self.q = queue.Queue()  # EXPECT
        self.sq = SimpleQueue()  # EXPECT
        self.sq2 = queue.SimpleQueue()  # EXPECT
        self.waiting = deque()  # EXPECT
        self.also_waiting = collections.deque([1, 2, 3])  # EXPECT
        self.aq = asyncio.Queue()  # EXPECT
        self.zero_is_infinite = Queue(maxsize=0)  # EXPECT
        self.zero_positional = queue.Queue(0)  # EXPECT
        self.none_maxlen = deque([], maxlen=None)  # EXPECT
        self.lifo = queue.LifoQueue()  # EXPECT


class RouterResumeFanIn:
    # The ISSUE 10 router pattern gone wrong: per-choice resume pumps
    # feeding an unbounded frame queue turn a slow client into memory
    # growth instead of backpressure on the upstream reads.
    def __init__(self):
        self.frames = asyncio.Queue()  # EXPECT


class KVTransferInbox:
    # The ISSUE 15 transfer pattern gone wrong: buffering inbound KV
    # chunk frames in an unbounded queue turns one slow scatter into
    # unbounded host memory instead of backpressure on the sender.
    def __init__(self):
        self.chunks = asyncio.Queue()  # EXPECT
        self.pending_imports = deque()  # EXPECT
