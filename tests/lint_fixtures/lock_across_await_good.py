"""Negative corpus for VDT002: release before awaiting, or use the
async lock form."""

import asyncio
import threading

_lock = threading.Lock()
_alock = asyncio.Lock()


async def read_then_await(peer):
    # The FaultInjector.on_write pattern: read state under the lock,
    # do the slow thing outside it.
    with _lock:
        value = 1
    await peer.call(value)


async def async_with_is_fine(peer):
    async with _alock:
        await peer.call()


async def nested_def_not_held(peer):
    with _lock:
        async def later():
            await peer.call()
    return later
