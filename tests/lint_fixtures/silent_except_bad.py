"""Positive corpus for VDT006 silent-except."""


def teardown(x):
    try:
        x.close()
    except Exception:  # EXPECT
        pass
    try:
        x.flush()
    except:  # noqa: E722  # EXPECT
        pass
    try:
        x.sync()
    except (ValueError, Exception):  # EXPECT
        pass
