"""Positive corpus for VDT009 bounded-cardinality."""


class Metrics:
    def __init__(self, counter, gauge):
        self.counter = counter
        self.gauge = gauge

    def record(self, request_id, prompt, trace_id, req):
        # One time series per request id: the classic cardinality bomb.
        self.counter.labels(request_id=request_id).inc()  # EXPECT
        # Attribute chains count: req.request_id is the same source.
        self.counter.labels(rid=req.request_id).inc()  # EXPECT
        # Formatting it into another label does not launder it.
        self.counter.labels(model_name=f"m-{request_id}").inc()  # EXPECT
        # Prompt-derived labels grow with the corpus of user text.
        self.gauge.labels(prompt=prompt[:16]).set(1)  # EXPECT
        # Trace/span ids are 128-bit randoms: one series per request.
        self.gauge.labels(span=trace_id).set(1)  # EXPECT
        # Positional label values are checked like keyword ones.
        self.counter.labels(request_id).inc()  # EXPECT
        # Splatted label dicts name their sources too.
        self.counter.labels(**{"request_id": request_id}).inc()  # EXPECT

    def record_qos(self, req, victim):
        # QoS control loops (ISSUE 16) emit per-class series — keyed
        # by the registry-resolved class name, never per-request
        # identity, however tempting "which request was preempted" is.
        self.counter.labels(qos_class=req.request_id).inc()  # EXPECT
        self.counter.labels(victim=victim.request_id).inc()  # EXPECT
