"""Pallas paged-attention kernel vs the pure-JAX reference oracle, in
interpreter mode on CPU (SURVEY.md §4 item 2: kernel tests over head
dims, page sizes, GQA ratios, masks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    merge_kv_pages,
    paged_attention_reference,
)
from vllm_distributed_tpu.ops.pallas.paged_attention import paged_attention


def build_case(
    rng,
    *,
    seq_specs,  # list of (ctx_len, chunk_len): context incl. chunk
    s_pad=8,
    t_pad=None,
    hq=4,
    hkv=2,
    d=64,
    page_size=16,
    num_pages=64,
    dtype=jnp.float32,
):
    """Random paged KV state + flat query batch covering mixed
    prefill/decode."""
    t_real = sum(c for _, c in seq_specs)
    t_pad = t_pad or max(16, 1 << (t_real - 1).bit_length())
    max_pages_needed = max(
        -(-ctx // page_size) for ctx, _ in seq_specs
    )
    pages_pad = max(8, 1 << (max_pages_needed - 1).bit_length())

    k_pages = jnp.asarray(
        rng.standard_normal((num_pages, page_size, hkv, d)), dtype
    )
    v_pages = jnp.asarray(
        rng.standard_normal((num_pages, page_size, hkv, d)), dtype
    )
    kv_pages = merge_kv_pages(k_pages, v_pages)
    q = jnp.asarray(rng.standard_normal((t_pad, hq, d)), dtype)

    seq_ids = np.full(t_pad, s_pad, np.int32)
    positions = np.zeros(t_pad, np.int32)
    block_tables = np.zeros((s_pad, pages_pad), np.int32)
    seq_lens = np.zeros(s_pad, np.int32)
    chunk_starts = np.zeros(s_pad, np.int32)
    logits_idx = np.zeros(s_pad, np.int32)

    next_page = 1  # page 0 reserved
    cursor = 0
    for s, (ctx, chunk) in enumerate(seq_specs):
        n_pages = -(-ctx // page_size)
        pages = list(range(next_page, next_page + n_pages))
        next_page += n_pages
        block_tables[s, :n_pages] = pages
        seq_lens[s] = ctx
        chunk_starts[s] = ctx - chunk
        positions[cursor : cursor + chunk] = np.arange(ctx - chunk, ctx)
        seq_ids[cursor : cursor + chunk] = s
        logits_idx[s] = cursor + chunk - 1
        cursor += chunk

    meta = AttentionMetadata(
        q_seq_ids=jnp.asarray(seq_ids),
        q_positions=jnp.asarray(positions),
        slot_mapping=jnp.zeros(t_pad, jnp.int32),
        block_tables=jnp.asarray(block_tables),
        seq_lens=jnp.asarray(seq_lens),
        logits_indices=jnp.asarray(logits_idx),
        chunk_starts=jnp.asarray(chunk_starts),
    )
    max_q = max(c for _, c in seq_specs)
    max_q = 1 << (max_q - 1).bit_length() if max_q > 1 else 1
    return q, kv_pages, meta, max_q, cursor, hkv


def _compare(case, scale=0.125, atol=2e-5):
    q, kv_pages, meta, max_q, t_real, hkv = case
    ref = paged_attention_reference(
        q, kv_pages, meta, scale=scale, num_kv_heads=hkv
    )
    got = paged_attention(
        q, kv_pages, meta, scale=scale, num_kv_heads=hkv,
        max_q=max_q, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[:t_real]),
        np.asarray(ref[:t_real]),
        rtol=1e-4,
        atol=atol,
    )


def test_pure_decode_batch():
    rng = np.random.default_rng(0)
    _compare(build_case(rng, seq_specs=[(17, 1), (33, 1), (160, 1)]))


def test_pure_prefill():
    rng = np.random.default_rng(1)
    _compare(build_case(rng, seq_specs=[(24, 24), (7, 7)]))


def test_chunked_prefill_continuation():
    # Context 40 of which the last 8 are this step's chunk.
    rng = np.random.default_rng(2)
    _compare(build_case(rng, seq_specs=[(40, 8), (64, 16)]))


def test_mixed_prefill_and_decode():
    rng = np.random.default_rng(3)
    _compare(
        build_case(rng, seq_specs=[(50, 1), (20, 20), (33, 1), (48, 12)])
    )


def test_gqa_ratios():
    rng = np.random.default_rng(4)
    _compare(
        build_case(rng, seq_specs=[(30, 1), (12, 12)], hq=8, hkv=2)
    )


def test_mha_group_1():
    rng = np.random.default_rng(5)
    _compare(build_case(rng, seq_specs=[(21, 1), (9, 9)], hq=4, hkv=4))


def test_page_size_32_head_dim_128():
    rng = np.random.default_rng(6)
    _compare(
        build_case(
            rng,
            seq_specs=[(70, 6), (100, 1)],
            page_size=32,
            d=128,
            num_pages=32,
        )
    )


def test_single_token_context():
    rng = np.random.default_rng(7)
    _compare(build_case(rng, seq_specs=[(1, 1)]))


def test_long_context_multiblock():
    # Forces multiple kv blocks (ctx 600 > 256-token block).
    rng = np.random.default_rng(8)
    _compare(
        build_case(
            rng, seq_specs=[(600, 1), (300, 4)], num_pages=80
        )
    )


def test_bfloat16_cache():
    rng = np.random.default_rng(9)
    q, kv, meta, max_q, t_real, hkv = build_case(
        rng, seq_specs=[(40, 4), (21, 1)], dtype=jnp.bfloat16
    )
    ref = paged_attention_reference(
        q, kv, meta, scale=0.125, num_kv_heads=hkv
    )
    got = paged_attention(
        q, kv, meta, scale=0.125, num_kv_heads=hkv,
        max_q=max_q, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[:t_real], np.float32),
        np.asarray(ref[:t_real], np.float32),
        rtol=5e-2,
        atol=5e-2,
    )


def test_engine_e2e_with_pallas_backend(tmp_path):
    """Whole engine on the interpret-mode kernel must equal the
    reference backend token-for-token."""
    from tests.utils import make_tiny_llama
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    model_dir = make_tiny_llama(str(tmp_path / "m"))

    def run(backend):
        config = EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
            max_num_seqs=8,
            max_num_batched_tokens=32,  # force chunking
        ).create_engine_config()
        engine = LLMEngine(config)
        engine.executor.worker.runner._attn_fn = _backend(backend)
        prompts = [list(range(1, 40)), [5, 6, 7], list(range(50, 70))]
        for i, p in enumerate(prompts):
            engine.add_request(
                f"r{i}",
                prompt_token_ids=p,
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=5, ignore_eos=True
                ),
            )
        done = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
        return [done[f"r{i}"] for i in range(len(prompts))]

    def _backend(name):
        if name == "pallas":
            from vllm_distributed_tpu.ops.pallas.paged_attention import (
                paged_attention_cpu,
            )

            return paged_attention_cpu
        return paged_attention_reference

    assert run("pallas") == run("reference")


def test_cross_seq_prefetch_multiblock_decode():
    """Decode with contexts spanning MULTIPLE kv blocks (ctx > 1024
    tokens at the default 512 KiB KV buffer with hkv=2/d=64/f32), which
    flips on the cross-sequence block-0 prefetch path — including an
    empty sequence between live ones and uneven final blocks."""
    rng = np.random.default_rng(9)
    _compare(
        build_case(
            rng,
            seq_specs=[(1100, 1), (2047, 1), (1025, 1)],
            num_pages=300,
        )
    )


def test_cross_seq_prefetch_with_empty_seq():
    rng = np.random.default_rng(10)
    # A zero-length sequence in the middle: the prefetch chain must skip
    # it without unbalancing DMA starts/waits.
    _compare(
        build_case(
            rng,
            seq_specs=[(1500, 1), (0, 0), (1100, 1)],
            num_pages=300,
        )
    )


def test_staged_side_buffer_decode():
    """Kernel + reference with side_kv/side_len must equal the reference
    over a pool where the staged rows were already flushed."""
    rng = np.random.default_rng(11)
    hq, hkv, d, page_size = 8, 2, 64, 16
    k_steps, step_i = 16, 9  # micro-step 9 of a 16-step dispatch
    bases = [37, 160, 0, 5]  # pool-resident lengths; row 2 = padding
    s_pad = len(bases)
    num_pages = 64

    from vllm_distributed_tpu.ops.attention import (
        kv_pool_shape,
        write_kv_pages,
    )

    kv = jnp.asarray(
        rng.standard_normal(kv_pool_shape(num_pages, page_size, hkv, d)),
        jnp.float32,
    )
    side = jnp.asarray(
        rng.standard_normal((s_pad, 2, k_steps, hkv * d)), jnp.float32
    )
    max_pages = 16
    bt = np.zeros((s_pad, max_pages), np.int32)
    nxt = 1
    for i, b in enumerate(bases):
        if b <= 0:
            continue
        need = -(-(b + k_steps) // page_size)
        bt[i, :need] = np.arange(nxt, nxt + need)
        nxt += need

    # Queries: one decode token per live row at position base + step_i.
    pos = np.asarray(
        [b + step_i if b > 0 else 0 for b in bases], np.int32
    )
    sid = np.asarray(
        [i if b > 0 else s_pad for i, b in enumerate(bases)], np.int32
    )
    q = jnp.asarray(rng.standard_normal((s_pad, hq, d)), jnp.float32)
    meta_staged = AttentionMetadata(
        q_seq_ids=jnp.asarray(sid),
        q_positions=jnp.asarray(pos),
        slot_mapping=jnp.zeros(s_pad, jnp.int32),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.asarray(np.asarray(bases, np.int32)),  # POOL lens
        logits_indices=jnp.arange(s_pad, dtype=jnp.int32),
        chunk_starts=jnp.asarray(pos),
    )
    side_len = jnp.asarray([step_i + 1], jnp.int32)

    # Oracle: flush side rows 0..step_i into a copy of the pool and run
    # the plain reference with full sequence lengths.
    flushed = kv
    for i, b in enumerate(bases):
        if b <= 0:
            continue
        for j in range(step_i + 1):
            p = b + j
            slot = bt[i, p // page_size] * page_size + p % page_size
            flushed = write_kv_pages(
                flushed,
                side[i, 0, j].reshape(1, hkv, d),
                side[i, 1, j].reshape(1, hkv, d),
                jnp.asarray([slot], jnp.int32),
            )
    meta_full = AttentionMetadata(
        q_seq_ids=meta_staged.q_seq_ids,
        q_positions=meta_staged.q_positions,
        slot_mapping=meta_staged.slot_mapping,
        block_tables=meta_staged.block_tables,
        seq_lens=jnp.asarray(
            np.asarray(
                [b + step_i + 1 if b > 0 else 0 for b in bases], np.int32
            )
        ),
        logits_indices=meta_staged.logits_indices,
        chunk_starts=meta_staged.chunk_starts,
    )
    want = paged_attention_reference(
        q, flushed, meta_full, scale=0.125, num_kv_heads=hkv
    )

    got_ref = paged_attention_reference(
        q, kv, meta_staged, scale=0.125, num_kv_heads=hkv,
        side_kv=side, side_len=side_len,
    )
    got_pl = paged_attention(
        q, kv, meta_staged, scale=0.125, num_kv_heads=hkv,
        max_q=1, side_kv=side, side_len=side_len, interpret=True,
    )
    live = np.asarray([i for i, b in enumerate(bases) if b > 0])
    np.testing.assert_allclose(
        np.asarray(got_ref)[live], np.asarray(want)[live],
        rtol=1e-4, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(got_pl)[live], np.asarray(want)[live],
        rtol=1e-4, atol=2e-5,
    )
