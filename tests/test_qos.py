"""QoS control plane suite (ISSUE 16): class registry, class-aware
admission shares, priority scheduling + victim selection + weighted
preempt-to-shed, the chunked-prefill fairness budget, and the
router-side placement/autoscale signal units.

Layered like the feature: pure registry units (parse/resolve/bounds —
including the VDT009 drift check that every name a QoS loop can emit is
already metrics-safe); AdmissionController share units; scheduler-level
units reusing the test_scheduler step harness; the satellite 4
starvation A/B (decode ITL bounded under a long low-class prefill,
work-conserving without decode, fairness-off schedule-identical to
seed); a 3-class overload A/B acceptance (QoS-on strictly beats QoS-off
on high-class completion with total throughput preserved and all
preemption pressure on the lowest class); and router policy units
(segregate/reserve placement, goodput windowing, prefill-demand EWMA).

Everything is default-off: the registry parsed from an empty spec
drives the exact seed code paths, which the schedule-identity tests
pin down step by step.
"""

from __future__ import annotations

import math

import pytest

from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
from vllm_distributed_tpu.engine.overload import (
    AdmissionController,
    EngineOverloadedError,
)
from vllm_distributed_tpu.engine.qos import (
    QosRegistry,
    parse_qos_classes,
)
from vllm_distributed_tpu.engine.request import Request, RequestStatus
from vllm_distributed_tpu.engine.scheduler import Scheduler
from vllm_distributed_tpu.engine.slo import (
    DEFAULT_CLASS,
    MAX_CLASSES,
    sanitize_class,
)
from vllm_distributed_tpu.router.qos import (
    GoodputTracker,
    PrefillDemand,
    QosRouterPolicy,
)
from vllm_distributed_tpu.sampling_params import SamplingParams

pytestmark = pytest.mark.qos


# ---------------------------------------------------------------------
# harness (the test_scheduler step loop, with QoS knobs)
# ---------------------------------------------------------------------
def make_scheduler(
    max_num_seqs=8,
    max_num_batched_tokens=64,
    num_pages=64,
    page_size=4,
    max_model_len=256,
    chunked=True,
    qos_classes="",
    qos_prefill_share=0.0,
    preempt_shed_threshold=0,
):
    return Scheduler(
        SchedulerConfig(
            max_num_seqs=max_num_seqs,
            max_num_batched_tokens=max_num_batched_tokens,
            enable_chunked_prefill=chunked,
            max_model_len=max_model_len,
            qos_classes=qos_classes,
            qos_prefill_share=qos_prefill_share,
            preempt_shed_threshold=preempt_shed_threshold,
        ),
        CacheConfig(page_size=page_size),
        num_pages=num_pages,
    )


def make_req(rid, prompt_len=8, max_tokens=8, slo_class="default"):
    return Request(
        request_id=rid,
        prompt_token_ids=list(range(prompt_len)),
        sampling_params=SamplingParams(
            max_tokens=max_tokens, slo_class=slo_class
        ),
        eos_token_id=None,
    )


def run_step(sched):
    out = sched.schedule()
    tokens = {}
    for req_id, n in out.num_scheduled_tokens.items():
        req = sched.requests[req_id]
        if (
            req.num_computed_tokens + n
            >= req.num_prompt_tokens + req.num_output_tokens
        ):
            tokens[req_id] = [7]
    finished = sched.update_from_output(out, tokens)
    return out, finished


# ---------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------
def test_parse_qos_classes_full_and_defaulted_fields():
    classes = parse_qos_classes(
        "interactive:10:0.5:1.5, default:0:0.3 ,batch:-10"
    )
    assert set(classes) == {"interactive", "default", "batch"}
    it = classes["interactive"]
    assert (it.priority, it.admission_share, it.preemption_weight) == (
        10,
        0.5,
        1.5,
    )
    # share defaults to 0 (borrow-only), weight to 1 (seed shed budget).
    assert classes["batch"].admission_share == 0.0
    assert classes["batch"].preemption_weight == 1.0
    assert parse_qos_classes("") == {}


def test_parse_qos_classes_rejects_bad_specs():
    bad = (
        "gold",  # no priority
        "gold:x",  # non-integer priority
        "gold:1:2.0",  # share outside [0, 1]
        "gold:1:-0.1",
        "gold:1:0.5:0",  # non-positive weight
        "gold:1:0.6,silver:0:0.6",  # shares sum > 1
        "gold:1,gold:2",  # duplicate name
        "gold:1:0.5:1:9",  # too many fields
    )
    for spec in bad:
        with pytest.raises(ValueError):
            parse_qos_classes(spec)
    with pytest.raises(ValueError):
        parse_qos_classes(
            ",".join(f"c{i}:0" for i in range(MAX_CLASSES + 1))
        )


def test_registry_disabled_by_default_is_neutral():
    for spec in ("", None):
        reg = QosRegistry.parse(spec)
        assert not reg.enabled
        assert reg.class_names() == []
        assert reg.min_priority() == 0
        qc = reg.resolve("anything-at-all")
        assert qc.name == DEFAULT_CLASS
        assert (qc.priority, qc.admission_share, qc.preemption_weight) == (
            0,
            0.0,
            1.0,
        )


def test_registry_resolve_folds_unknown_into_default():
    reg = QosRegistry.parse("interactive:10:0.5,default:0:0.2")
    assert reg.enabled
    assert reg.resolve("interactive").priority == 10
    # Unknown/absent names land on the CONFIGURED default entry.
    assert reg.resolve("no-such-class").admission_share == 0.2
    assert reg.resolve(None).name == DEFAULT_CLASS
    # Priority-ordered placement listing, name-tiebreak.
    reg2 = QosRegistry.parse("b:5,a:5,z:9")
    assert reg2.class_names() == ["z", "a", "b"]
    assert reg2.min_priority() == 5


def test_registry_labels_are_metrics_safe():
    """VDT009 drift check: every label a QoS control loop can emit is
    registry-resolved — names survive sanitize_class unchanged and the
    table is capped, so hostile request strings can never grow the
    per-class series space."""
    reg = QosRegistry.parse("Weird Näme!:3:0.1,ok-class_2:1")
    for name in reg.class_names():
        assert name == sanitize_class(name)
    assert len(reg.classes) <= MAX_CLASSES
    emittable = set(reg.classes) | {DEFAULT_CLASS}
    for hostile in (
        "x" * 4096,
        "a,b{}\n",
        "../../etc/passwd",
        None,
        "ok-class_2",
    ):
        assert reg.resolve(hostile).name in emittable


# ---------------------------------------------------------------------
# admission-share units (AdmissionController)
# ---------------------------------------------------------------------
def _ac(**cfg_kw) -> AdmissionController:
    cfg_kw.setdefault("qos_classes", "gold:10:0.5,bronze:-10:0")
    return AdmissionController(SchedulerConfig(**cfg_kw))


def test_admission_borrow_then_guarantee_under_overload():
    ac = _ac(max_waiting_requests=10)
    # Spare capacity: a zero-share class borrows freely up to the cap.
    for _ in range(10):
        ac.reserve(0, slo_class="bronze")
    with pytest.raises(EngineOverloadedError) as e:
        ac.reserve(0, slo_class="bronze")
    assert e.value.reason == "queue_full"
    # The cap is saturated with bronze, but gold still has its whole
    # guaranteed slice (0.5 * 10 = 5): the 429s land on bronze first.
    for _ in range(5):
        ac.reserve(0, slo_class="gold")
    with pytest.raises(EngineOverloadedError):
        ac.reserve(0, slo_class="gold")
    # Work-conserving: freeing spare capacity re-opens borrowing.
    for _ in range(6):
        ac.release(0, slo_class="bronze")
    ac.reserve(0, slo_class="bronze")


def test_admission_token_cap_shares():
    ac = _ac(max_queued_tokens=100, qos_classes="gold:10:0.4,bronze:0:0")
    ac.reserve(100, slo_class="bronze")  # borrow the whole spare cap
    with pytest.raises(EngineOverloadedError) as e:
        ac.reserve(10, slo_class="bronze")
    assert e.value.reason == "queued_tokens"
    ac.reserve(40, slo_class="gold")  # inside the 0.4 * 100 guarantee
    with pytest.raises(EngineOverloadedError):
        ac.reserve(10, slo_class="gold")
    assert ac.class_queued_tokens("gold") == 40
    ac.consumed(40, slo_class="gold")
    assert ac.class_queued_tokens("gold") == 0
    assert ac.class_queue_depth("gold") == 0


def test_admission_disabled_registry_ignores_class():
    ac = _ac(max_waiting_requests=3, qos_classes="")
    assert not ac.qos.enabled
    for cls in ("gold", "bronze", None):
        ac.reserve(0, slo_class=cls)
    # Seed FIFO cap: class strings buy nothing once the cap is hit.
    with pytest.raises(EngineOverloadedError):
        ac.reserve(0, slo_class="gold")


# ---------------------------------------------------------------------
# scheduler: priority admission + victim selection + weighted shed
# ---------------------------------------------------------------------
def test_waiting_admission_prefers_high_class():
    sched = make_scheduler(
        max_num_batched_tokens=16, qos_classes="gold:10,bronze:-10"
    )
    sched.add_request(make_req("b0", slo_class="bronze"))
    sched.add_request(make_req("b1", slo_class="bronze"))
    sched.add_request(make_req("g0", slo_class="gold"))
    out, _ = run_step(sched)
    # Budget fits two 8-token prefills: gold jumps the bronze backlog,
    # then FIFO within bronze.
    assert set(out.num_scheduled_tokens) == {"g0", "b0"}
    assert sched.waiting_by_class.get("bronze") == 1
    assert not sched.waiting_by_class.get("gold")


def test_waiting_fifo_within_equal_class():
    sched = make_scheduler(
        max_num_batched_tokens=16, qos_classes="gold:10,bronze:-10"
    )
    for i in range(4):
        sched.add_request(make_req(f"b{i}", slo_class="bronze"))
    out, _ = run_step(sched)
    assert set(out.num_scheduled_tokens) == {"b0", "b1"}


def test_preemption_victim_is_lowest_class():
    # Same pressure as test_scheduler's preemption unit, but the bronze
    # request ARRIVES FIRST: the seed (most-recent) policy would evict
    # gold, the QoS policy must evict bronze.
    sched = make_scheduler(
        num_pages=16,
        page_size=4,
        max_num_batched_tokens=32,
        qos_classes="gold:10,bronze:-10",
    )
    bronze = make_req("b", prompt_len=12, max_tokens=20, slo_class="bronze")
    gold = make_req("g", prompt_len=12, max_tokens=20, slo_class="gold")
    sched.add_request(bronze)
    sched.add_request(gold)
    out, _ = run_step(sched)
    assert set(out.num_scheduled_tokens) == {"b", "g"}
    preempted: list[str] = []
    for _ in range(120):
        out, _ = run_step(sched)
        preempted += out.preempted_req_ids
        if not sched.has_unfinished_requests():
            break
    assert preempted, "pool pressure never triggered a preemption"
    assert set(preempted) == {"b"}
    assert gold.num_preemptions == 0
    assert sched.preemptions_by_class == {"bronze": len(preempted)}
    # Both still finish: preemption is deferral, not loss.
    assert gold.num_output_tokens == 20
    assert bronze.num_output_tokens == 20


def test_weighted_preempt_shed_budget():
    # threshold 2: bronze (weight 0.5) sheds after 1 eviction, gold
    # (weight 2.0) rides out 4.
    sched = make_scheduler(
        num_pages=64,
        preempt_shed_threshold=2,
        qos_classes="gold:5:0:2.0,bronze:-5:0:0.5",
    )

    def preempt_once(req):
        run_step(sched)  # (re)admit + run
        assert req in sched.running
        sched._preempt(req, set())

    bronze = make_req("b", max_tokens=64, slo_class="bronze")
    sched.add_request(bronze)
    preempt_once(bronze)
    assert bronze.status == RequestStatus.PREEMPTED  # within budget
    preempt_once(bronze)
    assert bronze.status == RequestStatus.FINISHED_SHED
    assert sched.sheds_by_class == {"bronze": 1}
    assert [r.request_id for r in sched.take_finished_out_of_band()] == ["b"]

    gold = make_req("g", max_tokens=64, slo_class="gold")
    sched.add_request(gold)
    for _ in range(4):
        preempt_once(gold)
        assert gold.status == RequestStatus.PREEMPTED
    preempt_once(gold)
    assert gold.status == RequestStatus.FINISHED_SHED
    assert sched.sheds_by_class == {"bronze": 1, "gold": 1}


# ---------------------------------------------------------------------
# chunked-prefill fairness budget (satellite 4)
# ---------------------------------------------------------------------
FAIR_KW = dict(
    max_num_batched_tokens=64,
    max_model_len=512,
    num_pages=256,
    qos_classes="gold:10,bronze:-10",
    qos_prefill_share=0.25,
)


def test_prefill_fairness_bounds_decode_itl():
    """The starvation scenario: a long low-class prefill lands while a
    high-class request decodes.  With the fairness budget the decode is
    scheduled EVERY step (bounded ITL) and prefill chunks never exceed
    share * budget; without it the very same arrival grabs the whole
    remaining budget."""
    sched = make_scheduler(**FAIR_KW)
    gold = make_req("g", prompt_len=8, max_tokens=60, slo_class="gold")
    sched.add_request(gold)
    run_step(sched)  # prefill completes; gold is decode-bound
    bronze = make_req("b", prompt_len=300, max_tokens=4, slo_class="bronze")
    sched.add_request(bronze)
    steps = 0
    while bronze.is_prefill:
        out, _ = run_step(sched)
        steps += 1
        assert out.num_scheduled_tokens["g"] == 1  # never skipped
        assert out.num_scheduled_tokens.get("b", 0) <= 16  # 0.25 * 64
        assert steps < 60
    # The budget actually throttled: 300 tokens at <=16/step.
    assert steps >= math.ceil(300 / 16)

    # A/B: fairness off (share=0) — the same arrival takes the whole
    # leftover budget in one chunk (63 = 64 - 1 decode token).
    off = make_scheduler(**{**FAIR_KW, "qos_prefill_share": 0.0})
    off.add_request(make_req("g", prompt_len=8, max_tokens=60, slo_class="gold"))
    run_step(off)
    off.add_request(
        make_req("b", prompt_len=300, max_tokens=4, slo_class="bronze")
    )
    out, _ = run_step(off)
    assert out.num_scheduled_tokens["b"] == 63


def test_prefill_fairness_work_conserving_without_decode():
    # No decode-bound request running: the cap disarms and prefill
    # fills the full step budget (exact seed policy).
    sched = make_scheduler(**FAIR_KW)
    sched.add_request(
        make_req("b", prompt_len=300, max_tokens=4, slo_class="bronze")
    )
    out, _ = run_step(sched)
    assert out.num_scheduled_tokens["b"] == 64


def test_prefill_fairness_exempts_higher_class_prefill():
    # bronze decodes; a GOLD prefill outranks every decode-bound class
    # so the budget does not throttle it.
    sched = make_scheduler(**FAIR_KW)
    sched.add_request(
        make_req("b", prompt_len=8, max_tokens=60, slo_class="bronze")
    )
    run_step(sched)
    sched.add_request(
        make_req("g", prompt_len=300, max_tokens=4, slo_class="gold")
    )
    out, _ = run_step(sched)
    assert out.num_scheduled_tokens["g"] == 63


def _drive_identical(sched_a, sched_b, workload, steps=40):
    """Feed both schedulers the same workload and assert the per-step
    schedules are identical."""
    for req_args in workload:
        sched_a.add_request(make_req(*req_args[:-1], slo_class=req_args[-1]))
        sched_b.add_request(make_req(*req_args[:-1], slo_class=req_args[-1]))
    for _ in range(steps):
        out_a, _ = run_step(sched_a)
        out_b, _ = run_step(sched_b)
        assert out_a.num_scheduled_tokens == out_b.num_scheduled_tokens
        assert out_a.preempted_req_ids == out_b.preempted_req_ids
        if not (
            sched_a.has_unfinished_requests()
            or sched_b.has_unfinished_requests()
        ):
            break
    assert not sched_a.has_unfinished_requests()
    assert not sched_b.has_unfinished_requests()


def test_qos_neutral_settings_schedule_identical_to_seed():
    """Satellite 4's off-switch guarantee, strengthened: BOTH a
    disabled registry and an enabled-but-neutral one (equal priorities,
    no shares, share=0 fairness) produce the seed schedule step for
    step on a mixed workload."""
    workload = [
        ("r0", 40, 8, "interactive"),
        ("r1", 8, 12, "batch"),
        ("r2", 24, 4, ""),
        ("r3", 8, 8, "interactive"),
    ]
    seed_kw = dict(max_num_batched_tokens=32, num_pages=64)
    _drive_identical(
        make_scheduler(**seed_kw),
        make_scheduler(
            **seed_kw, qos_classes="interactive:0,batch:0,default:0"
        ),
        workload,
    )
    _drive_identical(
        make_scheduler(**seed_kw),
        make_scheduler(**seed_kw, qos_classes=""),
        workload,
    )


# ---------------------------------------------------------------------
# 3-class overload acceptance (scheduler-level A/B)
# ---------------------------------------------------------------------
def _overload_run(qos_classes: str):
    """12 requests, 4 per class, WORST arrival order for the high
    class (bronze first), under seat + page pressure.  Returns
    (scheduler, steps at which each gold request finished, total
    completed, per-step completion order)."""
    sched = make_scheduler(
        max_num_seqs=4,
        max_num_batched_tokens=32,
        num_pages=20,
        qos_classes=qos_classes,
    )
    reqs = []
    for cls in ("bronze", "silver", "gold"):
        for i in range(4):
            r = make_req(f"{cls}{i}", prompt_len=8, max_tokens=16,
                         slo_class=cls)
            reqs.append(r)
            sched.add_request(r)
    gold_done: list[int] = []
    completed = 0
    for step in range(400):
        _, finished = run_step(sched)
        for r in finished:
            if r.status != RequestStatus.FINISHED_SHED:
                completed += 1
            if r.request_id.startswith("gold"):
                gold_done.append(step)
        if not sched.has_unfinished_requests():
            break
    assert not sched.has_unfinished_requests()
    return sched, gold_done, completed


def test_three_class_overload_qos_on_beats_off():
    spec = "gold:10:0.5,silver:0:0.3,bronze:-10:0:0.5"
    sched_on, gold_on, total_on = _overload_run(spec)
    sched_off, gold_off, total_off = _overload_run("")
    assert len(gold_on) == len(gold_off) == 4
    # Strictly better high-class latency: every gold completion lands
    # no later than QoS-off's, and the last one strictly earlier.
    assert max(gold_on) < max(gold_off)
    assert sum(gold_on) < sum(gold_off)
    # QoS ordering does not tax total throughput (acceptance: within
    # 10% — here the same closed workload completes in full).
    assert total_on == total_off == 12
    # Preemption/shed pressure lands on the lowest class first.  (Gold
    # may still self-preempt when every lower-class page holder is
    # already evicted this step — the yield rule — but the bulk of the
    # evictions must be bronze, and any shed is bronze-only.)
    assert set(sched_on.sheds_by_class) <= {"bronze"}
    by_cls = sched_on.preemptions_by_class
    assert (
        by_cls.get("bronze", 0)
        >= by_cls.get("silver", 0)
        >= by_cls.get("gold", 0)
    )
    assert by_cls.get("bronze", 0) > by_cls.get("gold", 0)


# ---------------------------------------------------------------------
# router policy units
# ---------------------------------------------------------------------
class _Rep:
    def __init__(self, rid):
        self.replica_id = rid

    def __repr__(self):  # pragma: no cover - debug aid
        return self.replica_id


def _fleet(n):
    return [_Rep(f"r{i:02d}") for i in range(n)]


def test_qos_placement_shared_is_passthrough():
    pol = QosRouterPolicy(
        QosRegistry.parse("gold:10:0.5,bronze:0:0"), "shared"
    )
    reps = _fleet(4)
    assert pol.filter(reps, "gold") is reps
    assert not pol.active
    # Disabled registry: any mode is a passthrough.
    pol2 = QosRouterPolicy(QosRegistry.parse(""), "segregate")
    assert pol2.filter(reps, "gold") is reps
    with pytest.raises(ValueError):
        QosRouterPolicy(QosRegistry.parse(""), "bogus")


def test_qos_placement_segregate_partitions_by_share():
    pol = QosRouterPolicy(
        QosRegistry.parse("gold:10:0.5,silver:0:0.25,bronze:-10:0"),
        "segregate",
    )
    reps = _fleet(8)
    gold = pol.filter(reps, "gold")
    silver = pol.filter(reps, "silver")
    bronze = pol.filter(reps, "bronze")
    assert len(gold) == 4 and len(silver) == 2 and len(bronze) == 2
    ids = lambda rs: {r.replica_id for r in rs}  # noqa: E731
    assert not (ids(gold) & ids(silver))
    assert not (ids(gold) & ids(bronze)) and not (ids(silver) & ids(bronze))
    assert ids(gold) | ids(silver) | ids(bronze) == ids(reps)
    # Deterministic in membership: a shuffled candidate list partitions
    # identically (every router instance agrees).
    assert ids(pol.filter(list(reversed(reps)), "gold")) == ids(gold)
    # A class with NO slice (unknown → default, not configured) falls
    # back to the full set rather than failing closed.
    assert pol.filter(reps, "no-such-class") == reps
    # So does a fleet too small to slice.
    one = _fleet(1)
    assert pol.filter(one, "silver") is one


def test_qos_placement_reserve_keeps_headroom_for_top_class():
    pol = QosRouterPolicy(
        QosRegistry.parse("gold:10:0.5,bronze:0:0"), "reserve"
    )
    reps = _fleet(4)
    assert pol.filter(reps, "gold") == sorted(
        reps, key=lambda r: r.replica_id
    )
    bronze = pol.filter(reps, "bronze")
    # ceil(0.5 * 4) = 2 tail replicas reserved for gold.
    assert [r.replica_id for r in bronze] == ["r00", "r01"]
    # Never fail closed: with nothing outside the headroom, bronze
    # keeps the full set.
    two = _fleet(1)
    assert pol.filter(two, "bronze") is two


def test_goodput_tracker_windows_floor_and_reset():
    tr = GoodputTracker(floor=0.9, min_requests=5)
    assert tr.update({"a": {"requests": 10, "goodput": 9}}) is None
    # Next window: 20 more requests, 11 more goodput → 0.55 < 0.9.
    assert tr.update({"a": {"requests": 30, "goodput": 20}}) == "a"
    assert tr.window["a"] == (20, 11)
    # Counters going backwards (replica left the merge) restart the
    # window instead of reporting a bogus negative delta.
    assert tr.update({"a": {"requests": 5, "goodput": 5}}) is None
    assert tr.window["a"] == (5, 5)
    # Thin windows can't trigger; the WORST sagging class is reported.
    tr2 = GoodputTracker(floor=0.9, min_requests=5)
    tr2.update({})
    sag = tr2.update(
        {
            "thin": {"requests": 2, "goodput": 0},
            "bad": {"requests": 10, "goodput": 1},
            "worse": {"requests": 10, "goodput": 0},
        }
    )
    assert sag == "worse"
    # Floor 0 = trigger off.
    tr3 = GoodputTracker(floor=0.0, min_requests=1)
    assert tr3.update({"a": {"requests": 100, "goodput": 0}}) is None


def test_prefill_demand_ewma():
    pd = PrefillDemand(ewma_seconds=10.0)
    assert pd.sample(100.0) == 0.0  # first sample only arms the clock
    pd.observe(20)
    rate = pd.sample(110.0)  # inst 2.0 req/s, alpha = 1 - e^-1
    assert rate == pytest.approx(2.0 * (1 - math.exp(-1.0)), rel=1e-6)
    # Non-advancing clock: rate unchanged, counts keep accumulating.
    pd.observe(5)
    assert pd.sample(110.0) == rate
    # Idle interval decays toward zero.
    assert pd.sample(140.0) < rate
