"""Accelerator-free mock worker for control-plane tests (SURVEY.md §4
item 4: exercise executor topology, lifecycle ordering, reply-rank
selection, env replication, and failure propagation without chips)."""

from __future__ import annotations

import os

from vllm_distributed_tpu.outputs import ModelRunnerOutput


class MockWorker:
    def __init__(
        self,
        config,
        rank: int = 0,
        local_rank: int = 0,
        distributed_init_method: str | None = None,
        is_driver_worker: bool = True,
    ) -> None:
        self.config = config
        self.rank = rank
        self.distributed_init_method = distributed_init_method
        self.is_driver_worker = is_driver_worker
        self.calls: list[str] = []

    def init_device(self) -> None:
        self.calls.append("init_device")

    def load_model(self, load_format=None) -> None:
        self.calls.append("load_model")

    def determine_num_pages(self) -> int:
        # Different per rank so min() aggregation is observable.
        return 100 + self.rank

    def initialize_cache(self, num_pages: int) -> None:
        self.num_pages = num_pages

    def execute_model(self, scheduler_output) -> ModelRunnerOutput | None:
        if not self.is_driver_worker:
            return None
        out = ModelRunnerOutput()
        for req_id in scheduler_output.num_scheduled_tokens:
            out.sampled_token_ids[req_id] = [42]
        return out

    def check_health(self) -> bool:
        return True

    def get_rank_and_env(self, var: str) -> tuple[int, str | None]:
        return self.rank, os.environ.get(var)

    def get_lifecycle(self) -> list[str]:
        return list(self.calls)
