"""Accelerator-free mock worker for control-plane tests (SURVEY.md §4
item 4: exercise executor topology, lifecycle ordering, reply-rank
selection, env replication, and failure propagation without chips).

Fault hooks: ``inject_fault`` arms deterministic failures on NON-driver
workers only (the worker lives in the agent process, so transport-level
modes reach the agent's process-global FaultInjector installed when the
agent runs with VDT_FAULT_INJECTION=1):

- worker faults:    ``hang_execute``, ``die_in_execute`` — fire on the
                    next execute_model/dispatch_model;

Token modes: by default every step samples the constant 42 (topology
tests only care that A token arrived).  With ``VDT_MOCK_TOKEN_SEQ=1``
the worker instead samples a deterministic function of the request's
absolute position — token i equals the total token count before it — so
recovery/replay tests can assert bit-identical continuations: a request
replayed as prompt+emitted-prefix continues with exactly the tokens an
uninterrupted run would have produced, and any replay bug (dropped,
duplicated, or restarted-from-scratch tokens) changes the sequence.
``VDT_MOCK_TOKEN_SEQ=seq:t0,t1,...`` generalizes this to token i =
L[i mod len(L)] so speculative-decoding tests (ISSUE 11) can force
full-accept (a periodic history the n-gram proposer predicts exactly),
full-reject (a prompt whose recurring n-gram continues differently
than the emitted stream), and mixed-acceptance batches — the mock
verifies drafts against the same function, accepting the longest
matching prefix plus one bonus token, exactly like the real greedy
accept kernel.

``VDT_MOCK_HBM_PASS_SECONDS`` (default 0) simulates memory-bound
device time per step as cost × HBM passes: a fused decode window of K
micro-steps streams weights+KV K times, a speculative verify window
streams them ONCE — the roofline asymmetry the spec-decode bench gate
measures deterministically without chips.
- transport faults: ``drop_writes`` / ``blackhole_writes`` /
                    ``corrupt_writes`` / ``delay_writes`` / ``hang_writes``
                    — armed with a small ``after_writes`` budget so the
                    arming RPC's own reply frame (and at most one
                    concurrent pong) escapes before the fault engages.
"""

from __future__ import annotations

import concurrent.futures
import os
import queue
import time

from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.outputs import ModelRunnerOutput
from vllm_distributed_tpu.utils import next_power_of_2, run_method
from vllm_distributed_tpu.worker.telemetry import DeviceTelemetry

# Simulated device time per fused dispatch in the two-phase protocol
# (per-process override: VDT_MOCK_STEP_SECONDS — the dispatch
# microbench shrinks it to put driver overhead and device time in the
# production regime).
MOCK_STEP_SECONDS = 0.3

_TRANSPORT_FAULTS = {
    "drop_writes": "drop",
    "blackhole_writes": "blackhole",
    "corrupt_writes": "corrupt",
    "delay_writes": "delay",
    "hang_writes": "hang",
}


class MockWorker:
    def __init__(
        self,
        config,
        rank: int = 0,
        local_rank: int = 0,
        distributed_init_method: str | None = None,
        is_driver_worker: bool = True,
    ) -> None:
        self.config = config
        self.rank = rank
        self.distributed_init_method = distributed_init_method
        self.is_driver_worker = is_driver_worker
        self.calls: list[str] = []
        self._deferred: queue.Queue = queue.Queue()
        self._fault: str | None = None
        # (event, step_id, monotonic time) — lets tests assert that
        # dispatch N+1 reached this worker before fetch N completed.
        self.timeline: list[tuple[str, int, float]] = []
        # Deterministic position-based sampling (see module docstring):
        # "1" -> token i = i; "seq:a,b,c" -> token i = L[i % len(L)].
        mode = os.environ.get("VDT_MOCK_TOKEN_SEQ", "")
        self._seq_mode = mode == "1" or mode.startswith("seq:")
        self._seq_list: list[int] | None = (
            [int(x) for x in mode[4:].split(",")]
            if mode.startswith("seq:")
            else None
        )
        # req_id -> {"total": tokens known, "computed": KV computed}.
        self._seq_state: dict[str, dict[str, int]] = {}
        # Simulated memory-bound device time: cost per weights+KV HBM
        # pass (fused decode pays one per micro-step, a spec verify
        # window pays ONE for the whole window).
        self._hbm_pass_seconds = float(
            os.environ.get("VDT_MOCK_HBM_PASS_SECONDS", "0")
        )
        # Simulated device time per blocking execute_model (recovery
        # tests need a stream slow enough to kill mid-generation).
        self._execute_sleep = float(
            os.environ.get("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0")
        )
        self._step_seconds = float(
            os.environ.get(
                "VDT_MOCK_STEP_SECONDS", str(MOCK_STEP_SECONDS)
            )
        )
        # Simulated XLA-compile accounting (ISSUE 12): the real runner
        # compiles one program per (kind, shape-bucket) key; the mock
        # mirrors that with the scheduler-visible power-of-2 token
        # bucket so tests can induce and observe a "recompile" without
        # chips.  Same DeviceTelemetry ledger + snapshot wire format.
        self.telemetry = DeviceTelemetry()
        self._compiled_buckets: set[str] = set()
        # Simulated per-scheduled-token device time (seconds): makes
        # prefill cost proportional to chunk length, so prefix-cache /
        # restore ablations measure a real warm-TTFT delta without
        # chips.
        self._token_seconds = float(
            os.environ.get("VDT_MOCK_TOKEN_SECONDS", "0")
        )
        # Tiered-KV simulation (ISSUE 14): the mock "writes" actual
        # token ids into a per-page store as steps advance, mirrors
        # spill/restore spans between the page store and a host dict,
        # and VERIFIES on every prefix-cache admission (new request
        # with num_computed_tokens > 0) that the attached pages hold
        # exactly the prompt's tokens — so any protocol bug (stale page
        # handed out as a hit, restore landing after use, spill
        # capturing after overwrite, slot reuse races) fails loudly
        # instead of silently passing the trivially-deterministic
        # output checks.
        self._kv_page_size = config.cache_config.page_size
        self._kv_pages: dict[int, list] = {}
        self._kv_host: dict[int, list] = {}
        self._kv_req: dict[str, dict] = {}

    # ---- fault injection ----
    def inject_fault(
        self, name: str, value: float = 1.0, after_writes: int = 1
    ) -> str:
        """Arm one fault on the remote worker; no-op on the driver (the
        fault under test is always a REMOTE host misbehaving)."""
        if self.is_driver_worker:
            return "driver-noop"
        if name in _TRANSPORT_FAULTS:
            from vllm_distributed_tpu.distributed.rpc_transport import (
                get_global_injector,
            )

            injector = get_global_injector()
            assert injector is not None, (
                "transport faults need the agent started with "
                "VDT_FAULT_INJECTION=1"
            )
            injector.arm(
                _TRANSPORT_FAULTS[name], value, after_writes=after_writes
            )
            return "armed"
        assert name in ("hang_execute", "die_in_execute"), name
        self._fault = name
        return "armed"

    def _maybe_fault(self) -> None:
        fault, self._fault = self._fault, None
        if fault == "hang_execute":
            time.sleep(3600)  # wedged device program; agent proc is
            # terminated by the test, the thread never outlives it
        elif fault == "die_in_execute":
            os._exit(17)  # crash mid-RPC: no goodbye, just EOF

    def init_device(self) -> None:
        self.calls.append("init_device")

    def load_model(self, load_format=None) -> None:
        self.calls.append("load_model")

    def determine_num_pages(self) -> int:
        # An explicit pool size wins (tiering/ablation tests constrain
        # it); otherwise different per rank so min() aggregation is
        # observable.
        if self.config.cache_config.num_pages is not None:
            return self.config.cache_config.num_pages
        return 100 + self.rank

    def initialize_cache(self, num_pages: int) -> None:
        self.num_pages = num_pages

    def _tok(self, pos: int) -> int:
        """Deterministic token at absolute position ``pos``."""
        if self._seq_list is not None:
            return self._seq_list[pos % len(self._seq_list)]
        return pos

    def _hbm_passes(self, scheduler_output) -> int:
        """Weights+KV HBM passes one dispatch costs: a fused decode
        window pays one per micro-step, a spec verify window ONE for
        the whole window (the memory-bound asymmetry spec decode
        exploits)."""
        if getattr(scheduler_output, "draft_token_ids", None):
            return 1
        return max(getattr(scheduler_output, "decode_steps", 1) or 1, 1)

    def _simulate_device(self, scheduler_output) -> None:
        self._simulate_compile(scheduler_output)
        if self._hbm_pass_seconds:
            time.sleep(
                self._hbm_pass_seconds * self._hbm_passes(scheduler_output)
            )
        if self._token_seconds:
            time.sleep(
                self._token_seconds
                * scheduler_output.total_num_scheduled_tokens
            )

    # ---- tiered-KV simulation (ISSUE 14) ----
    def _apply_kv_ops(self, so) -> None:
        """Mirror the real runner's span application order: all spills
        (page store -> host dict), then all restores (host dict -> page
        store, slot consumed).  A restore from a missing slot is a
        protocol violation and raises."""
        ps = self._kv_page_size
        for page, slot in getattr(so, "kv_spill_ops", None) or []:
            self._kv_host[slot] = list(
                self._kv_pages.get(page, [None] * ps)
            )
        for slot, page in getattr(so, "kv_restore_ops", None) or []:
            self._kv_pages[page] = self._kv_host.pop(slot)

    def _kv_track(self, so, sampled: dict[str, list[int]]) -> None:
        """Write this step's token ids into the simulated page store
        and VERIFY prefix-cache admissions against it (see __init__).
        getattr-defensive: topology tests drive the mock with minimal
        hand-built payloads that may omit scheduler-only fields."""
        ps = self._kv_page_size
        finished = getattr(so, "finished_req_ids", None) or []
        preempted = getattr(so, "preempted_req_ids", None) or []
        for rid in finished + preempted:
            self._kv_req.pop(rid, None)
        for nr in getattr(so, "new_requests", None) or []:
            st = {
                "tokens": list(nr.prompt_token_ids),
                "pages": list(nr.page_ids),
                "computed": nr.num_computed_tokens,
            }
            self._kv_req[nr.req_id] = st
            for pos in range(nr.num_computed_tokens):
                page = st["pages"][pos // ps]
                row = self._kv_pages.get(page)
                got = row[pos % ps] if row is not None else None
                want = st["tokens"][pos]
                if got != want:
                    raise RuntimeError(
                        f"prefix-cache KV mismatch for {nr.req_id}: "
                        f"pos {pos} (page {page}) holds {got!r}, "
                        f"prompt says {want!r} — the allocator served "
                        "a stale or mis-restored page as a hit"
                    )
        for c in getattr(so, "cached_requests", None) or []:
            st = self._kv_req.get(c.req_id)
            if st is not None:
                st["pages"].extend(c.new_page_ids)
        drafts = getattr(so, "draft_token_ids", None) or {}
        for rid, n in (
            getattr(so, "num_scheduled_tokens", None) or {}
        ).items():
            st = self._kv_req.get(rid)
            if st is None:
                continue  # hand-built test payloads / unknown requests
            emitted = sampled.get(rid, [])
            st["tokens"].extend(emitted)
            # Spec verify windows advance by the EMITTED count (the
            # rejected-draft rows are overwritten in place and never
            # reach the prefix index); everything else by the scheduled
            # width, clamped to known tokens like registrable_tokens.
            adv = len(emitted) if rid in drafts else n
            end = min(st["computed"] + adv, len(st["tokens"]))
            for pos in range(st["computed"], end):
                page_i = pos // ps
                if page_i >= len(st["pages"]):
                    break
                page = st["pages"][page_i]
                row = self._kv_pages.get(page)
                if row is None or len(row) != ps:
                    row = [None] * ps
                    self._kv_pages[page] = row
                row[pos % ps] = st["tokens"][pos]
            st["computed"] += adv

    # ---- KV-page export/import (disaggregated prefill, ISSUE 15) ----
    # The mock's "KV" is the simulated page-content store (token ids per
    # page row), so a handed-off page carries exactly the content the
    # decode-side admission verification checks against the prompt — a
    # transfer that reorders, corrupts, or half-applies pages fails
    # loudly.  Two synthetic "layers" (identical rows) exercise the
    # per-layer chunking + completeness contract without chips.
    MOCK_KV_LAYERS = 2

    def export_kv_pages(
        self, page_ids: list[int], layer_start: int, layer_count: int
    ) -> dict | None:
        if not self.is_driver_worker:
            return None
        import hashlib
        import json

        ps = self._kv_page_size
        rows = [
            list(self._kv_pages.get(p, [None] * ps)) for p in page_ids
        ]
        data = json.dumps(rows).encode()
        checksum = hashlib.sha256(data).hexdigest()
        start = max(int(layer_start), 0)
        end = min(start + max(int(layer_count), 0), self.MOCK_KV_LAYERS)
        return {
            "num_layers": self.MOCK_KV_LAYERS,
            "layers": [
                {
                    "index": i,
                    "num_layers": self.MOCK_KV_LAYERS,
                    "data": data,
                    "checksum": checksum,
                }
                for i in range(start, end)
            ],
        }

    def import_kv_pages(
        self, page_ids: list[int], layers: list[dict]
    ) -> dict | None:
        if not self.is_driver_worker:
            return None
        import hashlib
        import json

        for layer in layers:
            data = layer["data"]
            if hashlib.sha256(data).hexdigest() != layer["checksum"]:
                return {
                    "ok": False,
                    "error": (
                        f"kv transfer checksum mismatch on layer "
                        f"{layer.get('index')}"
                    ),
                }
            rows = json.loads(data)
            for page, row in zip(page_ids, rows):
                self._kv_pages[page] = list(row)
        return {"ok": True}

    def get_kv_tier_info(self) -> dict | None:
        if not self.is_driver_worker:
            return None
        page_bytes = 4096  # deterministic stand-in for the gauge scale
        return {
            "page_bytes": page_bytes,
            "host_slots": len(self._kv_host),
            "host_bytes": len(self._kv_host) * page_bytes,
        }

    def _simulate_compile(self, scheduler_output) -> None:
        """Record one simulated XLA compile per new (kind, token-bucket)
        shape key — the mock analog of ModelRunner._observed_call."""
        if getattr(scheduler_output, "draft_token_ids", None):
            kind = "spec"
        elif (getattr(scheduler_output, "decode_steps", 1) or 1) > 1:
            kind = "decode"
        else:
            kind = "prefill"
        bucket = next_power_of_2(
            max(scheduler_output.total_num_scheduled_tokens, 16)
        )
        key = f"{kind}:t={bucket}"
        if key not in self._compiled_buckets:
            self._compiled_buckets.add(key)
            self.telemetry.record_compile(kind, 0.001, key)
        self.telemetry.record_step(
            max(self._step_seconds, 1e-6),
            scheduler_output.total_num_scheduled_tokens * 1024,
            819e9,
        )

    def get_device_telemetry(self) -> dict | None:
        if not self.is_driver_worker:
            return None
        snap = self.telemetry.snapshot(probe_memory=False)
        # Deterministic stand-in HBM numbers so the gauges move in tests.
        snap["hbm_live_bytes"] = 1 << 30
        snap["hbm_limit_bytes"] = 16 << 30
        return snap

    def _sample(self, scheduler_output) -> dict[str, list[int]]:
        """One sampled token per scheduled request: constant 42, or the
        deterministic position stream under VDT_MOCK_TOKEN_SEQ.  Spec
        verify windows (draft_token_ids) emit the longest draft prefix
        matching the stream plus one bonus token — the mock analog of
        ops/sampling.spec_greedy_accept, so greedy bit-identity holds
        by the same argument as on the real runner."""
        drafts = getattr(scheduler_output, "draft_token_ids", None) or {}
        if not self._seq_mode:
            out: dict[str, list[int]] = {}
            for req_id in scheduler_output.num_scheduled_tokens:
                d = drafts.get(req_id)
                if d:
                    a = 0
                    while a < len(d) and d[a] == 42:
                        a += 1
                    out[req_id] = [42] * (a + 1)
                else:
                    out[req_id] = [42]
            return out
        # Drop finished/preempted state BEFORE seeding new requests —
        # the real worker's _apply_scheduler_deltas order — so a step
        # that both finishes request id X and re-admits a new X keeps
        # the new state.
        for req_id in (
            scheduler_output.finished_req_ids
            + scheduler_output.preempted_req_ids
        ):
            self._seq_state.pop(req_id, None)
        for nr in scheduler_output.new_requests:
            self._seq_state[nr.req_id] = {
                "total": len(nr.prompt_token_ids),
                "computed": nr.num_computed_tokens,
            }
        sampled: dict[str, list[int]] = {}
        for req_id, n in scheduler_output.num_scheduled_tokens.items():
            st = self._seq_state.get(req_id)
            if st is None:
                continue
            d = drafts.get(req_id)
            if d is not None:
                # Spec verify window: accept the longest draft prefix
                # matching the deterministic stream, emit it plus one
                # bonus token, and advance by the EMITTED count (the
                # scheduler reconciles the same way).
                pos0 = st["total"]
                a = 0
                while a < len(d) and d[a] == self._tok(pos0 + a):
                    a += 1
                emitted = [self._tok(pos0 + j) for j in range(a + 1)]
                st["total"] += len(emitted)
                st["computed"] = st["total"] - 1
                sampled[req_id] = emitted
                continue
            st["computed"] += n
            if st["computed"] >= st["total"]:
                # Prompt fully prefetched: sample.  The token IS a
                # function of the absolute position, so a replayed
                # request (longer prompt, same total) continues the
                # identical sequence.  A fused decode window (num_new
                # > 1, engine num_decode_steps > 1) emits one position
                # token per micro-step, exactly like the real worker's
                # scan.
                k = st["computed"] - st["total"] + 1
                sampled[req_id] = [
                    self._tok(p)
                    for p in range(st["total"], st["total"] + k)
                ]
                st["total"] += k
        return sampled

    def execute_model(self, scheduler_output) -> ModelRunnerOutput | None:
        self._maybe_fault()
        if self._execute_sleep:
            time.sleep(self._execute_sleep)
        t0 = time.perf_counter()
        self._apply_kv_ops(scheduler_output)
        tier_s = time.perf_counter() - t0
        self._simulate_device(scheduler_output)
        sampled = self._sample(scheduler_output)
        self._kv_track(scheduler_output, sampled)
        if not self.is_driver_worker:
            return None
        out = ModelRunnerOutput()
        out.sampled_token_ids = sampled
        out.kv_tier_seconds = tier_s
        return out

    # ---- two-phase step (cross-RPC pipelining) ----
    def dispatch_model(self, scheduler_output) -> int:
        self._maybe_fault()
        self.timeline.append(
            ("dispatch", scheduler_output.step_id, time.monotonic())
        )
        self._deferred.put(scheduler_output)
        return scheduler_output.step_id

    def fetch_results(self, step_id: int) -> ModelRunnerOutput | None:
        so = self._deferred.get(timeout=10)
        assert so.step_id == step_id, (so.step_id, step_id)
        time.sleep(self._step_seconds)  # pretend the device is busy
        self._apply_kv_ops(so)  # FIFO order == frame order
        self._simulate_device(so)
        self.timeline.append(("fetch_done", step_id, time.monotonic()))
        sampled = self._sample(so)
        self._kv_track(so, sampled)
        if not self.is_driver_worker:
            return None
        out = ModelRunnerOutput()
        out.sampled_token_ids = sampled
        return out

    def get_timeline(self) -> list[tuple[str, int, float]]:
        return list(self.timeline)

    def check_health(self) -> bool:
        return True

    def shutdown(self) -> None:
        pass

    def get_rank_and_env(self, var: str) -> tuple[int, str | None]:
        return self.rank, os.environ.get(var)

    def get_lifecycle(self) -> list[str]:
        return list(self.calls)


class MockUniProcExecutor(Executor):
    """In-process single-worker executor over MockWorker: the lightest
    way to boot a whole AsyncLLM + api_server 'replica' without chips or
    agent processes (router tests and chaos_soak --replicas spin up N
    of these behind the router).  Honors VDT_MOCK_TOKEN_SEQ /
    VDT_MOCK_EXECUTE_SLEEP_SECONDS like the multihost mock deployments.
    """

    def _init_executor(self) -> None:
        self.worker = MockWorker(
            self.config, rank=0, is_driver_worker=True
        )
        self.collective_rpc("init_device")
        self.collective_rpc("load_model")

    def collective_rpc(
        self,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        unique_reply_rank: int | None = None,
        non_block: bool = False,
        timeout: float | None = None,
    ):
        result = run_method(self.worker, method, args, kwargs or {})
        if non_block:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            fut.set_result(result)
            return fut
        return result if unique_reply_rank is not None else [result]
