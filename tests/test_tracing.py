"""End-to-end request tracing (ISSUE 5).

Unit level: tracer semantics (ids, nesting, ring bound, clock-offset
adoption, the allocation-free no-op path) and trace integrity (children
nest inside parents, per-host monotonic timestamps, no span leaks open).

E2E level (the acceptance scenario): an OpenAI-API request served by an
AsyncLLM over the mocked 2-host MultiHostExecutor produces ONE trace
containing api → queue → prefill → decode → rpc-dispatch →
worker-execute spans with consistent parent/child links across the RPC
boundary; /debug/traces serves it as JSON and as Chrome trace-event
format; the trace id is echoed in a response header; the per-stage
Prometheus histograms are fed from the same spans.  With VDT_TRACING
unset the engine loop runs the no-op tracer and /debug/traces is 404.
"""

import asyncio
import json
import multiprocessing
import os

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockWorker  # noqa: F401 (import check)
from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.distributed.agent import remote_main
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    TRACE_HEADER,
    ServerState,
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.tracing import (
    NOOP_SPAN,
    Tracer,
    get_tracer,
)
from vllm_distributed_tpu.utils import get_open_port

EPS = 0.1  # interval-nesting tolerance (separate wall-clock reads)


# ---------------------------------------------------------------------
# tracer unit tests
# ---------------------------------------------------------------------
def test_disabled_tracer_is_allocation_free_noop():
    t = Tracer()
    assert not t.enabled
    # Every open returns the SAME shared object: no per-call allocation.
    assert t.span("a") is NOOP_SPAN
    assert t.span("b", trace_root=True) is NOOP_SPAN
    with t.span("c") as sp:
        sp.set_attribute("k", "v")  # all no-ops
    t.record_span("d", 0.0, 1.0, parent=("t", "s"))
    t.event(("t", "s"), "e")
    assert t.snapshot() == []
    assert t.num_open_spans == 0


def test_ids_are_w3c_sized():
    t = Tracer().configure(True)
    with t.span("root", trace_root=True) as root:
        pass
    assert len(root.trace_id) == 32  # 128-bit hex
    assert len(root.span_id) == 16  # 64-bit hex
    int(root.trace_id, 16), int(root.span_id, 16)


def test_context_nesting_and_finalize():
    t = Tracer().configure(True, ring_size=8)
    with t.span("root", trace_root=True) as root:
        with t.span("child") as child:
            with t.span("grandchild") as grand:
                pass
        # Sibling opened after child closed inherits root again.
        with t.span("sibling") as sib:
            pass
    assert child.parent_id == root.span_id
    assert grand.parent_id == child.span_id
    assert sib.parent_id == root.span_id
    snap = t.snapshot()
    assert len(snap) == 1 and snap[0]["complete"]
    assert snap[0]["root_span_id"] == root.span_id
    assert t.num_open_spans == 0


def test_span_without_context_is_dropped():
    """Untraced work stays untraced: a child span with no parent and no
    root flag is the no-op singleton, not an orphan trace."""
    t = Tracer().configure(True)
    assert t.span("orphan") is NOOP_SPAN
    assert t.snapshot() == []


def test_ring_buffer_bounds_completed_traces():
    t = Tracer().configure(True, ring_size=4)
    ids = []
    for _ in range(10):
        with t.span("root", trace_root=True) as root:
            pass
        ids.append(root.trace_id)
    snap = t.snapshot()
    assert len(snap) == 4
    assert [tr["trace_id"] for tr in snap] == ids[-4:]  # oldest evicted
    assert t.get_trace(ids[0]) is None


def test_ring_shrink_reindexes_finished_traces():
    """Reconfiguring to a smaller ring must evict from the id index too:
    get_trace() and snapshot() stay in sync and dropped traces are freed."""
    t = Tracer().configure(True, ring_size=8)
    ids = []
    for _ in range(8):
        with t.span("root", trace_root=True) as root:
            pass
        ids.append(root.trace_id)
    t.configure(True, ring_size=2)
    assert [tr["trace_id"] for tr in t.snapshot()] == ids[-2:]
    for tid in ids[:-2]:
        assert t.get_trace(tid) is None
    for tid in ids[-2:]:
        assert t.get_trace(tid) is not None


def test_metrics_sink_cleared_only_for_owner():
    """clear_metrics_sink detaches the caller's sink but never a newer
    engine's: the slot must not outlive the engine that installed it."""
    t = Tracer()
    sink_a: list = []
    sink_b: list = []
    t.set_metrics_sink(sink_a.append)
    t.clear_metrics_sink(sink_b.append)
    assert t._metrics_sink is not None  # someone else's sink survives
    t.clear_metrics_sink(sink_a.append)
    assert t._metrics_sink is None


def test_overflow_evicted_trace_not_duplicated_on_root_close():
    """A trace force-evicted from the active set (too many in flight)
    whose root span closes afterwards must not enter the ring twice or
    desync the trace_id index."""
    t = Tracer().configure(True, ring_size=4)
    # The active set caps at max(ring_size, 64): hold 70 roots open.
    roots = [t.span(f"root{i}", trace_root=True) for i in range(70)]
    for r in roots:
        r.__enter__()
    for r in reversed(roots):
        r.__exit__(None, None, None)
    snap = t.snapshot()
    ids = [tr["trace_id"] for tr in snap]
    assert len(ids) == len(set(ids)) == 4  # no duplicates, ring bound
    for tid in ids:
        assert t.get_trace(tid) is not None  # index consistent
    assert t.num_open_spans == 0


def test_adopt_applies_clock_offset():
    t = Tracer().configure(True)
    with t.span("root", trace_root=True) as root:
        pass
    # Remote host's clock runs 5s ahead; a low-RTT sample established it.
    t.set_clock_offset("host1", 5.0, rtt=0.001)
    t.adopt(
        [
            {
                "name": "worker.execute",
                "trace_id": root.trace_id,
                "span_id": "aa" * 8,
                "parent_id": root.span_id,
                "host": "host1",
                "start": root.start + 5.0 + 0.01,
                "duration": 0.002,
                "attributes": {},
            }
        ]
    )
    trace = t.get_trace(root.trace_id)
    adopted = [s for s in trace["spans"] if s["name"] == "worker.execute"]
    assert len(adopted) == 1
    # Mapped back onto the local timeline: ~10ms after root start.
    assert abs(adopted[0]["start"] - (root.start + 0.01)) < 1e-6


def test_clock_offset_prefers_low_rtt_samples():
    t = Tracer().configure(True)
    t.set_clock_offset("h", 1.0, rtt=0.001)
    t.set_clock_offset("h", 99.0, rtt=0.5)  # congested sample: rejected
    assert t.clock_offset("h") == 1.0
    t.set_clock_offset("h", 2.0, rtt=0.0009)  # better sample: accepted
    assert t.clock_offset("h") == 2.0


def test_metrics_sink_fed_from_spans():
    observed = []
    t = Tracer().configure(True)
    t.set_metrics_sink(lambda name, dur: observed.append((name, dur)))
    with t.span("root", trace_root=True):
        pass
    t.record_span("scheduler.schedule", 0.0, 0.25, parent=None)
    t.set_metrics_sink(None)
    names = [n for n, _ in observed]
    assert "root" in names
    # record_span without a trace context still feeds the sink (stage
    # histograms populate even for untraced engine-level callers).
    assert ("scheduler.schedule", 0.25) in observed


def test_chrome_export_is_valid_trace_event_json():
    t = Tracer().configure(True)
    with t.span("root", trace_root=True, rid="r1") as root:
        with t.span("child"):
            pass
        t.event(root.ctx, "engine.preempted", request_id="r1")
    chrome = json.loads(t.to_chrome_json())
    events = chrome["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    meta = [e for e in events if e.get("ph") == "M"]
    assert len(complete) == 2 and len(instants) == 1 and meta
    for e in complete:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["trace_id"] == root.trace_id
    assert any(
        m["args"]["name"] == "driver"
        for m in meta
        if m["name"] == "process_name"
    )


def test_otlp_degrades_silently_without_sdk(monkeypatch):
    """Trace finalization must not raise when the opentelemetry SDK is
    absent (prometheus_client parallel: missing optional dep = silently
    off).  The SDK import is blocked explicitly so the test holds even
    on machines that have it installed."""
    import sys

    monkeypatch.setitem(sys.modules, "opentelemetry.sdk", None)
    t = Tracer().configure(True)
    with t.span("root", trace_root=True):
        pass
    assert t.snapshot()  # finalized fine
    assert t._otlp is False  # resolved to permanently-off


def test_open_span_accounting_survives_errors():
    """A raise inside a with-span must close it (no leaked open span) —
    the property the code-hygiene start_span lint protects."""
    t = Tracer().configure(True)
    with pytest.raises(ValueError):
        with t.span("root", trace_root=True):
            with t.span("child"):
                raise ValueError("boom")
    assert t.num_open_spans == 0
    snap = t.snapshot()
    child = next(
        s for s in snap[0]["spans"] if s["name"] == "child"
    )
    assert child["attributes"]["error"] == "ValueError"


# ---------------------------------------------------------------------
# trace_summary CLI
# ---------------------------------------------------------------------
def test_trace_summary_cli(tmp_path, capsys):
    from tools.trace_summary import main, summarize

    t = Tracer().configure(True)
    for _ in range(3):
        with t.span("root", trace_root=True) as root:
            t.record_span(
                "engine.queue", root.start, 0.01, parent=root.ctx
            )
            t.record_span(
                "engine.decode", root.start, 0.10, parent=root.ctx
            )
            t.event(root.ctx, "engine.preempted")  # instant: excluded
    traces = t.snapshot()
    stats = summarize(traces)
    assert stats["engine.queue"]["count"] == 3
    assert abs(stats["engine.decode"]["p50"] - 0.10) < 1e-9
    dump = tmp_path / "traces.json"
    dump.write_text(json.dumps({"traces": traces}))
    assert main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "engine.queue" in out and "p99(ms)" in out
    assert "3 trace(s)" in out


def test_trace_summary_overlap_view(tmp_path, capsys):
    """The overlap view pairs gather N with dispatch N+1 per host and
    counts positive gaps as stall windows."""
    from tools.trace_summary import main, overlap_summary

    def span(name, step_id, start, duration, host="host0"):
        return {
            "name": name,
            "start": start,
            "duration": duration,
            "attributes": {"step_id": step_id, "target_host": host},
        }

    # Step 0: gather ends at t=1.0.  Step 1 dispatched at t=0.9 →
    # overlapped (negative gap).  Step 1's gather ends at 2.0; step 2
    # dispatched at 2.25 → one 250ms stall window.
    spans = [
        span("executor.dispatch", 0, 0.0, 0.01),
        span("executor.gather", 0, 0.5, 0.5),
        span("executor.dispatch", 1, 0.9, 0.01),
        span("executor.gather", 1, 1.5, 0.5),
        span("executor.dispatch", 2, 2.25, 0.01),
        span("executor.gather", 2, 2.5, 0.5),
    ]
    traces = [{"trace_id": "t0", "spans": spans}]
    overlap = overlap_summary(traces)
    assert overlap is not None
    assert overlap["steps"] == 2
    assert overlap["stall_windows"] == 1
    assert abs(overlap["gap_max"] - 0.25) < 1e-9
    assert overlap["gap_p50"] < 0.25  # the overlapped pair is negative
    # Spans without step ids (pre-overlap dumps) → no overlap section.
    legacy = [{"trace_id": "t1", "spans": [
        {"name": "executor.dispatch", "start": 0.0, "duration": 0.01,
         "attributes": {"target_host": "host0"}},
    ]}]
    assert overlap_summary(legacy) is None
    dump = tmp_path / "traces.json"
    dump.write_text(json.dumps({"traces": traces}))
    assert main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "stall_windows  : 1" in out
    assert "dispatch overlap" in out


def test_stage_order_covers_every_pipeline_span():
    """Regression (ISSUE 20 satellite): every pipeline-stage span the
    code records must appear in trace_summary's ``_STAGE_ORDER``, so a
    new span kind cannot silently fall off (or to the bottom of) the
    latency table.  ``engine.kv_handoff`` and ``router.handoff`` did
    exactly that.  Scans the package by AST for literal names passed
    to ``span()`` / ``record_span()`` / ``_record_stage()``."""
    import ast
    import pathlib

    from tools.trace_summary import _STAGE_ORDER

    # Control-plane spans that are deliberately not in the
    # request-pipeline table (they still print, alphabetically).
    non_pipeline = {"router.reconnect"}

    pkg = pathlib.Path(__file__).resolve().parents[1] / "vllm_distributed_tpu"
    recorded: set[str] = set()
    for path in pkg.rglob("*.py"):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if attr in ("span", "record_span"):
                arg_idx = 0
            elif attr == "_record_stage":
                arg_idx = 1  # (req, name, ...)
            else:
                continue
            if len(node.args) <= arg_idx:
                continue
            arg = node.args[arg_idx]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if "." in arg.value:
                    recorded.add(arg.value)
    # Marker stages recorded as instant events on a parent span.
    recorded.add("router.handoff")
    assert recorded, "AST scan found no recorded spans — scanner broken?"
    missing = recorded - set(_STAGE_ORDER) - non_pipeline
    assert not missing, (
        f"span kinds missing from trace_summary._STAGE_ORDER: "
        f"{sorted(missing)} — add them (or to the non_pipeline "
        "allowlist if they are not request-pipeline stages)"
    )
    assert "engine.kv_handoff" in _STAGE_ORDER
    assert "router.handoff" in _STAGE_ORDER


def test_trace_summary_marker_stage_rows():
    """Stages recorded as instant events (router.handoff) get a
    count-only row instead of vanishing."""
    from tools.trace_summary import format_table, summarize

    traces = [{
        "trace_id": "t0",
        "spans": [
            {"name": "router.request", "start": 0.0, "duration": 0.2},
            {"name": "router.handoff", "start": 0.1, "duration": None},
            {"name": "engine.preempted", "start": 0.1, "duration": None},
        ],
    }]
    stats = summarize(traces)
    assert stats["router.handoff"]["count"] == 1
    assert stats["router.handoff"]["p50"] is None
    # Non-stage markers stay excluded, as before.
    assert "engine.preempted" not in stats
    table = format_table(stats)
    assert "router.handoff" in table
    # The marker row renders dashes, ordered right after router.request.
    lines = table.splitlines()
    assert lines.index(
        next(ln for ln in lines if ln.startswith("router.handoff"))
    ) == lines.index(
        next(ln for ln in lines if ln.startswith("router.request"))
    ) + 1


# ---------------------------------------------------------------------
# engine no-op path + /debug/traces while disabled
# ---------------------------------------------------------------------
def test_engine_step_loop_runs_noop_tracer_when_unset(tmp_path, monkeypatch):
    monkeypatch.delenv("VDT_TRACING", raising=False)
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    tracer = get_tracer()
    assert engine.tracer is tracer and not tracer.enabled
    tracer.reset()
    engine.add_request(
        "r0",
        prompt_token_ids=[1, 5, 9],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=4, ignore_eos=True
        ),
    )
    while engine.has_unfinished_requests():
        engine.step()
    engine.shutdown()
    # The whole run went through the no-op path: same singleton span,
    # nothing recorded, nothing open.
    assert tracer.span("x") is NOOP_SPAN
    assert tracer.snapshot() == []
    assert tracer.num_open_spans == 0


def test_debug_traces_404_when_disabled():
    get_tracer().configure(False)
    state = ServerState(engine=None, model_name="x", max_model_len=1)

    async def run():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.get("/debug/traces")
            assert r.status == 404
            body = await r.json()
            assert "VDT_TRACING" in body["message"]
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(run())


def test_debug_traces_rejects_negative_limit():
    tracer = get_tracer().configure(True)
    tracer.reset()
    state = ServerState(engine=None, model_name="x", max_model_len=1)

    async def run():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.get("/debug/traces?limit=-1")
            assert r.status == 400
            body = await r.json()
            assert "non-negative" in body["message"]
        finally:
            await client.close()

    try:
        asyncio.new_event_loop().run_until_complete(run())
    finally:
        tracer.configure(False)


# ---------------------------------------------------------------------
# E2E acceptance: api → queue → prefill → decode → dispatch → worker
# ---------------------------------------------------------------------
class TracedMultiHostExecutor(MultiHostExecutor):
    worker_cls = "tests.mock_worker.MockWorker"


def _agent_with_env(port, env):
    for k, v in (env or {}).items():
        os.environ[k] = v
    remote_main("127.0.0.1", port)


@pytest.fixture
def traced_app(tmp_path, monkeypatch):
    """OpenAI app over AsyncLLM over the mocked 2-host executor with
    tracing on; VDT_TRACING reaches the agent via env replication."""
    port = get_open_port()
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_TRACING", "1")
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    get_tracer().reset()
    agent = multiprocessing.Process(
        target=_agent_with_env,
        args=(
            port,
            {"VDT_ADVERTISE_NUM_CHIPS": "4", "VDT_ADVERTISE_PLATFORM": "cpu"},
        ),
        daemon=True,
    )
    agent.start()
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=write_llama_config(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            load_format="dummy",
            num_hosts=2,
            num_decode_steps=1,
            max_model_len=512,
            distributed_executor_backend=TracedMultiHostExecutor,
        )
    )
    state = init_app_state(engine, served_model_name="tiny")
    yield lambda: build_app(state)
    engine.shutdown()
    if agent.is_alive():
        agent.terminate()
    agent.join(timeout=5)
    # Don't leak an enabled global tracer into later test files.
    get_tracer().configure(False)
    get_tracer().set_metrics_sink(None)
    get_tracer().reset()


def _span_index(trace):
    by_name = {}
    for span in trace["spans"]:
        by_name.setdefault(span["name"], []).append(span)
    return by_name


def test_request_produces_one_linked_trace(traced_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={"model": "tiny", "prompt": [1, 5, 9], "max_tokens": 4},
        )
        assert r.status == 200
        trace_id = r.headers.get(TRACE_HEADER)
        assert trace_id and len(trace_id) == 32

        r = await client.get("/debug/traces")
        assert r.status == 200
        traces = (await r.json())["traces"]
        trace = next(
            t for t in traces if t["trace_id"] == trace_id
        )
        assert trace["complete"]
        by_name = _span_index(trace)

        # The acceptance chain, all in ONE trace.
        for name in (
            "api.request",
            "engine.queue",
            "engine.prefill",
            "engine.decode",
            "scheduler.schedule",
            "executor.dispatch",
            "executor.gather",
            "worker.execute",
        ):
            assert name in by_name, (name, sorted(by_name))

        root = by_name["api.request"][0]
        assert root["parent_id"] is None
        assert root["span_id"] == trace["root_span_id"]
        span_ids = {s["span_id"] for s in trace["spans"]}

        # queue/prefill/decode parent to the root; stages tile the
        # request: queue ends where prefill starts, prefill where
        # decode starts, all inside the root interval.
        stages = {}
        for name in ("engine.queue", "engine.prefill", "engine.decode"):
            (span,) = by_name[name]
            assert span["parent_id"] == root["span_id"]
            stages[name] = span
        q, p, d = (
            stages["engine.queue"],
            stages["engine.prefill"],
            stages["engine.decode"],
        )
        assert abs(q["start"] + q["duration"] - p["start"]) < EPS
        assert abs(p["start"] + p["duration"] - d["start"]) < EPS
        root_end = root["start"] + root["duration"]
        for s in (q, p, d):
            assert s["start"] >= root["start"] - EPS
            assert s["start"] + s["duration"] <= root_end + EPS

        # Cross-RPC links: every worker-side span's parent is a
        # driver-side dispatch span in this same trace.
        dispatch_ids = {
            s["span_id"] for s in by_name["executor.dispatch"]
        }
        workers = by_name["worker.execute"]
        assert all(w["host"] == "host1" for w in workers)
        assert all(w["parent_id"] in dispatch_ids for w in workers)
        # Worker replies also landed (serialize + reply marker).
        assert "worker.serialize" in by_name
        assert "worker.reply" in by_name

        # Step spans parent to the root (and dispatch carries the
        # control-message payload size).
        for s in by_name["scheduler.schedule"]:
            assert s["parent_id"] == root["span_id"]
            assert s["trace_id"] == trace_id
        assert any(
            s["attributes"].get("payload_bytes", 0) > 0
            for s in by_name["executor.dispatch"]
        )

        # Timestamps are monotonic per host: sorting any host's spans
        # by start gives non-negative durations and ordered starts.
        for host in {s["host"] for s in trace["spans"]}:
            spans = sorted(
                (s for s in trace["spans"] if s["host"] == host),
                key=lambda s: s["start"],
            )
            assert all((s["duration"] or 0.0) >= 0.0 for s in spans)

        # Every span id referenced as a parent exists in the trace
        # (except the root's None) — no dangling links across the
        # RPC boundary.
        for s in trace["spans"]:
            if s["parent_id"] is not None and s["name"] not in (
                "api.request",
            ):
                assert s["parent_id"] in span_ids, s

        # Chrome export: valid trace-event JSON with both hosts.
        r = await client.get("/debug/traces?format=chrome")
        assert r.status == 200
        chrome = json.loads(await r.text())
        assert chrome["traceEvents"]
        process_names = {
            e["args"]["name"]
            for e in chrome["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert {"driver", "host1"} <= process_names

        # Per-stage Prometheus histograms fed from the same spans.
        r = await client.get("/metrics")
        text = await r.text()
        for family in (
            "vllm:request_queue_time_seconds_count",
            "vllm:request_prefill_time_seconds_count",
            "vllm:request_decode_time_seconds_count",
            "vllm:step_schedule_time_seconds_count",
            "vllm:step_dispatch_time_seconds_count",
            "vllm:step_gather_time_seconds_count",
        ):
            line = next(
                ln for ln in text.splitlines() if ln.startswith(family)
            )
            assert float(line.split()[-1]) > 0, line

        # No span leaked open once the request finished.
        assert get_tracer().num_open_spans == 0

    async def run():
        server = TestServer(traced_app())
        client = TestClient(server)
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(run())
