"""Subprocess entry for the 2-process real-model multihost test: runs the
driver half (MultiHostExecutor + real Worker, tp=2 over a 2-process
jax.distributed CPU world) and prints the greedy tokens.

Run with: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=1
          VDT_SERVER_PORT=<port> VDT_HOST_IP=127.0.0.1
          python tests/multihost_driver.py <model_dir>
"""

import json
import sys


def main() -> None:
    model_dir = sys.argv[1]
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=32,
            max_model_len=64,
            tensor_parallel_size=2,
            num_hosts=2,
            num_decode_steps=4,
            distributed_executor_backend="multihost",
        )
    )
    engine.add_request(
        "x",
        prompt_token_ids=[1, 5, 9],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=6, ignore_eos=True
        ),
    )
    toks = None
    while engine.has_unfinished_requests():
        for out in engine.step():
            toks = out.outputs[0].token_ids
    print("TOKENS=" + json.dumps(toks), flush=True)
    engine.shutdown()


if __name__ == "__main__":
    main()
