"""Elastic fleet suite (ISSUE 13): replica lifecycle + autoscaling.

Layered like the feature: pure-policy units for the autoscaler's
``decide`` (hysteresis, cooldowns, bounds, secondary triggers, on
synthetic gauge traces); ReplicaManager state-machine units over fake
child handles (spawn → health-gated warmup → routable, crash-loop
backoff + restart-budget exhaustion, scale-down drains BEFORE reap,
shutdown reaps everything); pool/metrics membership hygiene (no stale
``replica="<id>"`` series after removal); a CommandLauncher
integration over a real stdlib-only subprocess; and the acceptance
runs — a 1→3→1 resize over forked mock-uniproc replicas under
streaming load with zero lost admitted work (manual /router/scale),
and a short autoscaled resize-chaos ramp smoke
(tools/chaos_soak.run_fleet_ramp) with a SIGKILL mid-resize.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import pytest

from vllm_distributed_tpu.router.fleet import (
    AutoscalerConfig,
    Autoscaler,
    CommandLauncher,
    FleetSignals,
    ReplicaManager,
    decide,
)
from vllm_distributed_tpu.router.metrics import RouterMetrics
from vllm_distributed_tpu.router.pool import ReplicaPool

pytestmark = pytest.mark.fleet


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------
# autoscaler policy units (pure decide() on synthetic gauge traces)
# ---------------------------------------------------------------------
CFG = AutoscalerConfig(
    min_replicas=1,
    max_replicas=4,
    interval=1.0,
    up_waiting=4.0,
    down_waiting=1.0,
    up_cooldown=10.0,
    down_cooldown=30.0,
)


def _sig(routable=2, waiting=0.0, reject_rate=0.0, itl=None):
    return FleetSignals(
        routable=routable,
        waiting=waiting,
        reject_rate=reject_rate,
        itl_p99_ms=itl,
    )


def test_decide_holds_inside_hysteresis_band():
    # Between the down and up watermarks: no decision either way.
    for per in (1.0, 2.5, 4.0):
        assert decide(
            2, _sig(waiting=2 * per), CFG, 100.0, 0.0, 0.0
        ) == (2, None)


def test_decide_scales_up_on_queue_depth_and_respects_cooldown():
    hot = _sig(waiting=2 * 5.0)  # 5 waiting per replica > 4
    assert decide(2, hot, CFG, 100.0, 0.0, 0.0) == (3, "queue_depth")
    # Inside the up-cooldown window: hold even though still hot.
    assert decide(3, hot, CFG, 100.0, 95.0, 0.0) == (3, None)
    # Cooldown elapsed: another single step.
    assert decide(3, hot, CFG, 120.0, 100.0, 0.0) == (4, "queue_depth")
    # At the ceiling: clamp.
    assert decide(4, hot, CFG, 200.0, 100.0, 0.0) == (4, None)


def test_decide_scales_down_only_when_idle_and_cooled():
    idle = _sig(waiting=0.0)
    # Idle but the down-cooldown hasn't elapsed since the last down.
    assert decide(3, idle, CFG, 100.0, 0.0, 90.0) == (3, None)
    # Idle but a recent scale-UP also blocks the down (anti-flap).
    assert decide(3, idle, CFG, 100.0, 90.0, 0.0) == (3, None)
    # Both cooldowns clear: one step down.
    assert decide(3, idle, CFG, 100.0, 0.0, 0.0) == (2, "idle")
    # Never below the floor.
    assert decide(1, idle, CFG, 500.0, 0.0, 0.0) == (1, None)


def test_decide_secondary_triggers_and_bounds():
    cfg = AutoscalerConfig(
        min_replicas=1,
        max_replicas=4,
        up_waiting=4.0,
        down_waiting=1.0,
        up_cooldown=10.0,
        down_cooldown=30.0,
        max_reject_rate=0.5,
        itl_p99_ms=200.0,
    )
    # Shallow queues but a hot 429 rate: still scale up.
    assert decide(
        2, _sig(waiting=0.0, reject_rate=1.0), cfg, 100.0, 0.0, 0.0
    ) == (3, "reject_rate")
    # Shallow queues but fleet ITL p99 over target: scale up.
    assert decide(
        2, _sig(waiting=0.0, itl=350.0), cfg, 100.0, 0.0, 0.0
    ) == (3, "itl_p99")
    # A hot trigger also VETOES the idle scale-down.
    assert decide(
        2, _sig(waiting=0.0, reject_rate=1.0), cfg, 100.0, 0.0, 0.0
    )[0] >= 2
    # Out-of-bounds targets snap back.
    assert decide(0, _sig(), cfg, 0.0, 0.0, 0.0) == (1, "min_bound")
    assert decide(9, _sig(), cfg, 0.0, 0.0, 0.0) == (4, "max_bound")
    # No routable replica: signals unreadable, hold (respawn is the
    # manager's job, not a scaling decision).
    assert decide(2, _sig(routable=0), cfg, 100.0, 0.0, 0.0) == (2, None)


def test_decide_goodput_trigger():
    """ISSUE 16: a class sagging below the goodput floor is a scale-up
    trigger of its own (DistServe's goodput-chasing argument), with a
    class-named reason; floor 0 keeps the trigger off even when the
    tracker reports a sag."""
    cfg = AutoscalerConfig(
        min_replicas=1,
        max_replicas=4,
        up_waiting=4.0,
        down_waiting=1.0,
        up_cooldown=10.0,
        down_cooldown=30.0,
        goodput_floor=0.9,
    )
    sag = _sig(waiting=0.0)
    sag.goodput_sag = "interactive"
    assert decide(2, sag, cfg, 100.0, 0.0, 0.0) == (
        3,
        "goodput:interactive",
    )
    # A sagging class also vetoes the idle scale-down.
    assert decide(2, sag, cfg, 100.0, 0.0, 0.0)[0] >= 2
    off = AutoscalerConfig(
        min_replicas=1, max_replicas=4, goodput_floor=0.0
    )
    assert decide(2, sag, off, 100.0, 0.0, 0.0)[1] != "goodput:interactive"


def test_tick_prefill_sizes_role_to_demand():
    """ISSUE 16 per-role autoscaling: the prefill pool target tracks
    ceil(EWMA long-prompt rate / benched per-replica rps), clamped to
    [prefill_min, prefill_max]; rps 0 keeps the loop off."""
    from vllm_distributed_tpu.router.qos import PrefillDemand

    class RoleManager:
        def __init__(self):
            self.target = 2
            self.role_targets = {"prefill": 1}
            self.calls = []

        def scale_role_to(self, role, n, reason=""):
            self.calls.append((role, n, reason))
            self.role_targets[role] = n

    def scaler_for(mgr, **cfg_kw):
        cfg_kw.setdefault("min_replicas", 1)
        cfg_kw.setdefault("max_replicas", 4)
        return Autoscaler(
            mgr,
            ReplicaPool([], allow_empty=True),
            RouterMetrics(enabled=False),
            AutoscalerConfig(**cfg_kw),
            prefill_demand=PrefillDemand(),
        )

    mgr = RoleManager()
    scaler = scaler_for(
        mgr, prefill_rps=2.0, prefill_min=1, prefill_max=3
    )
    sig = _sig()
    sig.prefill_rate = 5.0  # ceil(5 / 2) = 3
    scaler._tick_prefill(sig, now=100.0)
    assert mgr.calls == [("prefill", 3, "autoscale:prefill_demand")]
    # Demand gone: shrink back to the floor (never to zero here).
    sig.prefill_rate = 0.0
    scaler._tick_prefill(sig, now=110.0)
    assert mgr.role_targets["prefill"] == 1
    # Ceiling clamp.
    sig.prefill_rate = 100.0
    scaler._tick_prefill(sig, now=120.0)
    assert mgr.role_targets["prefill"] == 3
    # No change → no call (one-spawn-per-tick churn control).
    n_calls = len(mgr.calls)
    scaler._tick_prefill(sig, now=130.0)
    assert len(mgr.calls) == n_calls
    # rps 0 = off: the role target is whatever --fleet-prefill set.
    mgr2 = RoleManager()
    off = scaler_for(mgr2, prefill_rps=0.0)
    sig.prefill_rate = 50.0
    off._tick_prefill(sig, now=100.0)
    assert mgr2.calls == []


def test_scale_role_to_validates_and_sets_target():
    manager, _ = _manager(FakeLauncher())
    assert manager.scale_role_to("prefill", 2) == 2
    assert manager.role_targets["prefill"] == 2
    assert manager.scale_role_to("prefill", 0) == 0
    with pytest.raises(ValueError):
        manager.scale_role_to("embedding", 1)


def test_autoscaler_tick_trace_up_then_hold_then_down():
    """Drive Autoscaler.tick over a synthetic gauge trace: a burst
    scales up once per cooldown window, the idle tail scales back
    down."""

    class FakeManager:
        target = 1

        def scale_to(self, n, reason=""):
            self.target = n

    async def go():
        pool = ReplicaPool([], allow_empty=True)
        r = pool.add("http://h:1", replica_id="r1", state="healthy")
        cfg = AutoscalerConfig(
            min_replicas=1,
            max_replicas=3,
            up_waiting=2.0,
            down_waiting=0.5,
            up_cooldown=0.0,  # every tick may step in this unit
            down_cooldown=0.0,
        )
        mgr = FakeManager()
        scaler = Autoscaler(
            mgr, pool, RouterMetrics(enabled=False), cfg
        )
        r.waiting = 10.0
        assert await scaler.tick() == (2, "queue_depth")
        assert await scaler.tick() == (3, "queue_depth")
        assert await scaler.tick() == (3, None)  # at the ceiling
        r.waiting = 0.0
        assert await scaler.tick() == (2, "idle")
        assert await scaler.tick() == (1, "idle")
        assert await scaler.tick() == (1, None)  # at the floor
        assert [d["to"] for d in scaler.decisions] == [2, 3, 2, 1]

    _run(go())


# ---------------------------------------------------------------------
# manager state-machine units (fake child handles, injected probes)
# ---------------------------------------------------------------------
class FakeHandle:
    def __init__(self, pid: int, exit_code: int | None = None):
        self.pid = pid
        self._exit = exit_code  # non-None = born dead (crash-loop unit)
        self.log: list[str] = []

    def poll(self):
        return self._exit

    def terminate(self):
        self.log.append("terminate")
        if self._exit is None:
            self._exit = -15

    def kill(self):
        self.log.append("kill")
        if self._exit is None:
            self._exit = -9

    def wait(self, timeout=None):
        self.log.append("wait")
        return self._exit


class FakeLauncher:
    def __init__(self, born_dead: bool = False):
        self.born_dead = born_dead
        self.spawned: list[FakeHandle] = []

    def spawn(self, replica_id, port):
        handle = FakeHandle(
            pid=1000 + len(self.spawned),
            exit_code=1 if self.born_dead else None,
        )
        self.spawned.append(handle)
        return handle


def _manager(launcher, pool=None, **kw):
    pool = pool or ReplicaPool([], allow_empty=True)
    kw.setdefault("warmup_timeout", 5.0)
    kw.setdefault("drain_timeout", 5.0)
    kw.setdefault("check_interval", 0.01)
    kw.setdefault("max_restarts", 3)
    kw.setdefault("restart_window", 300.0)
    kw.setdefault("backoff_base", 0.0)
    kw.setdefault("backoff_cap", 0.0)
    return (
        ReplicaManager(
            pool, RouterMetrics(enabled=False), launcher, **kw
        ),
        pool,
    )


def test_spawn_health_gates_before_routable():
    """A spawned replica is NOT in the pool until its health probe
    passes; once it passes, it enters already routable."""
    probes = {"n": 0, "ok_after": 3}

    async def health_check(url):
        probes["n"] += 1
        return probes["n"] >= probes["ok_after"]

    async def go():
        manager, pool = _manager(
            FakeLauncher(), health_check=health_check
        )
        manager.scale_to(1)
        await manager._reconcile()
        (mr,) = manager.replicas
        assert mr.state == "starting"
        assert pool.replicas == []  # never routable before healthy
        await asyncio.wait_for(mr.task, timeout=5)
        assert mr.state == "ready"
        assert probes["n"] == probes["ok_after"]
        (replica,) = pool.replicas
        assert replica.url == mr.url
        assert replica.replica_id == mr.replica_id
        assert replica.routable  # healthy immediately, no poll tick
        events = [e["kind"] for e in manager.events]
        assert events == ["scale", "spawn", "ready"]
        await manager.stop(drain=False)

    _run(go())


def test_warmup_timeout_counts_as_crash():
    async def health_check(url):
        return False  # never comes up

    async def go():
        manager, pool = _manager(
            FakeLauncher(),
            health_check=health_check,
            warmup_timeout=0.05,
            max_restarts=1,
        )
        manager.scale_to(1)
        await manager._reconcile()
        (mr,) = manager.replicas
        await asyncio.wait_for(mr.task, timeout=5)
        assert manager.replicas == []
        assert pool.replicas == []
        kinds = [e["kind"] for e in manager.events]
        assert "warmup_failed" in kinds
        # The dead child was reaped (terminate/kill then wait).
        handle = mr.handle
        assert "wait" in handle.log
        await manager.stop(drain=False)

    _run(go())


def test_crash_loop_backoff_and_budget_exhaustion():
    """Born-dead children burn the restart budget, then the manager
    goes terminal (exhausted) instead of spinning; a manual resize
    clears exhaustion."""

    async def health_check(url):  # pragma: no cover - never reached
        return False

    async def go():
        launcher = FakeLauncher(born_dead=True)
        manager, pool = _manager(
            launcher, health_check=health_check, max_restarts=2
        )
        manager.scale_to(1)
        # Tick until the budget is spent (each reconcile spawns at most
        # one child and sweeps the corpse on the next pass).
        for _ in range(20):
            await manager._reconcile()
            if manager.exhausted:
                break
            await asyncio.sleep(0.01)
        assert manager.exhausted
        spawned_at_exhaustion = len(launcher.spawned)
        # Budget == max_restarts: 1 initial spawn + 2 respawns... the
        # crash path counts every death; at most max_restarts deaths
        # are forgiven, so spawn count is bounded by max_restarts + 1.
        assert spawned_at_exhaustion <= manager.max_restarts + 1
        kinds = [e["kind"] for e in manager.events]
        assert "restart_budget_exhausted" in kinds
        # Terminal: further reconciles spawn nothing.
        for _ in range(3):
            await manager._reconcile()
        assert len(launcher.spawned) == spawned_at_exhaustion
        assert pool.replicas == []
        # Operator override: an explicit resize clears exhaustion.
        manager.scale_to(1, reason="manual")
        assert not manager.exhausted
        await manager.stop(drain=False)

    _run(go())


def test_scale_down_drains_before_reap():
    """The scale-down ordering contract: /drain completes (in-flight
    work journal-migrates) BEFORE the process sees TERM/KILL, and the
    child is reaped synchronously."""
    order: list[str] = []

    async def health_check(url):
        return True

    async def drainer(url, timeout):
        order.append(f"drain:{url}")

    async def go():
        manager, pool = _manager(
            FakeLauncher(), health_check=health_check, drainer=drainer
        )
        manager.scale_to(2)
        await manager._reconcile()  # spawn 1 (one per tick)
        await manager._reconcile()  # spawn 2
        for mr in list(manager.replicas):
            await asyncio.wait_for(mr.task, timeout=5)
        assert manager.ready_count() == 2
        assert len(pool.replicas) == 2
        manager.scale_to(1)
        await manager._reconcile()
        victim = next(
            r
            for r in manager.replicas
            if r.task is not None and not r.task.done()
        )
        await asyncio.wait_for(victim.task, timeout=5)
        # The newest replica was picked, drained, then terminated.
        assert victim.replica_id == "fleet-2"
        assert order == [f"drain:{victim.url}"]
        assert victim.handle.log[0] == "terminate"
        assert "wait" in victim.handle.log  # synchronous reap
        assert manager.ready_count() == 1
        assert len(pool.replicas) == 1
        kinds = [
            (e["kind"], e["replica_id"])
            for e in manager.events
            if e["replica_id"] == victim.replica_id
        ]
        # drain strictly precedes stopped.
        assert kinds.index(("drain", victim.replica_id)) < kinds.index(
            ("stopped", victim.replica_id)
        )
        await manager.stop(drain=False)

    _run(go())


def test_manager_stop_drains_all_and_reaps():
    """Router-exit parity with the replica-side SIGTERM drain: stop()
    drains every serving replica (bounded) and reaps every child."""
    drained: list[str] = []

    async def health_check(url):
        return True

    async def drainer(url, timeout):
        drained.append(url)

    async def go():
        launcher = FakeLauncher()
        manager, pool = _manager(
            launcher, health_check=health_check, drainer=drainer
        )
        manager.scale_to(2)
        await manager._reconcile()
        await manager._reconcile()
        for mr in list(manager.replicas):
            await asyncio.wait_for(mr.task, timeout=5)
        urls = sorted(r.url for r in manager.replicas)
        # The injected drainer stands in for HTTP; stop()'s drain
        # phase only runs once a session exists (set by start()).
        manager.session = object()
        await manager.stop(drain=True)
        assert sorted(drained) == urls
        assert manager.replicas == [] and pool.replicas == []
        for handle in launcher.spawned:
            assert handle.poll() is not None  # dead
            assert "wait" in handle.log  # reaped

    _run(go())


# ---------------------------------------------------------------------
# pool + metrics membership hygiene
# ---------------------------------------------------------------------
def test_pool_membership_and_remove_hook():
    pool = ReplicaPool([], allow_empty=True)
    removed: list[str] = []
    pool.on_remove.append(lambda r: removed.append(r.replica_id))
    r = pool.add("http://h:1/", replica_id="r1", state="healthy")
    assert r.routable
    assert pool.add("http://h:1") is r  # idempotent, no dup
    assert len(pool.replicas) == 1
    assert pool.remove("http://h:1").replica_id == "r1"
    assert pool.replicas == [] and removed == ["r1"]
    assert pool.remove("http://h:1") is None  # idempotent


def test_pool_rejects_empty_unless_allowed():
    with pytest.raises(ValueError):
        ReplicaPool([])
    assert ReplicaPool([], allow_empty=True).replicas == []


def test_metrics_forget_replica_drops_series():
    metrics = RouterMetrics()
    if not metrics.enabled:
        pytest.skip("prometheus_client unavailable")
    pool = ReplicaPool([], allow_empty=True)
    pool.on_remove.append(
        lambda replica: metrics.forget_replica(replica.replica_id)
    )
    for rid in ("alive", "doomed"):
        pool.add(f"http://{rid}:1", replica_id=rid, state="healthy")
    metrics.update_replicas(pool)
    text = metrics.render().decode()
    assert 'replica_id="doomed"' in text
    pool.remove("http://doomed:1")
    metrics.update_replicas(pool)
    text = metrics.render().decode()
    # No stale series after scale-down: the doomed replica's labeled
    # rows are gone from the router's own exposition too.
    assert 'replica_id="doomed"' not in text
    assert 'replica_id="alive"' in text


def test_pool_remove_forgets_affinity_chains():
    """A removed replica's prefix-affinity chains are dropped: a
    churning autoscaled fleet must not accumulate departed replicas'
    index state (or keep steering prompts at ghosts) forever."""
    from vllm_distributed_tpu.router.app import RouterState

    state = RouterState(
        [],
        policy="affinity",
        health_interval=60.0,
        allow_empty_pool=True,
    )
    state.pool.add("http://h:1", replica_id="doomed", state="healthy")
    keys = state.index.keys_for(prompt_token_ids=list(range(32)))
    state.index.observe("doomed", keys)
    assert state.index.score(keys) == {"doomed": 32}
    state.pool.remove("http://h:1")
    assert state.index.score(keys) == {}
    assert state.index.num_blocks("doomed") == 0


def test_probe_jitter_bounded_by_interval():
    pool = ReplicaPool([], allow_empty=True, health_interval=2.0)
    assert 0 < pool._probe_jitter() <= 0.5
    pool.health_interval = 100.0
    assert pool._probe_jitter() == 1.0  # hard cap


def test_parse_ramp():
    from vllm_distributed_tpu.entrypoints.cli import parse_ramp

    assert parse_ramp("5:6,14:12,0:8") == [
        (5.0, 6.0),
        (14.0, 12.0),
        (0.0, 8.0),
    ]
    assert parse_ramp(" 2.5:1.5 ") == [(2.5, 1.5)]
    for bad in ("", "5", "5:0", "-1:5", "a:b"):
        with pytest.raises(SystemExit):
            parse_ramp(bad)


# ---------------------------------------------------------------------
# CommandLauncher over a real (stdlib-only) subprocess
# ---------------------------------------------------------------------
_HEALTH_SERVER = """
import json, sys
from http.server import BaseHTTPRequestHandler, HTTPServer


class H(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/health":
            body = json.dumps({"status": "ok"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *args):
        pass


HTTPServer(("127.0.0.1", int(sys.argv[1])), H).serve_forever()
"""


def test_command_launcher_template_validation():
    with pytest.raises(ValueError):
        CommandLauncher("vdt serve model")  # no {port}


def test_command_launcher_spawns_real_subprocess(tmp_path):
    """The --fleet-cmd path end to end: a real child process from the
    template, health-gated into the pool, then reaped on stop."""
    script = tmp_path / "health_server.py"
    script.write_text(_HEALTH_SERVER)

    async def go():
        import aiohttp

        launcher = CommandLauncher(f"{sys.executable} {script} {{port}}")
        pool = ReplicaPool(
            [], allow_empty=True, connect_timeout=2, probe_timeout=2
        )
        manager = ReplicaManager(
            pool,
            RouterMetrics(enabled=False),
            launcher,
            warmup_timeout=20.0,
            drain_timeout=1.0,
            check_interval=0.05,
            max_restarts=1,
            restart_window=300.0,
            backoff_base=0.0,
            backoff_cap=0.0,
        )
        async with aiohttp.ClientSession() as session:
            manager.session = session
            manager.scale_to(1)
            await manager._reconcile()
            (mr,) = manager.replicas
            # The child got its identity via the environment.
            assert mr.replica_id == "fleet-1"
            await asyncio.wait_for(mr.task, timeout=20)
            assert mr.state == "ready"
            assert pool.replicas[0].routable
            pid = mr.handle.pid
            await manager.stop(drain=False)
            assert manager.replicas == []
            # Synchronously reaped: the pid is gone (no zombie).
            assert mr.handle.poll() is not None
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)

    _run(go())


# ---------------------------------------------------------------------
# acceptance: 1→3→1 resize under load (forked mock-uniproc replicas)
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    from vllm_distributed_tpu.testing import write_llama_config

    return write_llama_config(
        str(tmp_path_factory.mktemp("fleet_model") / "m")
    )


MOCK_ENV = {
    "VDT_MOCK_TOKEN_SEQ": "1",
    "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.02",
}


def test_resize_1_3_1_under_load_loses_nothing(model_dir, monkeypatch):
    """The ISSUE 13 resize acceptance: scale a live fleet 1→3→1 through
    /router/scale while streaming load runs end to end.  Every admitted
    stream completes with the mock's exact position-token sequence
    (scale-downs drain + migrate, scale-ups health-gate), and no child
    outlives the router."""
    for k, v in MOCK_ENV.items():
        monkeypatch.setenv(k, v)
    from tests.mock_replica import MockReplicaLauncher
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        serve_http,
    )
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.utils import get_open_port

    max_tokens = 10
    prompt = [1, 2, 3]
    expected = list(range(len(prompt), len(prompt) + max_tokens))
    stats = {"admitted": 0, "completed": 0, "lost": 0, "mismatches": 0,
             "rejected": 0}

    async def go():
        import aiohttp

        launcher = MockReplicaLauncher(
            model_dir, extra_env=dict(MOCK_ENV), max_num_seqs=4
        )
        state = RouterState(
            [],
            policy="least_loaded",
            health_interval=0.25,
            connect_timeout=2,
            read_timeout=30,
            allow_empty_pool=True,
        )
        manager = ReplicaManager(
            state.pool,
            state.metrics,
            launcher,
            target=1,
            warmup_timeout=60,
            drain_timeout=10,
            check_interval=0.2,
            max_restarts=5,
            restart_window=3600.0,
            backoff_base=0.2,
            backoff_cap=1.0,
        )
        state.attach_fleet(manager)
        port = get_open_port()
        runner = await serve_http(
            build_router_app(state), host="127.0.0.1", port=port
        )
        url = f"http://127.0.0.1:{port}"
        timeout = aiohttp.ClientTimeout(total=None, sock_read=60)

        async def one_stream(session, tag):
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status == 429:
                        stats["rejected"] += 1
                        return
                    if resp.status != 200:
                        stats["lost"] += 1
                        return
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            break
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                    else:
                        stats["completed"] += 1
            except Exception:  # noqa: BLE001 — an admitted stream erroring IS lost work
                stats["lost"] += 1

        async def load(session, stop):
            """Closed-loop background load: 3 lanes of back-to-back
            streams, riding across every resize."""

            async def lane(j):
                k = 0
                while not stop.is_set():
                    await one_stream(session, f"lane{j}-{k}")
                    k += 1

            await asyncio.gather(*(lane(j) for j in range(3)))

        async def wait_until(cond, timeout_s, what):
            deadline = time.monotonic() + timeout_s
            while not cond():
                assert time.monotonic() < deadline, (
                    f"timed out waiting for {what}: "
                    f"{manager.snapshot()['replicas']}"
                )
                await asyncio.sleep(0.1)

        async with aiohttp.ClientSession() as session:
            await wait_until(
                lambda: manager.ready_count() >= 1, 60, "first replica"
            )
            stop = asyncio.Event()
            load_task = asyncio.ensure_future(load(session, stop))
            try:
                await asyncio.sleep(0.5)
                async with session.post(
                    f"{url}/router/scale", json={"replicas": 3}
                ) as r:
                    assert r.status == 200, await r.text()
                await wait_until(
                    lambda: manager.ready_count() == 3, 90, "scale to 3"
                )
                await asyncio.sleep(1.0)  # serve a while at 3
                async with session.post(
                    f"{url}/router/scale", json={"replicas": 1}
                ) as r:
                    assert r.status == 200, await r.text()
                await wait_until(
                    lambda: len(manager.active()) == 1
                    and manager.ready_count() == 1,
                    90,
                    "scale to 1",
                )
                await asyncio.sleep(0.5)  # serve a while back at 1
            finally:
                stop.set()
                await asyncio.wait_for(load_task, timeout=90)
            # Membership hygiene end to end: the merged exposition
            # carries exactly the one live replica.
            async with session.get(f"{url}/metrics") as r:
                exposition = await r.text()
            live_id = manager.replicas[0].replica_id
            import re

            labeled = set(
                re.findall(r'replica(?:_id)?="([^"]+)"', exposition)
            )
            assert labeled == {live_id}, labeled
            async with session.get(f"{url}/router/fleet") as r:
                fleet = await r.json()
        await runner.cleanup()
        return fleet, launcher

    fleet, launcher = _run(go())
    # Zero lost admitted work, zero token mismatches, through both
    # resizes.
    assert stats["lost"] == 0, (stats, fleet["events"])
    assert stats["mismatches"] == 0, stats
    assert stats["admitted"] == stats["completed"] > 0
    # Every scale-down drained before it stopped.
    ready_ids = {
        e["replica_id"] for e in fleet["events"] if e["kind"] == "ready"
    }
    drained: set[str] = set()
    for e in fleet["events"]:
        if e["kind"] == "drain":
            drained.add(e["replica_id"])
        elif e["kind"] == "stopped" and e["replica_id"] in ready_ids:
            assert e["replica_id"] in drained, fleet["events"]
    # No child outlived the router.
    assert launcher.leaked() == []


def test_fleet_ramp_smoke(model_dir):
    """Short autoscaled resize-chaos ramp (tools/chaos_soak.py --ramp):
    rate sweep up and down with a SIGKILL mid-resize — replica count
    follows the ramp within bounds, zero lost admitted work, zero
    token mismatches, drain-before-stop on every scale-down."""
    from tools.chaos_soak import run_fleet_ramp

    report = run_fleet_ramp(
        max_replicas=3,
        ramp="4:3,12:8,1:4,0:8",
        max_tokens=10,
        kill_mid_resize=True,
        autoscale_interval=0.5,
        up_cooldown=1.0,
        down_cooldown=2.0,
    )
    assert report["bounded"], report
    assert report["lost"] == 0 and report["mismatches"] == 0
    assert report["scaled_up"] and report["scaled_down"]
    assert report["max_ready_observed"] <= 3
    assert report["drained_before_stop"]
    assert report["leaked_children"] == []


def test_disagg_autoscale_ramp_smoke(model_dir):
    """Short per-role autoscale ramp (tools/chaos_soak.py
    --disagg-autoscale, the ISSUE 16 acceptance): a rising long-prompt
    sweep grows the prefill pool off the demand EWMA and the idle tail
    shrinks it back to the floor — no manual resize anywhere, zero lost
    admitted work and zero token mismatches through every per-role
    resize, drain-before-stop on every retire, and at least one planned
    KV hand-off served by the prefill pool."""
    from tools.chaos_soak import run_disagg_autoscale_ramp

    report = run_disagg_autoscale_ramp(
        ramp="0.5:2,5:6,0.5:3,0:8",
        short_rps=1.0,
        max_tokens=8,
        prefill_min=1,
        prefill_max=2,
        prefill_rps=2.5,
        ewma_seconds=1.5,
        autoscale_interval=0.4,
        settle_bound_s=20.0,
    )
    assert report["bounded"], report
    assert report["lost"] == 0 and report["mismatches"] == 0
    # The pool grew past its floor (target AND serving replicas), never
    # past its ceiling, and came back down to the floor — all of it the
    # autoscaler's doing.
    assert report["max_prefill_ready"] == 2
    assert report["demand_ups"] >= 1 and report["demand_downs"] >= 1
    assert report["manual_resizes"] == 0
    assert report["final"]["prefill_target"] == 1
    assert report["handoffs"].get("handoffs.planned", 0) >= 1
    assert report["drained_before_stop"]
    assert report["leaked_children"] == []


def test_router_kill_smoke(model_dir):
    """1-cycle router-kill chaos (tools/chaos_soak.py --router-kill,
    the ISSUE 17 acceptance): SIGKILL the router subprocess mid-stream
    and mid-scale-up, restart it against the same --state-dir — every
    WAL-recorded child survives and is re-adopted (zero leaked, zero
    double-spawned, pids preserved), every severed admitted stream was
    journaled and finishes bit-identically through the reconnect
    protocol, and nothing outlives the final graceful shutdown."""
    from tools.chaos_soak import run_router_kill

    report = run_router_kill(cycles=1, streams=2, max_tokens=32)
    assert report["bounded"], report
    assert report["lost"] == 0 and report["mismatches"] == 0
    assert report["interrupted"] >= 1
    assert report["resumed"] == report["interrupted"]
    cyc = report["cycles_detail"][0]
    assert cyc["children_survived_kill"], report
    assert cyc["adoption_complete"] and cyc["double_spawns"] == 0
    assert cyc["pids_preserved"] and cyc["killed_mid_scale_up"]
    assert report["leaked_children"] == []
