"""Tiered KV cache (ISSUE 14): radix index + host-DRAM spill tier.

Layers, bottom up:

- radix allocator property tests with the PR 1 hash-chain allocator as
  the ORACLE (randomized insert/match/free workloads on an
  eviction-free pool must produce identical hits), plus accounting
  invariants under eviction churn;
- structural tests: shared-interior refcounts, leaf-first eviction
  order, hot chains surviving colder exact-LRU victims, spill/restore
  span queues and slot-reuse deferral;
- the step-delta codec carrying tier spans;
- mock-worker end-to-end: the mock "writes" real token ids into a
  simulated page store, mirrors the spill/restore spans, and VERIFIES
  every prefix-cache admission against it — so the bit-identity
  assertions here are backed by content checks, not just the mock's
  deterministic sampling;
- the ISSUE 14 acceptance gate: with a page pool sized to force
  eviction, radix+spill beats the flat cache on prefix-cache hit
  tokens AND warm TTFT, with greedy outputs identical between
  resident-hit, restored-hit, and cold runs;
- a real-model (CPU) spill→restore bit-identity run exercising the
  actual device_get/device_put + donated-scatter path.
"""

from __future__ import annotations

import random
import statistics

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.block_manager import (
    NoFreePagesError,
    PrefixCachingAllocator,
    RadixPrefixCachingAllocator,
)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.sampling_params import SamplingParams

PS = 4  # page size for the unit tests


def make_req(rid, tokens):
    return Request(
        request_id=rid,
        prompt_token_ids=list(tokens),
        sampling_params=SamplingParams(),
    )


def computed(alloc, rid, tokens):
    """Allocate + mark every token computed + register full pages."""
    req = make_req(rid, tokens)
    alloc.allocate(req, len(tokens))
    req.num_computed_tokens = len(tokens)
    alloc.register_computed(req)
    return req


_query_seq = iter(range(10**6))


def query(alloc, tokens):
    return alloc.query_prefix(make_req(f"q{next(_query_seq)}", tokens))


def _check_invariants(alloc: RadixPrefixCachingAllocator):
    """Page conservation + cached-free accounting, recomputed from
    scratch against the allocator's incremental counters."""
    node_pages = set(alloc._page_node)
    plain_owned = set()
    for rid, pages in alloc._allocated.items():
        for p in pages:
            if p not in node_pages:
                assert p not in plain_owned, f"page {p} owned twice"
                plain_owned.add(p)
    free = set(alloc._free)
    assert not (free & node_pages), "freed page still indexed"
    assert not (free & plain_owned), "freed page still owned"
    assert len(free) + len(node_pages) + len(plain_owned) == (
        alloc.num_pages - 1
    ), "page conservation violated"

    # cached_free == nodes holding a page with no live owner.
    def walk(node):
        total = 0
        resident_children = 0
        for child in node.children.values():
            assert child.parent is node
            if child.page is not None:
                resident_children += 1
                if child.refs == 0:
                    total += 1
            else:
                assert child.host_slot is not None, "detached node in tree"
                assert child.refs == 0, "host-resident node with refs"
            total += walk(child)
        assert node.resident_children == resident_children, (
            "resident_children counter drifted"
        )
        return total

    assert walk(alloc._root) == alloc._cached_free
    assert alloc.num_free_pages == len(alloc._free) + alloc._cached_free


# ---------------------------------------------------------------------
# oracle property tests: radix vs the PR 1 hash-chain allocator
# ---------------------------------------------------------------------
def _random_prompts(rng, n):
    """Prompt population with heavy prefix sharing: a few base chains,
    random cut points, random divergent tails."""
    bases = [
        [rng.randrange(1, 50) for _ in range(rng.randrange(4, 40))]
        for _ in range(4)
    ]
    prompts = []
    for _ in range(n):
        base = rng.choice(bases)
        cut = rng.randrange(1, len(base) + 1)
        tail = [rng.randrange(50, 99) for _ in range(rng.randrange(0, 9))]
        prompts.append(base[:cut] + tail)
    return prompts


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_radix_matches_hash_chain_oracle_without_eviction(seed):
    """On a pool large enough that nothing is ever evicted, the radix
    walk and the hash-chain map are the same function: identical hit
    tokens for every query after any interleaving of computed-insert /
    free / query operations."""
    rng = random.Random(seed)
    flat = PrefixCachingAllocator(num_pages=4096, page_size=PS)
    radix = RadixPrefixCachingAllocator(num_pages=4096, page_size=PS)
    live: list[tuple[Request, Request]] = []
    for i, prompt in enumerate(_random_prompts(rng, 60)):
        op = rng.random()
        if op < 0.55:
            live.append(
                (
                    computed(flat, f"r{i}", prompt),
                    computed(radix, f"r{i}", prompt),
                )
            )
        elif op < 0.8 and live:
            rf, rr = live.pop(rng.randrange(len(live)))
            flat.free(rf)
            radix.free(rr)
        else:
            hit_f, _ = query(flat, prompt)
            hit_r, _ = query(radix, prompt)
            assert hit_f == hit_r, (seed, i, prompt)
        _check_invariants(radix)
    # Drain: every remaining query must still agree.
    for rf, rr in live:
        flat.free(rf)
        radix.free(rr)
    for prompt in _random_prompts(rng, 30):
        assert query(flat, prompt)[0] == query(radix, prompt)[0]
    _check_invariants(radix)


@pytest.mark.parametrize("seed", [7, 8, 9])
@pytest.mark.parametrize("host_pages", [0, 6])
def test_radix_invariants_under_eviction_churn(seed, host_pages):
    """Small pool, random allocate/free/query churn with eviction (and
    spill when host_pages > 0): accounting invariants hold at every
    step, rollback on true exhaustion is clean, and queried hits only
    ever name indexed pages."""
    rng = random.Random(seed)
    alloc = RadixPrefixCachingAllocator(
        num_pages=12, page_size=PS, host_pages=host_pages,
        restore_min_tokens=PS,
    )
    live: list[Request] = []
    for i, prompt in enumerate(_random_prompts(rng, 120)):
        op = rng.random()
        if op < 0.5:
            req = make_req(f"r{i}", prompt)
            try:
                alloc.allocate(req, len(prompt))
            except NoFreePagesError:
                _check_invariants(alloc)
                continue
            req.num_computed_tokens = len(prompt)
            alloc.register_computed(req)
            live.append(req)
        elif op < 0.8 and live:
            alloc.free(live.pop(rng.randrange(len(live))))
        else:
            hit, pages = query(alloc, prompt)
            assert hit == len(pages) * PS
            for p in pages:
                assert p in alloc._page_node
        # Ship + forget pending spans like a scheduler would.
        alloc.take_tier_ops()
        alloc.release_shipped_slots()
        _check_invariants(alloc)
        assert alloc.host_slots_used <= host_pages


# ---------------------------------------------------------------------
# structural guarantees
# ---------------------------------------------------------------------
def test_shared_interior_nodes_are_ref_counted():
    alloc = RadixPrefixCachingAllocator(num_pages=16, page_size=PS)
    prompt = list(range(1, 9))  # 2 full pages
    r1 = computed(alloc, "r1", prompt)
    shared = list(r1.page_ids)
    alloc.free(r1)

    hit, pages = query(alloc, prompt + [50])
    assert hit == 8 and pages == shared
    r2 = make_req("r2", prompt + [50])
    alloc.attach_prefix(r2, pages)
    r2.num_computed_tokens = hit
    r3 = make_req("r3", prompt + [60])
    alloc.attach_prefix(r3, pages)
    r3.num_computed_tokens = hit
    # One sharer leaves: interior AND leaf survive for the other.
    alloc.free(r2)
    grabbed = []
    while True:
        r = make_req(f"g{len(grabbed)}", [1])
        try:
            grabbed.extend(alloc.allocate(r, 1))
        except NoFreePagesError:
            break
    assert not set(shared) & set(grabbed)
    _check_invariants(alloc)
    # Last owner leaves: now evictable.
    alloc.free(r3)
    got = alloc.allocate(make_req("last", list(range(8))), 8)
    assert set(got) == set(shared)
    _check_invariants(alloc)


def test_eviction_is_leaf_first():
    """A freed 3-page chain is consumed tail-first: the root page (the
    most shareable) is the last to go, regardless of insertion order."""
    alloc = RadixPrefixCachingAllocator(num_pages=4, page_size=PS)
    chain = list(range(1, 13))  # 3 full pages fill the 3-usable pool
    r = computed(alloc, "r", chain)
    p0, p1, p2 = r.page_ids
    alloc.free(r)
    assert alloc.allocate(make_req("a", [1]), 1) == [p2]
    assert query(alloc, chain)[0] == 2 * PS  # root+middle still match
    assert alloc.allocate(make_req("b", [1]), 1) == [p1]
    assert alloc.allocate(make_req("c", [1]), 1) == [p0]
    assert query(alloc, chain)[0] == 0
    _check_invariants(alloc)


def test_hot_chain_survives_colder_exact_lru_victim():
    """Cache-aware eviction: a chain that keeps MATCHING stays resident
    even though its pages were freed long before a colder chain's.
    (The flat allocator's freed-order LRU evicts the hot chain here —
    exactly the precision the radix index adds.)"""
    alloc = RadixPrefixCachingAllocator(num_pages=7, page_size=PS)
    hot = list(range(1, 9))  # 2 pages, freed FIRST
    cold = list(range(100, 108))  # 2 pages, freed after
    r_hot = computed(alloc, "hot", hot)
    hot_pages = set(r_hot.page_ids)
    alloc.free(r_hot)
    r_cold = computed(alloc, "cold", cold)
    cold_pages = set(r_cold.page_ids)
    alloc.free(r_cold)
    # Traffic keeps walking the hot chain (router steering at it).
    for _ in range(3):
        assert query(alloc, hot + [77])[0] == 8
    # Pressure: take 4 pages (2 plain free + 2 evictions).
    taken = alloc.allocate(make_req("x", list(range(200, 216))), 16)
    assert cold_pages <= set(taken), "cold chain should be the victim"
    assert not (hot_pages & set(taken)), "hot chain was evicted"
    assert query(alloc, hot + [77])[0] == 8
    assert query(alloc, cold + [77])[0] == 0
    _check_invariants(alloc)


def test_full_prompt_hit_drops_tail_page_and_partial_never_matches():
    alloc = RadixPrefixCachingAllocator(num_pages=16, page_size=PS)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    r = computed(alloc, "r", prompt)
    alloc.free(r)
    hit, pages = query(alloc, prompt)
    assert hit == len(prompt) - PS and len(pages) == 1
    assert query(alloc, [1, 2, 3, 4]) == (0, [])
    assert query(alloc, [1, 2, 3]) == (0, [])
    # Partial page registered never matches.
    r2 = computed(alloc, "r2", [9, 9, 9, 9, 5, 5])
    alloc.free(r2)
    assert query(alloc, [9, 9, 9, 9, 5, 5, 6, 6])[0] == PS


# ---------------------------------------------------------------------
# spill tier bookkeeping
# ---------------------------------------------------------------------
def test_eviction_spills_to_host_and_restores():
    alloc = RadixPrefixCachingAllocator(
        num_pages=4, page_size=PS, host_pages=4, restore_min_tokens=PS
    )
    chain = list(range(1, 9))  # 2 pages; pool has 3 usable
    r = computed(alloc, "r", chain)
    page0, page1 = r.page_ids
    alloc.free(r)
    # Pressure: take both cached pages -> leaf-first spill of both.
    filler = make_req("f", list(range(100, 112)))
    alloc.allocate(filler, 12)
    spills, restores = alloc.take_tier_ops()
    assert [p for p, _ in spills] == [page1, page0]  # leaf first
    assert restores == []
    assert alloc.host_slots_used == 2
    alloc.release_shipped_slots()
    # The chain is fully host-resident: resident query misses, the
    # tiered plan sees it, the admission estimate counts it.
    assert query(alloc, chain + [50])[0] == 0
    probe = make_req("probe", chain + [50, 51])
    plan = alloc.plan_prefix(probe)
    assert plan.resident_tokens == 0 and plan.host_tokens == 8
    assert alloc.estimate_cached_tokens(chain + [50]) == 8
    # Free the filler, restore the chain into fresh pages.
    alloc.free(filler)
    restored = alloc.attach_plan(probe, plan, restore=True)
    assert restored == 2
    spills, restores = alloc.take_tier_ops()
    assert spills == []
    assert len(restores) == 2
    # Restored slots are deferred until the batch ships.
    assert alloc.host_slots_used == 2
    alloc.release_shipped_slots()
    assert alloc.host_slots_used == 0
    # Restored chain is resident again and shared.
    probe.num_computed_tokens = 8
    assert query(alloc, chain + [60])[0] == 8
    _check_invariants(alloc)


def test_restore_crossover_prefers_recompute_below_threshold():
    alloc = RadixPrefixCachingAllocator(
        num_pages=4, page_size=PS, host_pages=4,
        restore_min_tokens=3 * PS,
    )
    chain = list(range(1, 9))
    r = computed(alloc, "r", chain)
    alloc.free(r)
    alloc.allocate(make_req("f", list(range(100, 112))), 12)
    alloc.take_tier_ops()
    alloc.release_shipped_slots()
    plan = alloc.plan_prefix(make_req("p", chain + [50]))
    # 2 host pages (8 tokens) < 12-token crossover: the scheduler's
    # restore gate is plan.host_tokens >= restore_min_tokens.
    assert plan.host_tokens == 8 < alloc.restore_min_tokens
    # The admission estimate mirrors the same gate.
    assert alloc.estimate_cached_tokens(chain + [50]) == 0


def test_unshipped_restore_target_is_not_evictable():
    """A rolled-back admission can orphan a restore target with
    refs==0 before its (slot→page) span ships.  Evicting it would
    re-capture the page's PRE-restore garbage into the host tier —
    the page must be fenced until the batch ships, then evict
    normally."""
    alloc = RadixPrefixCachingAllocator(
        num_pages=4, page_size=PS, host_pages=4, restore_min_tokens=PS
    )
    chain = list(range(1, 9))
    r = computed(alloc, "r", chain)
    alloc.free(r)
    filler = make_req("f1", list(range(100, 112)))
    alloc.allocate(filler, 12)
    alloc.take_tier_ops()
    alloc.release_shipped_slots()  # both chain pages now host-resident
    alloc.free(filler)  # room for the restore targets
    probe = make_req("probe", chain + [50, 51])
    plan = alloc.plan_prefix(probe)
    assert len(plan.host) == 2
    alloc.attach_plan(probe, plan, restore=True)
    # Rollback analog: the admission failed after attach.
    alloc.free(probe)
    restored_pages = {p for _, p in alloc._pending_restores}
    # Pressure BEFORE the batch ships: the unmaterialized restore
    # targets must not be chosen as spill victims.
    taken = []
    while True:
        try:
            taken.extend(
                alloc.allocate(
                    make_req(f"g{len(taken)}", [1]), 1
                )
            )
        except NoFreePagesError:
            break
    assert not (set(taken) & restored_pages), (
        "evicted a page whose restore never shipped"
    )
    _check_invariants(alloc)


def test_register_skips_evicted_duplicate_cursor():
    """Finding-2 regression: a request whose registration cursor was a
    duplicate-content node (never reffed) must stop registering — not
    hang resident children under a spilled/detached cursor — when that
    node is evicted between steps."""
    alloc = RadixPrefixCachingAllocator(
        num_pages=8, page_size=PS, host_pages=4, restore_min_tokens=PS
    )
    prompt = list(range(1, 9))  # 2 full pages
    a = computed(alloc, "a", prompt)
    # B computes the SAME content: both pages are resident duplicates,
    # so B's cursor walks A's nodes without reffing them.
    b = computed(alloc, "b", prompt)
    assert alloc._req_nodes.get("b") in (None, [])
    # B already owns its third page (a decode window in flight).
    b.output_token_ids.extend([91, 92, 93, 94])
    alloc.allocate(b, 4)
    alloc.free(a)
    # Evict A's chain — including B's saved duplicate-content cursor.
    grabbed = []
    while True:
        try:
            grabbed.extend(
                alloc.allocate(make_req(f"g{len(grabbed)}", [1]), 1)
            )
        except NoFreePagesError:
            break
    cursor = alloc._reg_node["b"]
    assert cursor.page is None, "test setup: cursor was not evicted"
    # B's decode window lands; its saved cursor is gone/spilled.
    b.num_computed_tokens = 12
    alloc.register_computed(b)  # must not corrupt the tree or crash
    assert alloc._reg_node["b"] is None  # tombstoned, not mis-attached
    _check_invariants(alloc)
    alloc.free(b)
    _check_invariants(alloc)


def test_lazy_heaps_stay_bounded_under_touch_heavy_traffic():
    """Finding-3 regression: repeated prefix matches (router steering
    at a hot chain) must not grow the lazy eviction heap without
    bound."""
    alloc = RadixPrefixCachingAllocator(num_pages=64, page_size=PS)
    r = computed(alloc, "r", list(range(1, 17)))
    alloc.free(r)
    for _ in range(10_000):
        query(alloc, list(range(1, 17)) + [99])
    assert len(alloc._hbm_heap) <= 4 * len(alloc._page_node) + 64

    from vllm_distributed_tpu.router.affinity import PrefixAffinityIndex

    idx = PrefixAffinityIndex(block_tokens=4, capacity=64)
    keys = idx.keys_for(prompt_token_ids=list(range(16)))
    idx.observe("r1", keys)
    for _ in range(10_000):
        idx.score(keys)
    tree = idx._trees["r1"]
    assert len(tree._heap) <= 4 * tree.count + 64


def test_host_tier_is_bounded_and_prunes_unreachable_chains():
    alloc = RadixPrefixCachingAllocator(
        num_pages=4, page_size=PS, host_pages=1, restore_min_tokens=PS
    )
    chain = list(range(1, 13))  # 3 pages > 3-usable pool after tail
    r = computed(alloc, "r", chain[:8])
    alloc.free(r)
    # Two evictions, one host slot: the leaf spills, then the root's
    # eviction needs a slot -> evicts the (now childless? no: root's
    # child is host) ... root spill must evict the host LEAF first.
    alloc.allocate(make_req("f", list(range(100, 112))), 12)
    spills, _ = alloc.take_tier_ops()
    assert len(spills) == 2  # both spilled, second reused the slot
    assert alloc.host_slots_used == 1
    _check_invariants(alloc)


# ---------------------------------------------------------------------
# step-delta codec carries tier spans
# ---------------------------------------------------------------------
def test_step_frame_round_trips_tier_ops():
    from vllm_distributed_tpu.engine.scheduler import (
        NewRequestData,
        SchedulerOutput,
    )
    from vllm_distributed_tpu.engine.step_delta import (
        StepDeltaEncoder,
        StepStateMirror,
    )

    so = SchedulerOutput(step_id=0)
    nr = NewRequestData(
        req_id="a",
        prompt_token_ids=[1, 2, 3],
        num_prompt_tokens=3,
        page_ids=[5],
        num_computed_tokens=0,
        num_new_tokens=3,
        sampling_params=SamplingParams(),
    )
    so.new_requests.append(nr)
    so.num_scheduled_tokens["a"] = 3
    so.total_num_scheduled_tokens = 3
    so.kv_spill_ops = [(7, 0), (8, 1)]
    so.kv_restore_ops = [(2, 9)]
    frame = StepDeltaEncoder().encode(so, blocking=True)
    assert frame.raw is None
    assert frame.spills == [(7, 0), (8, 1)]
    assert frame.restores == [(2, 9)]
    decoded = StepStateMirror().decode(frame)
    assert decoded == so


# ---------------------------------------------------------------------
# mock-worker end-to-end + the ISSUE 14 acceptance gate
# ---------------------------------------------------------------------
_MOCK_MODEL_DIR = None


def _mock_engine(**kw):
    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.testing import write_llama_config

    global _MOCK_MODEL_DIR
    if _MOCK_MODEL_DIR is None:
        _MOCK_MODEL_DIR = write_llama_config()
    defaults = dict(
        model=_MOCK_MODEL_DIR,
        skip_tokenizer_init=True,
        load_format="dummy",
        page_size=4,
        max_num_seqs=8,
        max_model_len=256,
        num_decode_steps=1,
        distributed_executor_backend=MockUniProcExecutor,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


def _run_round(engine, prompts, tag, max_tokens=4):
    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )
    for i, p in enumerate(prompts):
        engine.add_request(
            f"{tag}{i}", prompt_token_ids=list(p), sampling_params=sp
        )
    done = {}
    ttfts = []
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
    outs = [done[f"{tag}{i}"] for i in range(len(prompts))]
    for o in outs:
        if o.metrics is not None and o.metrics.ttft is not None:
            ttfts.append(o.metrics.ttft)
    return [list(o.outputs[0].token_ids) for o in outs], ttfts


def _shared_prefix_prompts(n=4, shared=24, total=32):
    pre = list(range(1, shared + 1))
    return [
        pre + [100 + 10 * i + j for j in range(total - shared)]
        for i in range(n)
    ]


@pytest.fixture()
def seq_mode_env(monkeypatch):
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    yield


def test_spill_restore_bit_identical_on_mock(seq_mode_env, monkeypatch):
    """Constrained pool + spill tier on the mock worker: repeated
    shared-prefix rounds force evict→spill→restore cycles; outputs stay
    the exact deterministic position stream every round, and the mock's
    page-content verification (which raises on any stale or mis-routed
    page served as a hit) backs the assertion with real content checks.
    """
    prompts = _shared_prefix_prompts()
    expected = [
        [len(p) + k for k in range(4)] for p in prompts
    ]
    engine = _mock_engine(
        enable_prefix_caching=True,
        num_kv_pages=16,
        kv_spill_host_pages=32,
        kv_spill_restore_min_tokens=4,
    )
    for rnd in range(4):
        outs, _ = _run_round(engine, prompts, f"r{rnd}-")
        assert outs == expected, f"round {rnd} diverged"
    sched = engine.scheduler
    assert sched.kv_spill_pages > 0, "pool never spilled (test too lax)"
    assert sched.kv_restore_pages > 0, "host tier never restored"
    assert sched.prefix_cache_hits_host > 0
    assert sched.prefix_cache_hits >= sched.prefix_cache_hits_host
    # The worker's host dict is bounded by the configured pool.
    info = engine.executor.collective_rpc(
        "get_kv_tier_info", unique_reply_rank=0
    )
    assert info["host_slots"] <= 32
    # New metric families render (drift test pins the full registry).
    rendered = engine.metrics.render().decode()
    for fam in (
        "vllm:kv_spill_pages_total",
        "vllm:kv_restore_pages_total",
        "vllm:kv_restore_seconds",
        "vllm:host_kv_bytes",
    ):
        assert fam in rendered
    engine.shutdown()


def test_ablation_gate_radix_spill_beats_flat(seq_mode_env, monkeypatch):
    """ISSUE 14 acceptance: at a page pool sized to force eviction,
    radix+spill achieves strictly higher prefix-cache hit tokens and
    lower warm TTFT than the flat cache, with greedy outputs identical
    between resident-hit, restored-hit, and cold runs.

    Workload: six disjoint 32-token chains cycled one at a time through
    a pool that holds ~3 of them, with a simulated per-scheduled-token
    device cost — the chat-scale regime where the flat cache's
    evictions discard KV (full warm re-prefill) while the tiered cache
    streams it back from host DRAM (tail-page prefill only)."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SECONDS", "0.002")
    prompts = [
        [100 * (i + 1) + j for j in range(32)] for i in range(6)
    ]
    expected = [[len(p) + k for k in range(4)] for p in prompts]
    results = {}
    for mode, kw in {
        "cold": dict(),
        "flat": dict(
            enable_prefix_caching=True, prefix_cache_index="flat"
        ),
        "radix": dict(enable_prefix_caching=True),
        "radix+spill": dict(
            enable_prefix_caching=True,
            kv_spill_host_pages=64,
            kv_spill_restore_min_tokens=4,
        ),
    }.items():
        engine = _mock_engine(num_kv_pages=32, **kw)
        warm_ttfts = []
        for rnd in range(3):
            for i, p in enumerate(prompts):
                outs, ttfts = _run_round(
                    engine, [p], f"{mode}{rnd}-{i}-"
                )
                assert outs == [expected[i]], (
                    f"{mode} round {rnd} prompt {i} diverged"
                )
                if rnd == 2:
                    warm_ttfts.extend(ttfts)
        sched = engine.scheduler
        results[mode] = {
            "hits": sched.prefix_cache_hits,
            "host_hits": sched.prefix_cache_hits_host,
            "warm_ttft": statistics.mean(warm_ttfts),
        }
        engine.shutdown()
    # The gate: strictly more hit tokens AND lower warm TTFT.
    assert results["radix+spill"]["hits"] > results["flat"]["hits"]
    assert results["radix+spill"]["host_hits"] > 0
    assert (
        results["radix+spill"]["warm_ttft"]
        < results["flat"]["warm_ttft"]
    ), results
    # The radix index alone (no spill) must never do worse than flat.
    assert results["radix"]["hits"] >= results["flat"]["hits"]


def test_default_off_runs_without_tier_machinery(seq_mode_env):
    """Seed config (no prefix caching): base allocator, no tier spans
    on any step, no tier counters moving."""
    from vllm_distributed_tpu.engine.block_manager import PageAllocator

    prompts = _shared_prefix_prompts(n=2)
    engine = _mock_engine(num_kv_pages=64)
    assert type(engine.scheduler.allocator) is PageAllocator
    outs, _ = _run_round(engine, prompts, "d-")
    assert outs == [[len(p) + k for k in range(4)] for p in prompts]
    assert not hasattr(engine.scheduler.allocator, "take_tier_ops")
    engine.shutdown()


# ---------------------------------------------------------------------
# chaos spill phase (ISSUE 14 satellite): kill→recover with an active
# host tier.  A 1-cycle smoke runs in tier-1; longer loops carry the
# soak marker like the other chaos harnesses.
# ---------------------------------------------------------------------
def test_kv_spill_soak_smoke():
    from tools.chaos_soak import run_kv_spill_soak

    report = run_kv_spill_soak(cycles=1, chains=4)
    assert report["replay_failures"] == 0, report
    assert report["active"], report
    assert report["bounded"], report
    assert report["restarts_total"] >= 1


@pytest.mark.soak
@pytest.mark.slow
def test_kv_spill_soak_long():
    from tools.chaos_soak import run_kv_spill_soak

    report = run_kv_spill_soak(cycles=5)
    assert report["replay_failures"] == 0, report
    assert report["active"] and report["bounded"], report
    # No host-memory leak across recoveries: the host tier is a few
    # hundred 4-token mock pages — RSS must plateau, not grow with
    # cycle count.
    assert report["rss_growth_mb"] < 150, report


# ---------------------------------------------------------------------
# real-model (CPU) spill→restore bit-identity
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_llama(tmp_path_factory):
    return make_tiny_llama(str(tmp_path_factory.mktemp("llama_tier")))


def _real_engine(model_dir, **kw):
    defaults = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        num_kv_pages=128,
        page_size=8,
        max_num_seqs=8,
        max_model_len=256,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


def test_real_engine_restored_pages_bit_identical(tiny_llama):
    """The actual worker path: jax.device_get spills, donated-scatter
    restores, on a pool too small to keep every chain resident.  Six
    DISJOINT chains cycled one at a time guarantee that by the time a
    chain comes around again its pages have spilled whole — so warm
    hits on later rounds are genuine host-tier restores.  Outputs must
    match an unconstrained cold engine bit-for-bit."""
    prompts = [
        [100 * (i + 1) + j for j in range(19)] for i in range(6)
    ]
    cold_engine = _real_engine(tiny_llama)
    cold = [
        _run_round(cold_engine, [p], f"c{i}", max_tokens=6)[0][0]
        for i, p in enumerate(prompts)
    ]
    cold_engine.shutdown()
    tiered = _real_engine(
        tiny_llama,
        enable_prefix_caching=True,
        num_kv_pages=10,
        kv_spill_host_pages=32,
        kv_spill_restore_min_tokens=8,
    )
    for rnd in range(3):
        for i, p in enumerate(prompts):
            got = _run_round(tiered, [p], f"t{rnd}-{i}", max_tokens=6)
            assert got[0][0] == cold[i], (
                f"round {rnd} prompt {i} diverged under spill/restore"
            )
    sched = tiered.scheduler
    assert sched.kv_spill_pages > 0
    assert sched.kv_restore_pages > 0, (
        "restore path never ran — shrink the pool or the crossover"
    )
    assert sched.prefix_cache_hits_host > 0
    info = tiered.executor.collective_rpc(
        "get_kv_tier_info", unique_reply_rank=0
    )
    assert info is not None and info["page_bytes"] > 0
    assert info["host_slots"] <= 32
    tiered.shutdown()
