import pytest

from vllm_distributed_tpu.engine.block_manager import (
    NoFreePagesError,
    PageAllocator,
)
from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.sampling_params import SamplingParams


def make_req(rid="r0", prompt_len=10):
    return Request(
        request_id=rid,
        prompt_token_ids=list(range(prompt_len)),
        sampling_params=SamplingParams(),
    )


def test_allocate_and_free():
    alloc = PageAllocator(num_pages=8, page_size=4)
    # Page 0 reserved for padding.
    assert alloc.num_free_pages == 7
    req = make_req(prompt_len=10)
    new = alloc.allocate(req, 10)  # 10 tokens -> 3 pages
    assert len(new) == 3
    assert req.page_ids == new
    assert 0 not in new
    assert alloc.num_free_pages == 4
    alloc.free(req)
    assert alloc.num_free_pages == 7
    assert req.page_ids == []


def test_incremental_allocation():
    alloc = PageAllocator(num_pages=8, page_size=4)
    req = make_req(prompt_len=4)
    first = alloc.allocate(req, 4)
    assert len(first) == 1
    req.num_computed_tokens = 4
    # Next token needs a new page.
    second = alloc.allocate(req, 1)
    assert len(second) == 1
    req.num_computed_tokens = 5
    # Tokens 5..7 fit in the same page.
    third = alloc.allocate(req, 3)
    assert third == []


def test_exhaustion_and_rollback():
    alloc = PageAllocator(num_pages=4, page_size=4)  # 3 usable
    r1 = make_req("r1", 8)
    alloc.allocate(r1, 8)  # 2 pages
    r2 = make_req("r2", 12)
    with pytest.raises(NoFreePagesError):
        alloc.allocate(r2, 12)  # needs 3, only 1 free -> rollback
    assert alloc.num_free_pages == 1
    assert alloc.get_page_ids("r2") in ([], None) or alloc.get_page_ids("r2") == []


def test_slot_for_token():
    alloc = PageAllocator(num_pages=8, page_size=4)
    req = make_req(prompt_len=10)
    alloc.allocate(req, 10)
    p = req.page_ids
    assert alloc.slot_for_token(req, 0) == p[0] * 4
    assert alloc.slot_for_token(req, 5) == p[1] * 4 + 1
    assert alloc.slot_for_token(req, 9) == p[2] * 4 + 1


def test_can_allocate():
    alloc = PageAllocator(num_pages=4, page_size=4)
    r1 = make_req("r1", 8)
    assert alloc.can_allocate(r1, 8)
    alloc.allocate(r1, 8)
    r2 = make_req("r2", 8)
    assert not alloc.can_allocate(r2, 8)
    assert alloc.can_allocate(r2, 4)
