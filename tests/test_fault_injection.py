"""Fault-injection suite for the control plane (ISSUE 2 tentpole).

Each test injects ONE deterministic fault — via the transport-level
``FaultInjector`` (frames dropped/corrupted/delayed), the mock-worker
hooks (hang/die mid-execute), raw process kills, or connect delays — and
asserts the three-part contract:

1. bounded detection time (never "wait for a request to time out",
   never a hang);
2. a ``HostFailure`` with the right lifecycle phase and the offending
   host named;
3. the degraded surface: ``/health`` → 503 with the structured cause and
   ``Retry-After``, new work rejected with a typed error, and no leaked
   vdt threads or pending RPC futures afterwards.

Tier-1 (not `slow`): everything here runs on loopback with mock workers
and sub-second heartbeat intervals.
"""

import asyncio
import multiprocessing
import os
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockWorker  # noqa: F401 (import check)
from tools.chaos_soak import RespawningAgent, run_soak
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.distributed.agent import (
    reconnect_delay,
    remote_main,
    server_silence_watchdog,
)
from vllm_distributed_tpu.distributed.rpc_transport import FaultInjector
from vllm_distributed_tpu.engine.async_llm import AsyncLLM, EngineDeadError
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port

pytestmark = pytest.mark.fault

# Fast liveness so detection bounds are test-sized: miss budget is
# HB_INTERVAL * HB_THRESHOLD = 1.5 s.
HB_INTERVAL = 0.5
HB_THRESHOLD = 3
EXECUTE_TIMEOUT = 3.0
# CI slack on top of the theoretical detection deadline.
SLACK = 3.0


class FaultMultiHostExecutor(MultiHostExecutor):
    worker_cls = "tests.mock_worker.MockWorker"


def _agent_with_env(port, env):
    for k, v in (env or {}).items():
        os.environ[k] = v
    remote_main("127.0.0.1", port)


def _spawn_agent(port, extra_env=None):
    env = {
        "VDT_ADVERTISE_NUM_CHIPS": "4",
        "VDT_ADVERTISE_PLATFORM": "cpu",
        "VDT_FAULT_INJECTION": "1",
        **(extra_env or {}),
    }
    proc = multiprocessing.Process(
        target=_agent_with_env, args=(port, env), daemon=True
    )
    proc.start()
    return proc


def _vdt_threads():
    return {t for t in threading.enumerate() if t.name.startswith("vdt-")}


def _assert_no_new_vdt_threads(baseline, deadline=8.0):
    """Every vdt-* thread created since `baseline` must exit: heartbeat
    tasks cancelled, executor loop stopped, pools drained."""
    end = time.monotonic() + deadline
    extra = []
    while time.monotonic() < end:
        extra = [t for t in _vdt_threads() if t not in baseline]
        if not extra:
            return
        time.sleep(0.1)
    raise AssertionError(f"leaked threads: {[t.name for t in extra]}")


def _wait_for(predicate, deadline, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        if predicate():
            return time.monotonic() - t0
        time.sleep(0.05)
    raise AssertionError(f"{what} not observed within {deadline:.1f}s")


def _fault_env(monkeypatch, tmp_path, port):
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv(
        "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", str(int(EXECUTE_TIMEOUT))
    )
    monkeypatch.setenv("VDT_HEARTBEAT_INTERVAL_SECONDS", str(HB_INTERVAL))
    monkeypatch.setenv("VDT_HEARTBEAT_MISS_THRESHOLD", str(HB_THRESHOLD))
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    # These tests assert the TERMINAL death contract (drain + reject);
    # disable the in-process supervisor so a HostFailure stays fatal.
    # The recovery suite below re-enables it with its own knobs.
    monkeypatch.setenv("VDT_MAX_ENGINE_RESTARTS", "0")


def _recovery_env(monkeypatch, tmp_path, port):
    """Supervised-recovery flavor: fast restart policy, deterministic
    mock token sequences, and execute pacing slow enough to kill a
    stream mid-generation."""
    _fault_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_MAX_ENGINE_RESTARTS", "3")
    monkeypatch.setenv("VDT_ENGINE_RESTART_BACKOFF_SECONDS", "0.2")
    monkeypatch.setenv("VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS", "2")
    monkeypatch.setenv("VDT_CRASH_LOOP_WINDOW_SECONDS", "60")
    monkeypatch.setenv("VDT_CONNECT_TIMEOUT_SECONDS", "30")
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.05")


RECOVERY_AGENT_ENV = {
    "VDT_MOCK_TOKEN_SEQ": "1",
    "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
}


def _engine_args(tmp_path, **kw):
    model_dir = write_llama_config(str(tmp_path / "m"))
    return EngineArgs(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_hosts=2,
        **kw,
    )


@pytest.fixture
def fault_deployment(tmp_path, monkeypatch):
    """Executor-level 2-host mocked deployment with injection armed."""
    port = get_open_port()
    _fault_env(monkeypatch, tmp_path, port)
    baseline = _vdt_threads()
    agent = _spawn_agent(port)
    executor = FaultMultiHostExecutor(
        _engine_args(tmp_path).create_engine_config()
    )
    yield executor, agent, baseline
    executor.shutdown()
    if agent.is_alive():
        agent.terminate()
    agent.join(timeout=5)


@pytest.fixture
def engine_deployment(tmp_path, monkeypatch):
    """Full AsyncLLM over the mocked multihost executor, for /health and
    drain/reject assertions."""
    port = get_open_port()
    _fault_env(monkeypatch, tmp_path, port)
    baseline = _vdt_threads()
    agent = _spawn_agent(port)
    engine = AsyncLLM.from_engine_args(
        _engine_args(
            tmp_path,
            num_decode_steps=1,  # blocking step path: no mock device sleep
            max_model_len=512,  # fits the mock worker's 100-page cache
            distributed_executor_backend=FaultMultiHostExecutor,
        )
    )
    yield engine, agent, baseline
    engine.shutdown()
    if agent.is_alive():
        agent.terminate()
    agent.join(timeout=5)


def _so(step=0, req="r1"):
    return SchedulerOutput(
        step_id=step,
        num_scheduled_tokens={req: 1},
        total_num_scheduled_tokens=1,
    )


def _arm(executor, name, value=1.0, after_writes=2):
    """Arm a fault on the remote worker.  after_writes=2 lets the arming
    RPC's own reply (plus at most one in-flight pong) escape before the
    fault engages."""
    replies = executor.collective_rpc(
        "inject_fault", (name, value, after_writes)
    )
    assert "armed" in replies, replies


# ---------------------------------------------------------------------
# fault 1: stalled heartbeat (wedged host, socket open, NO traffic)
# ---------------------------------------------------------------------
def test_heartbeat_detects_wedged_host_without_requests(fault_deployment):
    """A host that silently stops answering (one-way partition: our
    frames arrive, its frames vanish) is detected by heartbeats alone —
    this test never calls execute_model, the deployment is idle."""
    executor, agent, baseline = fault_deployment
    _arm(executor, "blackhole_writes")
    budget = HB_INTERVAL * (HB_THRESHOLD + 3) + SLACK
    detect = _wait_for(
        lambda: executor.is_failed, budget, "heartbeat failure"
    )
    assert detect < budget
    failure = executor.failure_info
    assert failure is not None
    assert failure.phase == "heartbeat"
    assert failure.host_rank == 1
    assert "heartbeats missed" in failure.message
    # The orphaned agent fail-fasts once the driver drops the peer,
    # releasing its (pretend) TPU devices.
    agent.join(timeout=10)
    assert agent.exitcode not in (None, 0)
    executor.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# fault 2: a single dropped frame must NOT kill the deployment
# ---------------------------------------------------------------------
def test_single_dropped_frame_recovers(fault_deployment):
    """One lost pong = one missed heartbeat, then recovery; the pending
    RPC slot for the lost reply is reclaimed (no future leak) and the
    deployment keeps serving."""
    executor, agent, _ = fault_deployment
    _arm(executor, "drop_writes", value=1)
    time.sleep(HB_INTERVAL * (HB_THRESHOLD + 2))
    assert not executor.is_failed
    out = executor.execute_model(_so())
    assert out.sampled_token_ids == {"r1": [42]}
    peer = executor._remote_hosts[0].peer
    _wait_for(
        lambda: len(peer._pending) == 0,
        HB_INTERVAL * 4,
        "pending-map drain (lost-pong slot reclaimed)",
    )
    assert not executor.is_failed


# ---------------------------------------------------------------------
# fault 3: hung execute (device program wedged, control plane healthy)
# ---------------------------------------------------------------------
def test_hung_execute_attributes_offending_host(fault_deployment):
    """The remote worker hangs mid-execute while its agent keeps
    answering heartbeats: the execute deadline trips, and the timeout
    error names WHICH host missed it (satellite: no more bare
    TimeoutError from _gather)."""
    executor, agent, baseline = fault_deployment
    _arm(executor, "hang_execute")
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="Executor failed") as ei:
        executor.execute_model(_so())
    detect = time.monotonic() - t0
    assert detect < EXECUTE_TIMEOUT + SLACK
    assert "rank 1" in str(ei.value)  # offending host named in the error
    failure = executor.failure_info
    assert failure.phase == "execute"
    assert failure.host_rank == 1
    assert failure.address  # host address captured for the operator
    executor.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# fault 4: agent killed mid-execute
# ---------------------------------------------------------------------
def test_agent_killed_mid_execute(fault_deployment):
    """The agent process dies inside execute_model: detection is
    EOF-fast (no waiting out the execute deadline), and the failure
    names host 1 in whichever phase won the race (the in-flight
    collective or the connection-loss path)."""
    executor, agent, baseline = fault_deployment
    _arm(executor, "die_in_execute")
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="Executor failed"):
        executor.execute_model(_so())
    detect = time.monotonic() - t0
    assert detect < EXECUTE_TIMEOUT  # faster than the timeout budget
    failure = executor.failure_info
    assert failure.phase in ("execute", "connect")
    assert failure.host_rank == 1
    agent.join(timeout=10)
    assert agent.exitcode == 17
    executor.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# fault 5: agent killed between steps (idle connection loss)
# ---------------------------------------------------------------------
def test_agent_killed_between_steps(fault_deployment):
    executor, agent, baseline = fault_deployment
    out = executor.execute_model(_so())  # healthy step first
    assert out.sampled_token_ids == {"r1": [42]}
    agent.terminate()
    t0 = time.monotonic()
    detect = _wait_for(
        lambda: executor.is_failed, 10.0, "disconnect failure"
    )
    assert detect < 10.0
    failure = executor.failure_info
    assert failure.phase == "connect"
    assert failure.host_rank == 1
    assert "connection to agent lost" in failure.message
    with pytest.raises(RuntimeError, match="Executor failed"):
        executor.collective_rpc("check_health")
    executor.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# fault 6: corrupted frame
# ---------------------------------------------------------------------
def test_corrupted_frame_kills_connection(fault_deployment):
    """A corrupted pong fails the driver's unpickle, which tears the
    connection down — attribution is connection-phase with host 1."""
    executor, agent, baseline = fault_deployment
    _arm(executor, "corrupt_writes", value=1)
    budget = HB_INTERVAL * 4 + SLACK
    detect = _wait_for(
        lambda: executor.is_failed, budget, "corrupt-frame failure"
    )
    assert detect < budget
    failure = executor.failure_info
    assert failure.phase == "connect"
    assert failure.host_rank == 1
    executor.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# fault 7: delayed connect
# ---------------------------------------------------------------------
def test_delayed_connect_within_budget_boots(tmp_path, monkeypatch):
    """An agent that dials in late (but inside VDT_CONNECT_TIMEOUT) costs
    boot latency, nothing else."""
    port = get_open_port()
    _fault_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_CONNECT_TIMEOUT_SECONDS", "30")
    agent = _spawn_agent(
        port, {"VDT_FAULT_CONNECT_DELAY_SECONDS": "1.5"}
    )
    t0 = time.monotonic()
    executor = FaultMultiHostExecutor(
        _engine_args(tmp_path).create_engine_config()
    )
    try:
        assert time.monotonic() - t0 >= 1.0  # the delay actually applied
        assert not executor.is_failed
        out = executor.execute_model(_so())
        assert out.sampled_token_ids == {"r1": [42]}
    finally:
        executor.shutdown()
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)


def test_delayed_connect_beyond_budget_fails_boot(tmp_path, monkeypatch):
    """An agent delayed past the connect deadline fails boot in bounded
    time with a connect-phase attribution — and the half-booted executor
    leaks nothing."""
    port = get_open_port()
    _fault_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_CONNECT_TIMEOUT_SECONDS", "2")
    baseline = _vdt_threads()
    agent = _spawn_agent(
        port, {"VDT_FAULT_CONNECT_DELAY_SECONDS": "60"}
    )
    t0 = time.monotonic()
    try:
        with pytest.raises(RuntimeError, match="Executor failed") as ei:
            FaultMultiHostExecutor(
                _engine_args(tmp_path).create_engine_config()
            )
        elapsed = time.monotonic() - t0
        assert elapsed < 2 + SLACK + 2  # bounded by the connect deadline
        assert "[connect]" in str(ei.value)
        assert "0/1 agent(s)" in str(ei.value)
        _assert_no_new_vdt_threads(baseline)
    finally:
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)


# ---------------------------------------------------------------------
# full-engine degradation: /health 503 + structured cause, drain/reject
# ---------------------------------------------------------------------
def _serve(engine, coro_fn):
    state = init_app_state(engine, served_model_name="fault-test")

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.new_event_loop().run_until_complete(go())


def test_agent_killed_mid_generate_drains_and_rejects(engine_deployment):
    """Satellite: the existing kill path, end to end — agent dies while
    a generate() streams; the pending request gets a typed error (never
    a hang), /health flips to 503 with the per-host cause, new requests
    are rejected 503 + Retry-After, and the death is on /metrics."""
    engine, agent, baseline = engine_deployment
    sp = SamplingParams(temperature=0.0, max_tokens=100_000, ignore_eos=True)

    async def go(client):
        outs = 0
        t_kill = None
        with pytest.raises(EngineDeadError) as ei:
            async for _ in engine.generate(
                "victim", prompt_token_ids=[1, 2, 3], sampling_params=sp
            ):
                outs += 1
                if outs == 2:
                    agent.terminate()
                    t_kill = time.monotonic()
        detect = time.monotonic() - t_kill
        assert outs >= 2  # it WAS streaming before the kill
        assert detect < 10.0  # EOF-fast, not execute-timeout-slow
        failure = ei.value.failure
        assert failure is not None
        assert failure.host_rank == 1
        assert failure.phase in ("execute", "connect")

        # /health: 503 + structured cause + Retry-After.
        r = await client.get("/health")
        assert r.status == 503
        assert int(r.headers["Retry-After"]) > 0
        body = await r.json()
        assert body["failure"]["host_rank"] == 1
        assert body["failure"]["phase"] in ("execute", "connect")

        # New engine-level work: immediate typed rejection.
        with pytest.raises(EngineDeadError):
            async for _ in engine.generate(
                "after", prompt_token_ids=[1], sampling_params=sp
            ):
                pass

        # New HTTP work: 503 + Retry-After (retryable, not a 500).
        r = await client.post(
            "/v1/completions",
            json={"model": "m", "prompt": [1, 2], "max_tokens": 4},
        )
        assert r.status == 503
        assert "Retry-After" in r.headers

        # The death reaches Prometheus with its attribution labels.
        r = await client.get("/metrics")
        text = await r.text()
        assert "vllm:engine_dead_info" in text
        assert 'host_rank="1"' in text

    _serve(engine, go)
    engine.shutdown()
    _assert_no_new_vdt_threads(baseline)


def test_wedged_host_fails_idle_engine_health(engine_deployment):
    """The ISSUE's motivating scenario: an IDLE engine (no request ever
    submitted, execute_model never called) over a wedged host must not
    look healthy forever — heartbeats trip engine death and /health
    reports the heartbeat-phase cause."""
    engine, agent, baseline = engine_deployment
    executor = engine.engine.executor
    executor.collective_rpc("inject_fault", ("blackhole_writes", 1.0, 2))
    t0 = time.monotonic()
    budget = HB_INTERVAL * (HB_THRESHOLD + 3) + SLACK

    async def go(client):
        while not engine.errored:
            assert time.monotonic() - t0 < budget, (
                "idle wedged host not detected"
            )
            await asyncio.sleep(0.05)
        r = await client.get("/health")
        assert r.status == 503
        body = await r.json()
        assert body["failure"]["phase"] == "heartbeat"
        assert body["failure"]["host_rank"] == 1
        with pytest.raises(EngineDeadError) as ei:
            async for _ in engine.generate(
                "rejected",
                prompt_token_ids=[1],
                sampling_params=SamplingParams(max_tokens=1),
            ):
                pass
        assert ei.value.failure.phase == "heartbeat"

    _serve(engine, go)
    # Liveness gauge present with the host labeled.
    rendered = engine.metrics.render().decode()
    assert "vllm:host_up" in rendered and 'host_rank="1"' in rendered
    engine.shutdown()
    _assert_no_new_vdt_threads(baseline)


# ---------------------------------------------------------------------
# agent-side symmetry + unit pieces
# ---------------------------------------------------------------------
def test_server_silence_watchdog(monkeypatch):
    """Deployed agent, silent driver → the watchdog returns (→ exit) in
    bounded time; refreshed contact keeps it quiet."""
    monkeypatch.setenv("VDT_HEARTBEAT_INTERVAL_SECONDS", "0.1")
    monkeypatch.setenv("VDT_HEARTBEAT_MISS_THRESHOLD", "2")

    async def silent():
        hb = {"last_contact": time.monotonic()}
        t0 = time.monotonic()
        await asyncio.wait_for(server_silence_watchdog(hb), timeout=5)
        return time.monotonic() - t0

    elapsed = asyncio.new_event_loop().run_until_complete(silent())
    # Budget is interval * (threshold + 1) = 0.3 s; bounded well under 5.
    assert 0.2 <= elapsed < 3.0

    async def refreshed():
        hb = {"last_contact": time.monotonic()}

        async def keepalive():
            while True:
                hb["last_contact"] = time.monotonic()
                await asyncio.sleep(0.05)

        ka = asyncio.ensure_future(keepalive())
        try:
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(server_silence_watchdog(hb), 1.0)
        finally:
            ka.cancel()

    asyncio.new_event_loop().run_until_complete(refreshed())


def test_reconnect_backoff_is_jittered_and_capped():
    for attempt in range(16):
        for _ in range(4):
            d = reconnect_delay(attempt)
            assert 0 < d <= 30.0
    assert reconnect_delay(0) <= 1.0
    assert all(15.0 <= reconnect_delay(10) <= 30.0 for _ in range(6))
    # full jitter: repeated draws at one attempt differ
    assert len({reconnect_delay(5) for _ in range(8)}) > 1


def test_flight_recorder_dump_on_host_failure(tmp_path, monkeypatch):
    """ISSUE 12: an injected HostFailure makes the engine dump its
    flight-recorder ring automatically — a bounded JSON artifact with
    the last N step records and the failure attribution attached."""
    import json as _json

    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.distributed.failure import (
        PHASE_EXECUTE,
        HostFailure,
    )
    from vllm_distributed_tpu.engine.flight_recorder import FIELDS
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine

    fr_dir = tmp_path / "fr"
    monkeypatch.setenv("VDT_FLIGHT_RECORDER_DIR", str(fr_dir))
    monkeypatch.setenv("VDT_FLIGHT_RECORDER_SIZE", "32")
    model_dir = write_llama_config(str(tmp_path / "frm"))
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=64,
            max_model_len=512,
            num_decode_steps=1,
            distributed_executor_backend=MockUniProcExecutor,
        )
    )
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=3, ignore_eos=True
        )
        # More steps than the ring holds: the dump must stay bounded.
        for i in range(60):
            engine.add_request(
                f"fr-{i}", prompt_token_ids=[1, 2, 3], sampling_params=sp
            )
            while engine.has_unfinished_requests():
                engine.step()
        engine.executor._notify_failure(
            HostFailure(
                host_rank=1,
                address="10.0.0.2:30044",
                phase=PHASE_EXECUTE,
                message="injected for the flight-recorder contract",
            )
        )
        dumps = sorted(fr_dir.glob("flightrecorder-host_failure-*.json"))
        assert dumps, "HostFailure produced no flight-recorder artifact"
        payload = _json.loads(dumps[-1].read_text())
        assert payload["reason"] == "host_failure"
        assert payload["extra"]["host_rank"] == 1
        assert payload["extra"]["phase"] == PHASE_EXECUTE
        assert payload["fields"] == list(FIELDS)
        # Bounded: ring-limited records, not one per executed step.
        assert 0 < len(payload["steps"]) <= 32
        assert dumps[-1].stat().st_size < 1 << 20
    finally:
        engine.shutdown()


def test_fault_injector_unit():
    async def go():
        inj = FaultInjector()
        # pass-through when disarmed
        assert await inj.on_write(0, b"x") == (0, b"x")
        # drop honors after_writes then counts down
        inj.arm("drop", 2, after_writes=1)
        assert await inj.on_write(0, b"skip") == (0, b"skip")
        assert await inj.on_write(0, b"a") is None
        assert await inj.on_write(0, b"b") is None
        assert await inj.on_write(0, b"c") == (0, b"c")  # auto-disarm
        assert inj.frames_dropped == 2
        # corrupt flips bytes, preserves length
        inj.arm("corrupt", 1)
        kind, payload = await inj.on_write(1, b"\x00\xff")
        assert (kind, payload) == (1, b"\xff\x00")
        assert await inj.on_write(1, b"ok") == (1, b"ok")
        # blackhole swallows everything until disarmed
        inj.arm("blackhole")
        assert await inj.on_write(0, b"gone") is None
        assert await inj.on_write(0, b"gone2") is None
        inj.disarm()
        assert await inj.on_write(0, b"back") == (0, b"back")

    asyncio.new_event_loop().run_until_complete(go())

# ---------------------------------------------------------------------
# supervised recovery (ISSUE 4): kill → RECOVERING → rebuild → replay
# ---------------------------------------------------------------------
@pytest.fixture
def recovery_deployment(tmp_path, monkeypatch):
    """AsyncLLM over the mocked multihost executor with the supervisor
    armed and a compose-style agent respawner, so a killed host redials
    and the deployment can re-form in-process."""
    port = get_open_port()
    _recovery_env(monkeypatch, tmp_path, port)
    baseline = _vdt_threads()
    agents = RespawningAgent(port, RECOVERY_AGENT_ENV, spawn=_spawn_agent)
    engine = AsyncLLM.from_engine_args(
        _engine_args(
            tmp_path,
            num_decode_steps=1,
            max_model_len=512,
            distributed_executor_backend=FaultMultiHostExecutor,
        )
    )
    yield engine, agents, baseline
    engine.shutdown()
    agents.stop()


def _metric_value(engine, name):
    for line in engine.metrics.render().decode().splitlines():
        if line.startswith(name + "{") or line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    return None


def test_kill_mid_stream_recovers_and_replays(recovery_deployment):
    """The tentpole contract end to end: kill the remote host while a
    greedy stream is mid-generation → /health reports RECOVERING (503 +
    Retry-After from the backoff schedule, body carries the originating
    HostFailure), the respawned agent re-forms the deployment, and the
    interrupted request completes with output bit-identical to an
    uninterrupted run — the client stream never observes an error."""
    engine, agents, baseline = recovery_deployment
    sp = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)
    prompt = [1, 2, 3]
    # Mock seq mode: token i == absolute position, so an uninterrupted
    # greedy run of 12 tokens from a 3-token prompt is exactly 3..14.
    expected = list(range(3, 15))

    async def go(client):
        health_states = []

        async def poll_health():
            while True:
                r = await client.get("/health")
                body = {} if r.status == 200 else await r.json()
                health_states.append(
                    (r.status, body, r.headers.get("Retry-After"))
                )
                await asyncio.sleep(0.05)

        poller = asyncio.create_task(poll_health())
        tokens = []
        killed = False
        async for out in engine.generate(
            "victim", prompt_token_ids=prompt, sampling_params=sp
        ):
            tokens = list(out.outputs[0].token_ids)
            if not killed and len(tokens) >= 3:
                agents.kill_current()
                killed = True
        poller.cancel()
        assert killed
        assert out.finished
        # Replay determinism: bit-identical to the uninterrupted run.
        assert tokens == expected, f"{tokens} != {expected}"
        # The RECOVERING state was observable on /health mid-blip.
        recovering = [
            s for s in health_states
            if s[0] == 503 and s[1].get("status") == "recovering"
        ]
        assert recovering, (
            f"RECOVERING never observed on /health: {health_states}"
        )
        _, body, retry_after = recovering[0]
        assert body["failure"]["host_rank"] == 1
        assert body["failure"]["phase"] in (
            "execute", "connect", "heartbeat"
        )
        # Retry-After derives from the backoff schedule (base 0.2s,
        # cap 2s -> ceil in [1, 2]).
        assert 1 <= int(retry_after) <= 2
        # Recovered: healthy again.
        r = await client.get("/health")
        assert r.status == 200

    _serve(engine, go)
    assert engine.supervisor.restarts_total >= 1
    assert _metric_value(engine, "vllm:engine_restarts_total") >= 1
    assert _metric_value(engine, "vllm:requests_replayed_total") >= 1
    # The dead-info gauge closed the incident (back to 0).
    assert _metric_value(engine, "vllm:engine_dead_info") == 0
    engine.shutdown()
    _assert_no_new_vdt_threads(baseline)


def test_kill_with_steps_queued_in_stream_recovers(tmp_path, monkeypatch):
    """ISSUE 7 fault interplay: with the overlapped dispatch pipeline
    active (step streams + fused async scheduling, two steps in
    flight), kill the remote host while steps are queued in its stream
    — the in-flight/queued frames die with the host, the supervisor
    rebuild still replays the journaled request, the continuation is
    bit-identical, and nothing (loop threads, stream runners, futures)
    leaks."""
    port = get_open_port()
    _recovery_env(monkeypatch, tmp_path, port)
    # Pipelined protocol knobs: fused windows through the two-phase
    # stream path, device slow enough that the driver's two-in-flight
    # discipline keeps the remote inbox non-empty at kill time.
    monkeypatch.setenv("VDT_STEP_STREAMS", "1")
    monkeypatch.setenv("VDT_MOCK_STEP_SECONDS", "0.1")
    agent_env = {
        **RECOVERY_AGENT_ENV,
        "VDT_STEP_STREAMS": "1",
        "VDT_MOCK_STEP_SECONDS": "0.1",
    }
    baseline = _vdt_threads()
    agents = RespawningAgent(port, agent_env, spawn=_spawn_agent)
    engine = AsyncLLM.from_engine_args(
        _engine_args(
            tmp_path,
            num_decode_steps=4,  # fused windows -> non_block pipeline
            max_model_len=512,
            distributed_executor_backend=FaultMultiHostExecutor,
        )
    )
    try:
        prompt = [1, 2, 3]
        max_tokens = 24
        expected = list(range(3, 3 + max_tokens))
        sp = SamplingParams(
            temperature=0.0, max_tokens=max_tokens, ignore_eos=True
        )

        async def go(client):
            tokens = []
            killed = False
            async for out in engine.generate(
                "queued-victim",
                prompt_token_ids=list(prompt),
                sampling_params=sp,
            ):
                tokens = list(out.outputs[0].token_ids)
                if not killed and len(tokens) >= 4:
                    # First fused window delivered: the pipeline is
                    # full — step N+1 is executing and N+2 is queued
                    # in the stream when the host dies.
                    agents.kill_current()
                    killed = True
            assert killed and out.finished
            assert tokens == expected, f"{tokens} != {expected}"
            r = await client.get("/health")
            assert r.status == 200

        _serve(engine, go)
        assert engine.supervisor.restarts_total >= 1
        assert _metric_value(engine, "vllm:requests_replayed_total") >= 1
    finally:
        engine.shutdown()
        agents.stop()
    _assert_no_new_vdt_threads(baseline)


def test_restart_policy_exhaustion_goes_terminal(tmp_path, monkeypatch):
    """Exceeding VDT_MAX_ENGINE_RESTARTS within the crash-loop window
    lands in the pre-supervisor terminal state: typed EngineDeadError
    with attribution, 503 dead (not recovering), new work rejected, and
    no leaked threads (the PR 2 leak assertions)."""
    port = get_open_port()
    _recovery_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_MAX_ENGINE_RESTARTS", "2")
    # Nobody respawns the agent, so every rebuild times out fast.
    monkeypatch.setenv("VDT_CONNECT_TIMEOUT_SECONDS", "1")
    baseline = _vdt_threads()
    agent = _spawn_agent(port, RECOVERY_AGENT_ENV)
    engine = AsyncLLM.from_engine_args(
        _engine_args(
            tmp_path,
            num_decode_steps=1,
            max_model_len=512,
            distributed_executor_backend=FaultMultiHostExecutor,
        )
    )
    sp = SamplingParams(temperature=0.0, max_tokens=50, ignore_eos=True)

    async def go(client):
        outs = 0
        with pytest.raises(EngineDeadError) as ei:
            async for _ in engine.generate(
                "victim", prompt_token_ids=[1, 2, 3], sampling_params=sp
            ):
                outs += 1
                if outs == 2:
                    agent.terminate()
        assert outs >= 2
        failure = ei.value.failure
        assert failure is not None
        assert failure.host_rank == 1
        # Terminal, not recovering: /health says dead with attribution.
        r = await client.get("/health")
        assert r.status == 503
        body = await r.json()
        assert body["status"] == "dead"
        assert body["failure"]["host_rank"] == 1
        # New work: immediate typed rejection.
        with pytest.raises(EngineDeadError):
            async for _ in engine.generate(
                "after", prompt_token_ids=[1], sampling_params=sp
            ):
                pass

    try:
        _serve(engine, go)
        # Both restart attempts were spent before giving up.
        assert engine.supervisor.restarts_total == 2
        assert _metric_value(engine, "vllm:engine_restarts_total") == 2
        engine.shutdown()
        _assert_no_new_vdt_threads(baseline)
    finally:
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)


def test_request_submitted_during_recovery_waits_and_completes(
    recovery_deployment,
):
    """A request that arrives while the engine is RECOVERING queues in
    the intake and is served by the rebuilt engine — accepted work waits
    out the blip instead of failing."""
    engine, agents, baseline = recovery_deployment
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    async def go(client):
        # Kill mid-stream, then immediately submit new work while the
        # supervisor is still rebuilding.
        first_tokens = []
        late = None
        killed = False
        async for out in engine.generate(
            "victim", prompt_token_ids=[1, 2, 3], sampling_params=sp
        ):
            first_tokens = list(out.outputs[0].token_ids)
            if not killed and len(first_tokens) >= 2:
                agents.kill_current()
                killed = True
                late = asyncio.create_task(
                    _collect_gen(
                        engine.generate(
                            "late",
                            prompt_token_ids=[7, 7, 7, 7],
                            sampling_params=sp.clone(),
                        )
                    )
                )
        assert first_tokens == list(range(3, 9))
        late_out = await asyncio.wait_for(late, timeout=30)
        assert late_out.finished
        # Position-deterministic: 4-token prompt -> tokens 4..9.
        assert list(late_out.outputs[0].token_ids) == list(range(4, 10))

    _serve(engine, go)
    engine.shutdown()
    _assert_no_new_vdt_threads(baseline)


async def _collect_gen(gen):
    last = None
    async for out in gen:
        last = out
    return last


# ---------------------------------------------------------------------
# chaos soak (CI satellite): a 2-cycle smoke runs in the fault suite;
# longer loops carry the `soak` marker and stay out of tier-1.
# ---------------------------------------------------------------------
def test_chaos_soak_smoke(tmp_path):
    from vllm_distributed_tpu.testing import write_llama_config as _wlc

    report = run_soak(
        cycles=2, model_dir=_wlc(str(tmp_path / "soak-m"))
    )
    assert report["cycles"] == 2
    assert report["replay_failures"] == 0
    assert report["restarts_total"] >= 2
    assert report["recovery_seconds"]["max"] > 0
    # ISSUE 12: every kill→recover cycle leaves flight-recorder
    # artifacts behind (host_failure and/or recovery dumps).
    assert report["flightrecorder_dumps"] >= 1


@pytest.mark.soak
@pytest.mark.slow
def test_chaos_soak_long(tmp_path):
    from vllm_distributed_tpu.testing import write_llama_config as _wlc

    report = run_soak(
        cycles=10, model_dir=_wlc(str(tmp_path / "soak-m"))
    )
    assert report["replay_failures"] == 0
