"""Overload-resilience suite (ISSUE 8): bounded admission, per-request
deadlines, KV backpressure, preempt-to-shed, and graceful drain.

Layered like the feature: scheduler-level unit tests for the shed
policies, AdmissionController unit tests for the caps, AsyncLLM
end-to-end tests on a uniproc CPU engine (step slowed where queue
pressure must build deterministically), HTTP-level 429/Retry-After and
/drain contract tests, and two mock 2-host deployment tests for the
acceptance criteria: drain→restart→replay is bit-identical (greedy,
VDT_MOCK_TOKEN_SEQ), and ≥5× sustained offered load sheds with bounded
queues/memory instead of falling over.

Everything here is default-off in the engine: seed behavior is
unchanged unless the caps/deadlines are configured, which is exactly
what these tests opt into.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import multiprocessing
import os
import random
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockWorker  # noqa: F401 (import check)
from tools.chaos_soak import RespawningAgent
from vllm_distributed_tpu.config import (
    CacheConfig,
    EngineArgs,
    SchedulerConfig,
)
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.overload import (
    AdmissionController,
    EngineOverloadedError,
)
from vllm_distributed_tpu.engine.request import Request, RequestStatus
from vllm_distributed_tpu.engine.scheduler import Scheduler
from vllm_distributed_tpu.engine.supervisor import (
    EngineSupervisor,
    JournalEntry,
    RestartPolicy,
)
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
    serve_http,
)
from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port

pytestmark = pytest.mark.overload


# ---------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------
def _sp(**kw) -> SamplingParams:
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return SamplingParams(**kw)


def _req(rid: str, prompt=(1, 2), **sp_kw) -> Request:
    return Request(
        request_id=rid,
        prompt_token_ids=list(prompt),
        sampling_params=_sp(**sp_kw),
    )


def _mk_engine(tmp_path, name: str, **engine_kw) -> AsyncLLM:
    """Uniproc CPU engine with dummy weights (no safetensors load; the
    overload machinery never looks at weight values)."""
    kw = dict(
        model=write_llama_config(str(tmp_path / name)),
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=64,
        max_model_len=128,
        num_decode_steps=1,
    )
    kw.update(engine_kw)
    return AsyncLLM.from_engine_args(EngineArgs(**kw))


@contextlib.contextmanager
def _slowed(engine: AsyncLLM, delay: float):
    """Slow the engine step so queue pressure builds deterministically
    (the pattern test_async_llm uses for loop-isolation tests)."""
    real = engine.engine.step

    def slow_step():
        time.sleep(delay)
        return real()

    engine.engine.step = slow_step
    try:
        yield
    finally:
        engine.engine.step = real


async def _consume(agen):
    last = None
    async for item in agen:
        last = item
    return last


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


# ---------------------------------------------------------------------
# scheduler-level: deadline shed + preempt-to-shed + token accounting
# ---------------------------------------------------------------------
def _mk_sched(num_pages=8, page_size=2, **cfg_kw) -> Scheduler:
    cfg_kw.setdefault("max_num_seqs", 4)
    cfg_kw.setdefault("max_num_batched_tokens", 64)
    cfg_kw.setdefault("max_model_len", 64)
    cfg_kw.setdefault("num_decode_steps", 1)
    return Scheduler(
        SchedulerConfig(**cfg_kw),
        CacheConfig(page_size=page_size),
        num_pages,
    )


def test_waiting_token_counter_tracks_queue():
    sched = _mk_sched()
    a, b = _req("a", [1, 2, 3], max_tokens=4), _req("b", [4, 5], max_tokens=4)
    sched.add_request(a)
    sched.add_request(b)
    assert sched.num_waiting_tokens == 5
    sched.abort_request("b")
    assert sched.num_waiting_tokens == 3
    sched.schedule()  # admits a
    assert sched.num_waiting_tokens == 0
    assert len(sched.waiting) == 0


def test_expired_waiting_request_is_shed_before_prefill():
    sched = _mk_sched()
    req = _req("late", [1, 2], max_tokens=4)
    req.deadline_mono = time.monotonic() - 0.01  # already expired
    sched.add_request(req)
    out = sched.schedule()
    # Never scheduled: no prefill spent, no worker notice (they never
    # saw it), finished out of band with the timeout status.
    assert "late" not in out.num_scheduled_tokens
    assert out.finished_req_ids == []
    shed = sched.take_finished_out_of_band()
    assert [r.request_id for r in shed] == ["late"]
    assert shed[0].status == RequestStatus.FINISHED_TIMEOUT
    assert sched.num_waiting_tokens == 0
    assert not sched.has_unfinished_requests()
    assert sched.num_timeouts == 1


def test_expired_running_request_finishes_with_partial_output():
    sched = _mk_sched()
    req = _req("mid", [1, 2], max_tokens=8, deadline_ms=100_000)
    req.set_deadline(0)  # what LLMEngine.add_request does
    sched.add_request(req)
    # A second live request keeps the post-shed step non-empty, so the
    # finish notice can ride it (empty outputs are never dispatched;
    # notices on them are held for the next real step).
    other = _req("other", [3, 4], max_tokens=8)
    sched.add_request(other)
    out = sched.schedule()
    sched.update_from_output(out, {"mid": [7], "other": [9]})
    assert req.status == RequestStatus.RUNNING
    req.deadline_mono = time.monotonic() - 0.01  # expire mid-decode
    out2 = sched.schedule()
    # The finish notice rides the step like any other finish, so the
    # workers drop their mirrored state.
    assert "mid" in out2.finished_req_ids
    assert "mid" not in out2.num_scheduled_tokens
    assert "other" in out2.num_scheduled_tokens
    shed = sched.take_finished_out_of_band()
    assert [r.request_id for r in shed] == ["mid"]
    assert shed[0].status == RequestStatus.FINISHED_TIMEOUT
    assert shed[0].output_token_ids == [7]  # partial output survives


def test_preempt_shed_policy_threshold():
    sched = _mk_sched(preempt_shed_threshold=1)
    req = _req("thrash", [1, 2], max_tokens=8)
    sched.add_request(req)
    sched.schedule()
    assert req.status == RequestStatus.RUNNING
    # First preemption: under threshold, requeued as usual.
    sched._preempt(req, set())
    assert req.status == RequestStatus.PREEMPTED
    assert req in sched.waiting
    assert sched.take_finished_out_of_band() == []
    sched.schedule()  # resume
    assert req.status == RequestStatus.RUNNING
    # Second preemption crosses the threshold: shed, not requeued.
    sched._preempt(req, set())
    assert req.status == RequestStatus.FINISHED_SHED
    assert req not in sched.waiting
    assert "thrash" not in sched.requests
    shed = sched.take_finished_out_of_band()
    assert [r.request_id for r in shed] == ["thrash"]
    assert sched.num_sheds == 1


def test_preempt_shed_disabled_by_default():
    sched = _mk_sched()  # threshold 0 = seed behavior
    req = _req("resilient", [1, 2], max_tokens=8)
    sched.add_request(req)
    for _ in range(5):
        sched.schedule()
        assert req.status == RequestStatus.RUNNING
        sched._preempt(req, set())
        assert req.status == RequestStatus.PREEMPTED
    assert sched.take_finished_out_of_band() == []
    assert req.num_preemptions == 5


# ---------------------------------------------------------------------
# AdmissionController unit tests
# ---------------------------------------------------------------------
class _FakeAllocator:
    def __init__(self, num_pages=17, page_size=16, free=None):
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_free_pages = free if free is not None else num_pages - 1

    def estimate_cached_tokens(self, token_ids):
        return 0


class _FakeScheduler:
    def __init__(self, waiting=0, waiting_tokens=0, **alloc_kw):
        self.waiting = [None] * waiting
        self.num_waiting_tokens = waiting_tokens
        self.allocator = _FakeAllocator(**alloc_kw)


def _controller(sched=None, **cfg_kw) -> AdmissionController:
    cfg_kw.setdefault("max_num_seqs", 4)
    cfg_kw.setdefault("max_num_batched_tokens", 64)
    ctl = AdmissionController(SchedulerConfig(**cfg_kw), retry_after=7)
    ctl.attach_scheduler(sched or _FakeScheduler())
    return ctl


def test_admission_defaults_are_wide_open():
    ctl = _controller(_FakeScheduler(waiting=10_000, waiting_tokens=1 << 20))
    ctl.check(1, 1 << 16)  # no caps configured: anything goes


def test_admission_queue_cap():
    ctl = _controller(_FakeScheduler(waiting=2), max_waiting_requests=3)
    ctl.reserve(5)  # depth 2 + pending 1 = 3 == cap: admitted
    with pytest.raises(EngineOverloadedError) as ei:
        ctl.reserve(5)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after == 7
    # Consumption frees the pending slot.
    ctl.consumed(5)
    assert ctl.pending() == (0, 0)


def test_admission_token_cap():
    ctl = _controller(
        _FakeScheduler(waiting_tokens=6), max_queued_tokens=10
    )
    ctl.reserve(4)
    with pytest.raises(EngineOverloadedError) as ei:
        ctl.reserve(1)
    assert ei.value.reason == "queued_tokens"
    ctl.release(4)
    ctl.reserve(1)  # released capacity is reusable


def test_admission_kv_watermark():
    # usable = 16, watermark 0.5 -> keep 8 free.  120-token prompt
    # needs ceil(120/16)+1 = 9 pages; 16 - 9 = 7 < 8 -> reject.
    sched = _FakeScheduler(num_pages=17, page_size=16)
    ctl = _controller(sched, kv_admission_watermark=0.5)
    with pytest.raises(EngineOverloadedError) as ei:
        ctl.check(1, 120, list(range(120)))
    assert ei.value.reason == "kv_pressure"
    ctl.check(1, 16, list(range(16)))  # 2 pages: plenty left


def test_admission_drain_state():
    ctl = _controller()
    ctl.begin_drain()
    with pytest.raises(EngineOverloadedError) as ei:
        ctl.check()
    assert ei.value.reason == "draining"
    assert ctl.drain_state_name == "draining"
    ctl.finish_drain()
    assert ctl.drain_state_name == "drained"


# ---------------------------------------------------------------------
# AsyncLLM end-to-end on a uniproc CPU engine
# ---------------------------------------------------------------------
def test_queue_cap_rejects_burst(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_MAX_WAITING_REQUESTS", "2")
    engine = _mk_engine(tmp_path, "qcap", max_num_seqs=1)
    try:
        with _slowed(engine, 0.3):

            async def go():
                outcomes = {"completed": 0}
                rejects = []

                async def one(i):
                    try:
                        out = await _consume(
                            engine.generate(
                                f"q{i}",
                                prompt_token_ids=[1, 2, 3],
                                sampling_params=_sp(max_tokens=2),
                            )
                        )
                        assert out.finished
                        outcomes["completed"] += 1
                    except EngineOverloadedError as e:
                        rejects.append(e)

                # Warm one request into RUNNING (max_num_seqs=1), then
                # burst: the waiting queue caps at 2, the rest 429.
                first = asyncio.create_task(one(0))
                await asyncio.sleep(0.15)
                await asyncio.gather(*(one(i) for i in range(1, 6)))
                await first
                return outcomes, rejects

            outcomes, rejects = _run(go())
        assert rejects, "cap never triggered"
        assert all(e.reason == "queue_full" for e in rejects)
        assert outcomes["completed"] + len(rejects) == 6
        # The warm request may still occupy a waiting slot when the
        # burst lands (slow step delays its first schedule), so the
        # admitted count is 2 or 3 depending on that race — but the
        # cap itself is exact: everyone past it was rejected.
        assert outcomes["completed"] >= 2
        # The rejection counter observed every shed.
        rendered = engine.metrics.render().decode()
        assert 'vllm:requests_rejected_total{model_name' in rendered
    finally:
        engine.shutdown()


def test_queued_token_cap_rejects(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_MAX_QUEUED_TOKENS", "8")
    engine = _mk_engine(tmp_path, "tcap", max_num_seqs=1)
    try:
        with _slowed(engine, 0.3):

            async def go():
                completed, rejects = 0, []

                async def one(i):
                    nonlocal completed
                    try:
                        await _consume(
                            engine.generate(
                                f"t{i}",
                                prompt_token_ids=[1, 2, 3, 4, 5],
                                sampling_params=_sp(max_tokens=2),
                            )
                        )
                        completed += 1
                    except EngineOverloadedError as e:
                        rejects.append(e)

                first = asyncio.create_task(one(0))
                await asyncio.sleep(0.15)
                await asyncio.gather(*(one(i) for i in range(1, 4)))
                await first
                return completed, rejects

            completed, rejects = _run(go())
        assert rejects, "token cap never triggered"
        assert all(e.reason == "queued_tokens" for e in rejects)
        assert completed + len(rejects) == 4
    finally:
        engine.shutdown()


def test_kv_watermark_rejects_long_prompt(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_KV_ADMISSION_WATERMARK", "0.5")
    engine = _mk_engine(
        tmp_path, "wm", num_kv_pages=17, max_model_len=256
    )
    try:

        async def go():
            # 120-token prompt: ~9 pages against 16 usable with a
            # keep-8-free watermark -> rejected before any prefill.
            with pytest.raises(EngineOverloadedError) as ei:
                await _consume(
                    engine.generate(
                        "long",
                        prompt_token_ids=list(range(1, 121)),
                        sampling_params=_sp(max_tokens=2),
                    )
                )
            assert ei.value.reason == "kv_pressure"
            # A short prompt sails through the same watermark.
            out = await _consume(
                engine.generate(
                    "short",
                    prompt_token_ids=list(range(1, 17)),
                    sampling_params=_sp(max_tokens=2),
                )
            )
            assert out.finished

        _run(go())
    finally:
        engine.shutdown()


def test_deadline_waiting_request_times_out(tmp_path):
    engine = _mk_engine(tmp_path, "dls", max_num_seqs=1)
    try:
        with _slowed(engine, 0.25):

            async def go():
                hog = asyncio.create_task(
                    _consume(
                        engine.generate(
                            "hog",
                            prompt_token_ids=[1, 2, 3],
                            sampling_params=_sp(max_tokens=8),
                        )
                    )
                )
                await asyncio.sleep(0.1)
                late = await _consume(
                    engine.generate(
                        "late",
                        prompt_token_ids=[4, 5],
                        sampling_params=_sp(max_tokens=4, deadline_ms=300),
                    )
                )
                return await hog, late

            hog, late = _run(go())
        assert hog.finished
        assert len(hog.outputs[0].token_ids) == 8  # hog is unaffected
        assert late.finished
        assert late.outputs[0].finish_reason == "timeout"
        assert late.outputs[0].token_ids == []  # shed before prefill
    finally:
        engine.shutdown()


def test_deadline_running_request_partial_output(tmp_path):
    engine = _mk_engine(tmp_path, "dlr")
    try:
        with _slowed(engine, 0.15):

            async def go():
                return await _consume(
                    engine.generate(
                        "slowpoke",
                        prompt_token_ids=[1, 2, 3],
                        sampling_params=_sp(
                            max_tokens=50, deadline_ms=500
                        ),
                    )
                )

            out = _run(go())
        assert out.finished
        assert out.outputs[0].finish_reason == "timeout"
        # Partial output: started decoding, stopped at the deadline.
        assert 0 < len(out.outputs[0].token_ids) < 50
    finally:
        engine.shutdown()


def test_server_default_deadline_env(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_DEFAULT_DEADLINE_MS", "500")
    engine = _mk_engine(tmp_path, "dld")
    try:
        with _slowed(engine, 0.15):
            out = _run(
                _consume(
                    engine.generate(
                        "default-dl",
                        prompt_token_ids=[1, 2, 3],
                        sampling_params=_sp(max_tokens=50),
                    )
                )
            )
        assert out.outputs[0].finish_reason == "timeout"
        assert len(out.outputs[0].token_ids) < 50
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------
# supervisor: an expired request is never replayed
# ---------------------------------------------------------------------
class _StubScheduler:
    def __init__(self):
        self.requests = {}


class _StubEngine:
    def __init__(self):
        self.scheduler = _StubScheduler()
        self.detokenizers = {}
        self.added = []

    def add_request(
        self,
        request_id,
        prompt=None,
        prompt_token_ids=None,
        sampling_params=None,
        trace_ctx=None,
    ):
        req = Request(
            request_id=request_id,
            prompt_token_ids=list(prompt_token_ids or [1]),
            sampling_params=sampling_params or SamplingParams(),
        )
        self.scheduler.requests[request_id] = req
        self.added.append(request_id)


class _StubLLM:
    def __init__(self):
        self._journal = {}
        self.delivered = []

    def _to_request_queue(self, request_id, item):
        self.delivered.append((request_id, item))


def test_replay_skips_expired_entry():
    llm = _StubLLM()
    sup = EngineSupervisor(
        llm, policy=RestartPolicy(1, 0.1, 1.0, 60.0)
    )
    expired = JournalEntry(
        request_id="expired",
        prompt=None,
        prompt_token_ids=[1, 2],
        sampling_params=_sp(max_tokens=8),
        admitted=True,
        deadline_mono=time.monotonic() - 1.0,
        emitted_token_ids=[5, 6],
    )
    live = JournalEntry(
        request_id="live",
        prompt=None,
        prompt_token_ids=[3, 4],
        sampling_params=_sp(max_tokens=8),
        admitted=True,
        deadline_mono=time.monotonic() + 60.0,
        emitted_token_ids=[7],
    )
    llm._journal = {"expired": expired, "live": live}
    engine = _StubEngine()
    replayed = sup._replay(engine)
    assert replayed == 1
    assert engine.added == ["live"]  # the expired one never re-admitted
    # The expired request's client got a finished timeout output with
    # what was already delivered.
    assert len(llm.delivered) == 1
    rid, out = llm.delivered[0]
    assert rid == "expired"
    assert out.finished
    assert out.outputs[0].finish_reason == "timeout"
    assert out.outputs[0].token_ids == [5, 6]
    assert expired.finished
    # The live replay preserved its ORIGINAL deadline.
    req = engine.scheduler.requests["live"]
    assert req.deadline_mono == live.deadline_mono


def test_journal_entry_drain_round_trip():
    entry = JournalEntry(
        request_id="rt",
        prompt="hi",
        prompt_token_ids=[1, 2, 3],
        sampling_params=_sp(max_tokens=9, deadline_ms=1000),
        emitted_token_ids=[4, 5],
        emitted_logprobs=[{4: -0.5}, {5: -0.25}],
        emitted_cumulative_logprob=-0.75,
    )
    back = JournalEntry.from_dict(
        json.loads(json.dumps(entry.to_dict()))
    )
    assert back.request_id == "rt"
    assert back.prompt_token_ids == [1, 2, 3]
    assert back.sampling_params.max_tokens == 9
    assert back.emitted_token_ids == [4, 5]
    assert back.emitted_logprobs == [{4: -0.5}, {5: -0.25}]
    assert back.deadline_mono is None  # never crosses processes


# ---------------------------------------------------------------------
# HTTP: 429 + Retry-After, deadline header, /drain, /health states
# ---------------------------------------------------------------------
def _client_call(app, coro_fn):
    async def go():
        server = TestServer(app)
        client = TestClient(server)
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return _run(go())


def test_http_429_retry_after_and_drain(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_MAX_WAITING_REQUESTS", "1")
    monkeypatch.setenv("VDT_OVERLOAD_RETRY_AFTER_SECONDS", "3")
    engine = _mk_engine(tmp_path, "http", max_num_seqs=1)
    state = init_app_state(engine, served_model_name="ov")

    async def go(client):
        body = {
            "prompt": [1, 2, 3],
            "max_tokens": 2,
            "temperature": 0.0,
            "ignore_eos": True,
        }
        with _slowed(engine, 0.3):
            responses = await asyncio.gather(
                *(
                    client.post("/v1/completions", json=body)
                    for _ in range(5)
                )
            )
            by_status = {}
            for r in responses:
                by_status.setdefault(r.status, []).append(r)
            assert 429 in by_status, {
                s: len(v) for s, v in by_status.items()
            }
            rejected = by_status[429][0]
            assert rejected.headers["Retry-After"] == "3"
            payload = await rejected.json()
            assert payload["type"] == "overloaded_error"
            assert payload["reason"] == "queue_full"
            assert 200 in by_status  # the admitted ones served fine
        # Malformed deadline header is a 400, not a surprise.
        r = await client.post(
            "/v1/completions",
            json=body,
            headers={"X-VDT-Deadline-Ms": "soon"},
        )
        assert r.status == 400
        # A generous header deadline passes through harmlessly.
        r = await client.post(
            "/v1/completions",
            json=body,
            headers={"X-VDT-Deadline-Ms": "60000"},
        )
        assert r.status == 200
        # The server's own 429 counter observed the sheds.
        metrics_text = await (await client.get("/metrics")).text()
        rejected_lines = [
            line
            for line in metrics_text.splitlines()
            if line.startswith("vllm:requests_rejected_total{")
            and 'reason="queue_full"' in line
        ]
        assert rejected_lines and float(
            rejected_lines[0].rsplit(" ", 1)[1]
        ) >= 1
        # ---- drain: stop admission, report state, 429 new work ----
        r = await client.post("/drain", json={})
        drained = await r.json()
        assert r.status == 200
        assert drained["status"] == "drained"
        assert drained["aborted"] == 0  # nothing was in flight
        health = await client.get("/health")
        assert health.status == 503
        assert (await health.json())["status"] == "drained"
        r = await client.post("/v1/completions", json=body)
        assert r.status == 429
        assert (await r.json())["reason"] == "draining"
        metrics_text = await (await client.get("/metrics")).text()
        assert "vllm:engine_drain_state" in metrics_text

    try:
        _client_call(build_app(state), go)
    finally:
        engine.shutdown()


def test_nonstreaming_client_disconnect_aborts(tmp_path):
    """ISSUE 8 satellite: a non-streaming completion whose client hangs
    up must stop generating server-side (handler_cancellation in
    serve_http; streaming already aborted via its failing writes)."""
    engine = _mk_engine(tmp_path, "disc")
    port = get_open_port()

    async def go():
        state = init_app_state(engine, served_model_name="d")
        runner = await serve_http(
            build_app(state), host="127.0.0.1", port=port
        )
        try:
            with _slowed(engine, 0.15):
                body = json.dumps(
                    {
                        "prompt": [1, 2, 3],
                        "max_tokens": 100,  # ~15s if left running
                        "temperature": 0.0,
                        "ignore_eos": True,
                    }
                ).encode()
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                writer.write(
                    b"POST /v1/completions HTTP/1.1\r\n"
                    b"Host: t\r\nContent-Type: application/json\r\n"
                    b"Content-Length: %d\r\n\r\n" % len(body) + body
                )
                await writer.drain()
                # Let the request get admitted and start decoding...
                t0 = time.monotonic()
                while (
                    not engine.engine.scheduler.has_unfinished_requests()
                    and time.monotonic() - t0 < 5
                ):
                    await asyncio.sleep(0.05)
                assert engine.engine.scheduler.has_unfinished_requests()
                # ...then vanish.
                writer.close()
                t0 = time.monotonic()
                while engine.engine.scheduler.has_unfinished_requests():
                    assert time.monotonic() - t0 < 6, (
                        "request kept generating after client disconnect"
                    )
                    await asyncio.sleep(0.1)
        finally:
            await runner.cleanup()

    try:
        _run(go())
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------
# mock 2-host deployment: the two acceptance tests
# ---------------------------------------------------------------------
class OverloadMultiHostExecutor(MultiHostExecutor):
    worker_cls = "tests.mock_worker.MockWorker"


def _agent_with_env(port, env):
    for k, v in (env or {}).items():
        os.environ[k] = v
    from vllm_distributed_tpu.distributed.agent import remote_main

    remote_main("127.0.0.1", port)


def _spawn_agent(port, extra_env=None):
    env = {
        "VDT_ADVERTISE_NUM_CHIPS": "4",
        "VDT_ADVERTISE_PLATFORM": "cpu",
        "VDT_MOCK_TOKEN_SEQ": "1",
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
        **(extra_env or {}),
    }
    proc = multiprocessing.Process(
        target=_agent_with_env, args=(port, env), daemon=True
    )
    proc.start()
    return proc


def _deployment_env(monkeypatch, tmp_path, port):
    monkeypatch.setenv("VDT_SERVER_PORT", str(port))
    monkeypatch.setenv("VDT_CONNECT_TIMEOUT_SECONDS", "30")
    monkeypatch.setenv("VDT_HEARTBEAT_INTERVAL_SECONDS", "0.5")
    monkeypatch.setenv("VDT_HEARTBEAT_MISS_THRESHOLD", "3")
    monkeypatch.setenv("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "10")
    monkeypatch.setenv("VDT_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_MOCK_EXECUTE_SLEEP_SECONDS", "0.05")


def _deployment_args(tmp_path, **kw):
    return EngineArgs(
        model=write_llama_config(str(tmp_path / "m")),
        skip_tokenizer_init=True,
        load_format="dummy",
        num_hosts=2,
        num_decode_steps=1,
        max_model_len=512,
        distributed_executor_backend=OverloadMultiHostExecutor,
        **kw,
    )


def test_drain_restart_replay_bit_identical(tmp_path, monkeypatch):
    """Acceptance: /drain under live streaming traffic → restart →
    journal replay loses zero admitted requests and finishes them
    bit-identically (greedy, VDT_MOCK_TOKEN_SEQ)."""
    port = get_open_port()
    journal = tmp_path / "drain.json"
    _deployment_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_DRAIN_JOURNAL_PATH", str(journal))
    agents = RespawningAgent(port, spawn=_spawn_agent)
    engine = AsyncLLM.from_engine_args(_deployment_args(tmp_path))
    prompt = [1, 2, 3]
    max_tokens = 20
    # Mock seq mode: token i == absolute position, so the uninterrupted
    # greedy run is exactly 3..22 — the drain+restart+replay run must
    # produce the SAME sequence.
    expected = list(range(3, 3 + max_tokens))

    async def phase_one():
        tokens_seen: list[int] = []
        cut = asyncio.Event()

        async def victim():
            try:
                async for out in engine.generate(
                    "handoff",
                    prompt_token_ids=list(prompt),
                    sampling_params=_sp(max_tokens=max_tokens),
                ):
                    tokens_seen[:] = list(out.outputs[0].token_ids)
                pytest.fail("victim finished before the drain cut it")
            except EngineOverloadedError as e:
                assert e.reason == "draining"
                cut.set()

        vt = asyncio.create_task(victim())
        t0 = time.monotonic()
        while len(tokens_seen) < 2:
            assert time.monotonic() - t0 < 20
            await asyncio.sleep(0.02)
        result = await engine.drain(timeout=0.2)
        await asyncio.wait_for(vt, timeout=5)
        assert cut.is_set()
        assert result["journaled"] == 1
        assert result["aborted"] == 1
        assert result["journal_path"] == str(journal)
        # /health surfaces the drain state.
        assert engine.drain_state_name == "drained"
        return list(tokens_seen)

    try:
        tokens_before = _run(phase_one())
        assert tokens_before == expected[: len(tokens_before)]
        assert len(tokens_before) < max_tokens  # genuinely mid-stream
    finally:
        engine.shutdown()
    assert journal.exists()

    # "Restart": a fresh AsyncLLM in the same environment picks the
    # journal up and finishes the drained request when the client
    # re-attaches under the same request id.
    engine2 = AsyncLLM.from_engine_args(_deployment_args(tmp_path))
    try:
        assert engine2.resumable_request_ids() == ["handoff"]

        async def phase_two():
            return await _consume(engine2.generate("handoff"))

        final = _run(phase_two())
        assert final.finished
        assert final.outputs[0].finish_reason == "length"
        # Zero lost admitted work, bit-identical greedy output.
        assert list(final.outputs[0].token_ids) == expected
        # The journal was consumed: a crash loop can't double-replay.
        assert not journal.exists()
        assert engine2.resumable_request_ids() == []
    finally:
        engine2.shutdown()
        agents.stop()


def test_overload_5x_sheds_and_stays_bounded(tmp_path, monkeypatch):
    """Acceptance: ≥5× sustained offered load on the mock 2-host
    deployment sheds with typed rejections, keeps admitted-request ITL
    p99 bounded, and the waiting queue + RSS plateau."""
    port = get_open_port()
    _deployment_env(monkeypatch, tmp_path, port)
    monkeypatch.setenv("VDT_MAX_WAITING_REQUESTS", "8")
    baseline_threads = {
        t for t in threading.enumerate() if t.name.startswith("vdt-")
    }
    agent = _spawn_agent(port)
    engine = AsyncLLM.from_engine_args(
        _deployment_args(tmp_path, max_num_seqs=4)
    )
    # Capacity ceiling: 4 seats × (1 token / 0.05 s step) = 80 tok/s →
    # at 5 output tokens/request, ≤16 req/s.  Offer 80 req/s = ≥5×.
    offered_rps = 80.0
    duration_s = 2.5
    stats = {"completed": 0, "rejected": 0, "errors": 0}
    itls: list[float] = []
    max_waiting = 0

    async def one(i: int):
        last = None
        try:
            async for out in engine.generate(
                f"ov-{i}",
                prompt_token_ids=[1, 2, 3],
                sampling_params=_sp(max_tokens=5),
            ):
                now = time.monotonic()
                if last is not None:
                    itls.append(now - last)
                last = now
            stats["completed"] += 1
        except EngineOverloadedError:
            stats["rejected"] += 1
        except Exception:  # noqa: BLE001 — accounted and asserted == 0
            stats["errors"] += 1

    async def go():
        nonlocal max_waiting
        rng = random.Random(5)
        rss0 = _rss_mb()
        tasks = []
        end = time.monotonic() + duration_s
        i = 0
        while time.monotonic() < end:
            tasks.append(asyncio.create_task(one(i)))
            i += 1
            max_waiting = max(
                max_waiting, len(engine.engine.scheduler.waiting)
            )
            await asyncio.sleep(rng.expovariate(offered_rps))
        await asyncio.gather(*tasks)
        return rss0, _rss_mb(), i

    try:
        rss0, rss1, offered = _run(go())
    finally:
        engine.shutdown()
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)

    assert stats["errors"] == 0, stats
    assert stats["completed"] + stats["rejected"] == offered
    # Load genuinely exceeded capacity and the engine SHED rather than
    # queued: most offered work was rejected with the typed 429 error.
    assert offered >= duration_s * 40, f"arrival loop too slow: {offered}"
    assert stats["rejected"] > stats["completed"], stats
    assert stats["completed"] > 0
    # Bounded admission held: the waiting queue never exceeded the cap.
    assert max_waiting <= 8, max_waiting
    # Admitted-request ITL stayed bounded (sheds can't pollute this:
    # rejected requests never produce tokens).
    if itls:
        p99 = sorted(itls)[min(len(itls) - 1, int(0.99 * len(itls)))]
        assert p99 < 2.0, f"ITL p99 {p99:.2f}s under overload"
    # Memory plateaued: shedding, not queue growth.
    assert rss1 - rss0 < 150, f"RSS grew {rss1 - rss0:.0f} MiB"
    # No leaked engine threads after shutdown.
    t0 = time.monotonic()
    while time.monotonic() - t0 < 8:
        extra = [
            t
            for t in threading.enumerate()
            if t.name.startswith("vdt-") and t not in baseline_threads
        ]
        if not extra:
            break
        time.sleep(0.1)
    assert not extra, f"leaked threads: {[t.name for t in extra]}"


def test_chaos_soak_overload_smoke(tmp_path):
    """Satellite: the chaos-soak overload phase holds its bounded-memory
    contract across kill→recover cycles (1-cycle smoke; longer loops
    stay behind the soak marker)."""
    from tools.chaos_soak import run_soak

    report = run_soak(
        cycles=1,
        model_dir=write_llama_config(str(tmp_path / "soak")),
        max_tokens=10,
        kill_after_tokens=3,
        overload_rps=40.0,
        overload_cap=6,
    )
    assert report["replay_failures"] == 0
    overload = report["overload"]
    assert overload["offered"] > 0
    assert overload["rejected"] > 0, overload
    assert overload["max_waiting_depth"] <= 6, overload
    assert overload["bounded"], overload
