"""`vdt bench serve` — HTTP/SSE serving benchmark against a live server
(the reference wires `vllm bench serve`, launch.py:21-25; BASELINE.md's
tracked TTFT/ITL are SERVING metrics, so they must be measurable through
the API, not just the engine loop)."""

import argparse
import asyncio
import socket

import pytest
from aiohttp.test_utils import TestServer

from tests.utils import add_tiny_tokenizer, make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.cli import _bench_serve_async
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)


@pytest.fixture(scope="module")
def live_server(tmp_path_factory):
    model_dir = make_tiny_llama(str(tmp_path_factory.mktemp("bsrv")))
    add_tiny_tokenizer(model_dir)
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            num_kv_pages=128,
            max_model_len=128,
            max_num_seqs=8,
        )
    )
    state = init_app_state(engine, served_model_name="tiny")

    loop = asyncio.new_event_loop()
    port = None
    server = None

    async def start():
        nonlocal server, port
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = TestServer(build_app(state), port=port)
        await server.start_server()

    loop.run_until_complete(start())
    yield loop, f"http://127.0.0.1:{port}"
    loop.run_until_complete(server.close())
    engine.shutdown()
    loop.close()


def test_bench_serve_per_class_slo_mix(live_server):
    """ISSUE 12 satellite: --slo-class drives a per-class request mix
    and the report carries per-class client percentiles plus the
    server's own goodput judgment from the new counters."""
    loop, url = live_server
    args = argparse.Namespace(
        url=url,
        model="tiny",
        num_prompts=6,
        concurrency=3,
        input_len=8,
        output_len=8,
        slo_classes=["interactive:2", "batch"],
    )
    result = loop.run_until_complete(_bench_serve_async(args))
    per_class = result["per_class"]
    assert set(per_class) == {"interactive", "batch"}
    # 2:1 mix over 6 requests = 4 interactive, 2 batch.
    assert per_class["interactive"]["completed"] == 4
    assert per_class["batch"]["completed"] == 2
    assert per_class["interactive"]["ttft_s"]["p50"] > 0
    # Server-side goodput: no targets configured in this server, so
    # every completed request attains trivially.
    for cls in ("interactive", "batch"):
        assert per_class[cls]["server_goodput_ratio"] == 1.0
        assert per_class[cls]["server_ttft_attain_ratio"] == 1.0


def test_bench_serve_reports_http_path_metrics(live_server):
    loop, url = live_server
    args = argparse.Namespace(
        url=url,
        model="tiny",
        num_prompts=6,
        concurrency=3,
        input_len=8,
        output_len=12,
    )
    result = loop.run_until_complete(_bench_serve_async(args))

    assert result["mode"] == "serve"
    assert result["output_tokens_per_s"] > 0
    assert result["requests_per_s"] > 0
    # Client-side latency distributions through the SSE stream.
    assert result["ttft_s"]["p50"] > 0
    assert result["itl_ms"]["p50"] >= 0
    assert result["ttft_s"]["p99"] >= result["ttft_s"]["p50"]
    # Server-side cross-check from /metrics deltas over the run.
    sm = result["server_metrics"]
    assert sm["generation_tokens"] == 6 * 12
    assert sm["ttft_mean_s"] > 0
    # The two views of TTFT must be the same order of magnitude (client
    # adds only HTTP overhead on loopback).
    assert sm["ttft_mean_s"] < result["ttft_s"]["p99"] * 3 + 1.0
