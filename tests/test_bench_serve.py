"""`vdt bench serve` — HTTP/SSE serving benchmark against a live server
(the reference wires `vllm bench serve`, launch.py:21-25; BASELINE.md's
tracked TTFT/ITL are SERVING metrics, so they must be measurable through
the API, not just the engine loop)."""

import argparse
import asyncio
import socket

import pytest
from aiohttp.test_utils import TestServer

from tests.utils import add_tiny_tokenizer, make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.entrypoints.cli import (
    _bench_serve_async,
    parse_len_range,
    parse_tenants,
)
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)


@pytest.fixture(scope="module")
def live_server(tmp_path_factory):
    model_dir = make_tiny_llama(str(tmp_path_factory.mktemp("bsrv")))
    add_tiny_tokenizer(model_dir)
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            num_kv_pages=128,
            max_model_len=128,
            max_num_seqs=8,
        )
    )
    state = init_app_state(engine, served_model_name="tiny")

    loop = asyncio.new_event_loop()
    port = None
    server = None

    async def start():
        nonlocal server, port
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = TestServer(build_app(state), port=port)
        await server.start_server()

    loop.run_until_complete(start())
    yield loop, f"http://127.0.0.1:{port}"
    loop.run_until_complete(server.close())
    engine.shutdown()
    loop.close()


def test_bench_serve_per_class_slo_mix(live_server):
    """ISSUE 12 satellite: --slo-class drives a per-class request mix
    and the report carries per-class client percentiles plus the
    server's own goodput judgment from the new counters."""
    loop, url = live_server
    args = argparse.Namespace(
        url=url,
        model="tiny",
        num_prompts=6,
        concurrency=3,
        input_len=8,
        output_len=8,
        slo_classes=["interactive:2", "batch"],
    )
    result = loop.run_until_complete(_bench_serve_async(args))
    per_class = result["per_class"]
    assert set(per_class) == {"interactive", "batch"}
    # 2:1 mix over 6 requests = 4 interactive, 2 batch.
    assert per_class["interactive"]["completed"] == 4
    assert per_class["batch"]["completed"] == 2
    assert per_class["interactive"]["ttft_s"]["p50"] > 0
    # Server-side goodput: no targets configured in this server, so
    # every completed request attains trivially.
    for cls in ("interactive", "batch"):
        assert per_class[cls]["server_goodput_ratio"] == 1.0
        assert per_class[cls]["server_ttft_attain_ratio"] == 1.0


def test_parse_tenants_units():
    """ISSUE 16 multi-tenant load generator: profile parsing with
    defaults, class-defaults-to-name, and loud rejection of malformed
    specs."""
    chat, batch = parse_tenants(
        [
            "chat:arrival=bursty,rate=8,burst=2,input=8-16,output=4",
            "batch:class=bulk,arrival=closed,concurrency=3",
        ]
    )
    assert chat["name"] == chat["slo_class"] == "chat"
    assert chat["arrival"] == "bursty"
    assert (chat["rate"], chat["burst"]) == (8.0, 2)
    assert chat["input"] == (8, 16) and chat["output"] == (4, 4)
    assert batch["slo_class"] == "bulk"  # class= overrides the default
    assert batch["arrival"] == "closed" and batch["concurrency"] == 3
    assert batch["input"] == (32, 32)  # untouched defaults

    assert parse_len_range("8", "input") == (8, 8)
    assert parse_len_range("32-128", "input") == (32, 128)
    for bad in ("0", "8-4", "x", "-3"):
        with pytest.raises(SystemExit):
            parse_len_range(bad, "input")
    for bad_spec in (
        ["noseparator"],
        ["dup:rate=1", "dup:rate=2"],
        ["t:arrival=sinusoid"],
        ["t:rate=0"],
        ["t:burst=0"],
        ["t:concurrency=0"],
        ["t:wat=1"],
        ["t:rate"],
    ):
        with pytest.raises(SystemExit):
            parse_tenants(bad_spec)


def test_bench_serve_multi_tenant(live_server):
    """The ISSUE 16 judging instrument end to end: two named tenant
    profiles (closed-loop interactive + Poisson batch) drive the live
    server concurrently; the report carries the seed, per-tenant
    accounting, and the per-class rollup both tenants feed."""
    loop, url = live_server
    args = argparse.Namespace(
        url=url,
        model="tiny",
        num_prompts=1,  # ignored by the tenant path
        seed=7,
        tenant_seconds=1.5,
        tenants=[
            "interactive:arrival=closed,concurrency=2,input=8,output=8",
            "batch:arrival=poisson,rate=6,input=8-16,output=4",
        ],
    )
    result = loop.run_until_complete(_bench_serve_async(args))
    assert result["arrival_process"] == "multi_tenant"
    assert result["seed"] == 7
    assert result["tenant_seconds"] == 1.5
    tenants = result["tenants"]
    assert set(tenants) == {"interactive", "batch"}
    it = tenants["interactive"]
    assert it["class"] == "interactive"
    assert it["arrival"] == "closed" and it["concurrency"] == 2
    assert it["completed"] > 0
    assert it["ttft_s"]["p50"] > 0
    bt = tenants["batch"]
    assert bt["arrival"] == "poisson" and bt["rate_rps"] == 6
    assert bt["input"] == [8, 16]
    assert bt["offered"] >= bt["completed"] >= 0
    # Both tenants also land in the per-class SLO rollup.
    assert set(result["per_class"]) == {"interactive", "batch"}
    assert (
        result["per_class"]["interactive"]["completed"] == it["completed"]
    )
    # The tenant path reports offered totals, not a fixed num_prompts.
    assert result["num_prompts"] == sum(
        t["offered"] for t in tenants.values()
    )
    assert result["concurrency"] is None
    assert result["input_len"] is None


def test_bench_serve_tenant_flag_conflicts_with_rate():
    args = argparse.Namespace(
        url="http://localhost:1",
        model="tiny",
        num_prompts=1,
        request_rate=4.0,
        tenants=["t:rate=1"],
    )
    with pytest.raises(SystemExit):
        asyncio.new_event_loop().run_until_complete(
            _bench_serve_async(args)
        )


def test_bench_serve_reports_http_path_metrics(live_server):
    loop, url = live_server
    args = argparse.Namespace(
        url=url,
        model="tiny",
        num_prompts=6,
        concurrency=3,
        input_len=8,
        output_len=12,
    )
    result = loop.run_until_complete(_bench_serve_async(args))

    assert result["mode"] == "serve"
    assert result["output_tokens_per_s"] > 0
    assert result["requests_per_s"] > 0
    # Client-side latency distributions through the SSE stream.
    assert result["ttft_s"]["p50"] > 0
    assert result["itl_ms"]["p50"] >= 0
    assert result["ttft_s"]["p99"] >= result["ttft_s"]["p50"]
    # Server-side cross-check from /metrics deltas over the run.
    sm = result["server_metrics"]
    assert sm["generation_tokens"] == 6 * 12
    assert sm["ttft_mean_s"] > 0
    # The two views of TTFT must be the same order of magnitude (client
    # adds only HTTP overhead on loopback).
    assert sm["ttft_mean_s"] < result["ttft_s"]["p99"] * 3 + 1.0
