"""Round-trip property tests for the delta-compressed step codec
(engine/step_delta.py, ISSUE 7): every admit/append/chunk/preempt/
resume/finish sequence a real scheduler can produce must reconstruct
the full ``SchedulerOutput`` exactly on the worker-side mirror, and
multiple mirrors fed the same frame stream must stay in lockstep.
"""

import random

import pytest

from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.engine.scheduler import (
    CachedRequestData,
    NewRequestData,
    Scheduler,
    SchedulerOutput,
)
from vllm_distributed_tpu.engine.step_delta import (
    StepDeltaEncoder,
    StepStateMirror,
)
from vllm_distributed_tpu.sampling_params import SamplingParams


def make_scheduler(**kw):
    defaults = dict(
        max_num_seqs=8,
        max_num_batched_tokens=64,
        num_pages=64,
        page_size=4,
        max_model_len=256,
    )
    defaults.update(kw)
    num_pages = defaults.pop("num_pages")
    page_size = defaults.pop("page_size")
    return Scheduler(
        SchedulerConfig(
            max_num_seqs=defaults["max_num_seqs"],
            max_num_batched_tokens=defaults["max_num_batched_tokens"],
            enable_chunked_prefill=True,
            max_model_len=defaults["max_model_len"],
        ),
        CacheConfig(page_size=page_size),
        num_pages=num_pages,
    )


def make_req(rid, prompt_len=8, max_tokens=8):
    return Request(
        request_id=rid,
        prompt_token_ids=list(range(prompt_len)),
        sampling_params=SamplingParams(max_tokens=max_tokens),
        eos_token_id=None,
    )


def sample_tokens(sched, out):
    tokens = {}
    for req_id, n in out.num_scheduled_tokens.items():
        req = sched.requests.get(req_id)
        if req is None:
            continue
        boundary = req.num_prompt_tokens + req.num_output_tokens
        if req.num_computed_tokens + n >= boundary:
            tokens[req_id] = [7]
    return tokens


def assert_roundtrip(encoder, mirrors, so):
    frame = encoder.encode(so)
    assert frame.raw is None, "scheduler output must be delta-encodable"
    for mirror in mirrors:
        rebuilt = mirror.decode(frame)
        assert rebuilt == so
    return frame


def test_admit_decode_finish_roundtrip():
    sched = make_scheduler()
    encoder = StepDeltaEncoder()
    mirrors = [StepStateMirror(), StepStateMirror()]  # two "hosts"
    sched.add_request(make_req("a", prompt_len=8, max_tokens=3))
    while sched.has_unfinished_requests():
        out = sched.schedule()
        if out.is_empty:
            break
        frame = assert_roundtrip(encoder, mirrors, out)
        # Steady-state decode frames carry no request-id strings and no
        # prompt tokens — that's the compression.
        if not out.new_requests:
            assert frame.new == []
            assert all(isinstance(i, int) for i, _, _ in frame.cached)
        sched.update_from_output(out, sample_tokens(sched, out))
    # The terminal finish notice rides the next dispatched step.
    sched.add_request(make_req("b", prompt_len=4, max_tokens=1))
    out = sched.schedule()
    assert "a" in out.finished_req_ids
    assert_roundtrip(encoder, mirrors, out)
    assert encoder.num_mirrored == mirrors[0].num_mirrored == 1


def test_randomized_workload_lockstep():
    """Seeded random admits/aborts over a small page pool (forces
    chunked prefill AND preemption/resume); every non-empty step must
    round-trip bit-exactly on both mirrors."""
    rng = random.Random(1234)
    sched = make_scheduler(
        num_pages=24, page_size=4, max_num_batched_tokens=32
    )
    encoder = StepDeltaEncoder()
    mirrors = [StepStateMirror(), StepStateMirror()]
    next_id = 0
    preempt_seen = resume_seen = 0
    for step in range(300):
        if next_id < 12 and rng.random() < 0.4:
            sched.add_request(
                make_req(
                    f"r{next_id}",
                    prompt_len=rng.randint(1, 40),
                    max_tokens=rng.randint(1, 24),
                )
            )
            next_id += 1
        if sched.requests and rng.random() < 0.05:
            sched.abort_request(rng.choice(sorted(sched.requests)))
        out = sched.schedule()
        if out.is_empty:
            if not sched.has_unfinished_requests() and next_id >= 12:
                break
            continue
        frame = assert_roundtrip(encoder, mirrors, out)
        preempt_seen += len(frame.preempted)
        resume_seen += sum(
            1 for n in out.new_requests if n.num_prompt_tokens
            < len(n.prompt_token_ids)
        )
        sched.update_from_output(out, sample_tokens(sched, out))
    assert preempt_seen > 0, "workload never preempted — weak test"
    assert encoder.num_mirrored == mirrors[0].num_mirrored
    assert mirrors[0].num_mirrored == mirrors[1].num_mirrored


def test_preempt_resume_reuses_id():
    """A preempted request leaves the mirror and is re-admitted as a
    NEW request (full re-prefill) — the id must be assignable again."""
    encoder = StepDeltaEncoder()
    mirror = StepStateMirror()

    def new_req(rid, computed=0, new=4):
        return NewRequestData(
            req_id=rid,
            prompt_token_ids=[1, 2, 3, 4],
            num_prompt_tokens=4,
            page_ids=[0],
            num_computed_tokens=computed,
            num_new_tokens=new,
            sampling_params=SamplingParams(max_tokens=8),
        )

    so0 = SchedulerOutput(
        step_id=0,
        new_requests=[new_req("a")],
        num_scheduled_tokens={"a": 4},
        total_num_scheduled_tokens=4,
    )
    assert mirror.decode(encoder.encode(so0)) == so0
    so1 = SchedulerOutput(step_id=1, preempted_req_ids=["a"])
    # Preemption notice plus re-admission in the same frame stream.
    so1.new_requests = [new_req("a")]
    so1.num_scheduled_tokens = {"a": 4}
    so1.total_num_scheduled_tokens = 4
    assert mirror.decode(encoder.encode(so1)) == so1
    assert encoder.num_mirrored == mirror.num_mirrored == 1


def test_computed_override_on_prediction_miss():
    """If the scheduler's num_computed_tokens disagrees with the
    encoder's prediction, the frame ships an explicit override and both
    sides resync instead of silently diverging."""
    encoder = StepDeltaEncoder()
    mirror = StepStateMirror()
    so0 = SchedulerOutput(
        step_id=0,
        new_requests=[
            NewRequestData(
                req_id="a",
                prompt_token_ids=[1, 2, 3, 4],
                num_prompt_tokens=4,
                page_ids=[0],
                num_computed_tokens=0,
                num_new_tokens=4,
                sampling_params=SamplingParams(max_tokens=8),
            )
        ],
        num_scheduled_tokens={"a": 4},
        total_num_scheduled_tokens=4,
    )
    mirror.decode(encoder.encode(so0))
    # Prediction says computed=4; hand the encoder computed=3 instead
    # (e.g. a rolled-back speculative token).
    so1 = SchedulerOutput(
        step_id=1,
        cached_requests=[
            CachedRequestData(
                req_id="a",
                new_page_ids=[1],
                num_computed_tokens=3,
                num_new_tokens=1,
            )
        ],
        num_scheduled_tokens={"a": 1},
        total_num_scheduled_tokens=1,
    )
    frame = encoder.encode(so1)
    assert frame.computed_overrides  # miss was detected and shipped
    assert mirror.decode(frame) == so1
    # Next step: prediction is back in lockstep, no override needed.
    so2 = SchedulerOutput(
        step_id=2,
        cached_requests=[
            CachedRequestData(
                req_id="a",
                new_page_ids=[],
                num_computed_tokens=4,
                num_new_tokens=1,
            )
        ],
        num_scheduled_tokens={"a": 1},
        total_num_scheduled_tokens=1,
    )
    frame2 = encoder.encode(so2)
    assert not frame2.computed_overrides
    assert mirror.decode(frame2) == so2


def test_raw_fallback_for_unencodable_payload():
    """Hand-built payloads whose num_scheduled_tokens has no matching
    new/cached record (test harness payloads) ship verbatim and bypass
    the mirror."""
    encoder = StepDeltaEncoder()
    mirror = StepStateMirror()
    so = SchedulerOutput(
        step_id=0,
        num_scheduled_tokens={"ghost": 4},
        total_num_scheduled_tokens=4,
    )
    frame = encoder.encode(so)
    assert frame.raw is so
    assert mirror.decode(frame) is so
    assert mirror.num_mirrored == 0  # raw frames leave the mirror alone


def test_desync_is_loud():
    encoder = StepDeltaEncoder()
    with pytest.raises(ValueError, match="unknown request"):
        encoder.encode(SchedulerOutput(step_id=0, finished_req_ids=["x"]))
    with pytest.raises(ValueError, match="unmirrored"):
        encoder.encode(
            SchedulerOutput(
                step_id=0,
                cached_requests=[
                    CachedRequestData(
                        req_id="x",
                        new_page_ids=[],
                        num_computed_tokens=4,
                        num_new_tokens=1,
                    )
                ],
                num_scheduled_tokens={"x": 1},
                total_num_scheduled_tokens=1,
            )
        )


def test_decode_frame_smaller_than_full_output():
    """The wire economy the codec exists for: a batch-64 decode frame
    must be much smaller than the full SchedulerOutput it replaces."""
    import pickle

    encoder = StepDeltaEncoder()
    admit = SchedulerOutput(step_id=0)
    for i in range(64):
        rid = f"request-{i:04d}"
        admit.new_requests.append(
            NewRequestData(
                req_id=rid,
                prompt_token_ids=list(range(512)),
                num_prompt_tokens=512,
                page_ids=list(range(i * 128, i * 128 + 128)),
                num_computed_tokens=0,
                num_new_tokens=512,
                sampling_params=SamplingParams(max_tokens=64),
            )
        )
        admit.num_scheduled_tokens[rid] = 512
        admit.total_num_scheduled_tokens += 512
    encoder.encode(admit)
    decode = SchedulerOutput(step_id=1)
    for i in range(64):
        rid = f"request-{i:04d}"
        decode.cached_requests.append(
            CachedRequestData(
                req_id=rid,
                new_page_ids=[],
                num_computed_tokens=512 + i,
                num_new_tokens=1,
            )
        )
        decode.num_scheduled_tokens[rid] = 1
        decode.total_num_scheduled_tokens += 1
    # The encoder predicts computed=512, the "scheduler" says 512+i —
    # build the predictable variant instead so no overrides ship.
    for c in decode.cached_requests:
        c.num_computed_tokens = 512
    frame = encoder.encode(decode)
    assert not frame.computed_overrides
    assert len(pickle.dumps(frame)) < len(pickle.dumps(decode)) / 4
