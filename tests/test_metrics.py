"""Prometheus metrics instrumentation (SURVEY.md §5.5; VERDICT r2 #5:
the registry must carry real instruments — TTFT/ITL/throughput — wired
from the engine loop, and /metrics must be non-empty under load)."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.utils import add_tiny_tokenizer, make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.metrics import EngineMetrics
from vllm_distributed_tpu.outputs import RequestMetrics
from vllm_distributed_tpu.sampling_params import SamplingParams


def test_engine_metrics_records():
    m = EngineMetrics("m", enabled=True)
    # Intervals come from the monotonic stamps; the wall-clock fields
    # exist only for span-start timestamps.
    rm = RequestMetrics(arrival_time=100.0, arrival_time_mono=100.0)
    rm.first_token_time_mono = 100.5
    m.record_prompt_tokens(7)
    m.record_new_tokens(rm, 1, now=100.5)  # first token -> TTFT
    m.record_new_tokens(rm, 4, now=100.9)  # fused batch -> 4 ITL obs
    m.record_queues(3, 2)
    m.record_preemptions(1)
    rm.finished_time_mono = 101.0
    m.record_finished(rm, "stop")
    text = m.render().decode()
    assert 'vllm:time_to_first_token_seconds_count{model_name="m"} 1.0' in text
    assert 'vllm:time_per_output_token_seconds_count{model_name="m"} 4.0' in text
    assert 'vllm:generation_tokens_total{model_name="m"} 5.0' in text
    assert 'vllm:prompt_tokens_total{model_name="m"} 7.0' in text
    assert 'vllm:num_requests_running{model_name="m"} 3.0' in text
    assert 'vllm:num_preemptions_total{model_name="m"} 1.0' in text
    assert (
        'vllm:request_success_total{finished_reason="stop",model_name="m"} 1.0'
        in text
    )
    # TTFT observed value lands in the right bucket neighborhood.
    assert 'vllm:time_to_first_token_seconds_sum{model_name="m"} 0.5' in text


def test_intervals_use_monotonic_clock():
    """ISSUE 5 satellite: an NTP wall-clock step (even a big backwards
    one) must not produce negative/garbage TTFT, ITL, or e2e — interval
    math reads only the monotonic stamps."""
    m = EngineMetrics("m", enabled=True)
    rm = RequestMetrics(
        arrival_time=2_000_000_000.0,  # wall clock, about to step back
        arrival_time_mono=50.0,
    )
    # Wall clock stepped back 1000s before the first token; monotonic
    # keeps counting.
    rm.first_token_time = 1_999_999_000.0
    rm.first_token_time_mono = 50.25
    m.record_new_tokens(rm, 1, now=50.25)
    m.record_new_tokens(rm, 2, now=50.45)
    rm.finished_time = 1_999_999_001.0
    rm.finished_time_mono = 50.5
    m.record_finished(rm, "stop")
    text = m.render().decode()
    assert 'vllm:time_to_first_token_seconds_sum{model_name="m"} 0.25' in text
    # 2 ITL observations of 0.1s each.
    assert 'vllm:time_per_output_token_seconds_count{model_name="m"} 2.0' in text
    assert (
        'vllm:time_per_output_token_seconds_sum{model_name="m"} 0.2' in text
    )
    assert 'vllm:e2e_request_latency_seconds_sum{model_name="m"} 0.5' in text


def test_metric_registry_matches_documented_names(tmp_path):
    """ISSUE 5 satellite: registry-drift guard.  After an engine run,
    render() must expose every documented vllm:* family exactly once —
    and nothing undocumented."""
    import re

    from vllm_distributed_tpu.metrics import DOCUMENTED_METRICS

    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "mdrift")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    engine.add_request(
        "r0",
        prompt_token_ids=[1, 5, 9],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=4, ignore_eos=True
        ),
    )
    while engine.has_unfinished_requests():
        engine.step()
    engine.shutdown()
    text = engine.metrics.render().decode()
    families = re.findall(r"^# TYPE (vllm:\S+) ", text, flags=re.M)
    # prometheus_client emits a companion `<name>_created` gauge per
    # counter/histogram once samples exist; those track the documented
    # family implicitly and are not part of the contract.
    vllm_families = [
        f
        for f in families
        if f.startswith("vllm:") and not f.endswith("_created")
    ]
    assert sorted(vllm_families) == sorted(set(vllm_families)), (
        "duplicate metric families rendered"
    )
    assert set(vllm_families) == set(DOCUMENTED_METRICS), (
        "metric registry drifted from DOCUMENTED_METRICS: "
        f"undocumented={set(vllm_families) - set(DOCUMENTED_METRICS)}, "
        f"missing={set(DOCUMENTED_METRICS) - set(vllm_families)}"
    )


def test_metrics_disabled_noop():
    m = EngineMetrics("m", enabled=False)
    rm = RequestMetrics(arrival_time=0.0)
    m.record_new_tokens(rm, 3)
    m.record_queues(1, 1)
    assert b"disabled" in m.render()


def test_engine_loop_populates_metrics(tmp_path):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    engine.add_request(
        "r0",
        prompt_token_ids=[1, 5, 9, 23],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=12, ignore_eos=True
        ),
    )
    while engine.has_unfinished_requests():
        engine.step()
    text = engine.metrics.render().decode()
    assert "vllm:generation_tokens_total" in text and " 12.0" in text
    assert "vllm:time_to_first_token_seconds_count" in text
    assert "vllm:e2e_request_latency_seconds_count" in text
    assert 'finished_reason="length"' in text


def test_disable_log_stats_honored(tmp_path):
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m2")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
            disable_log_stats=True,
        )
    )
    assert not engine.metrics.enabled
    assert b"disabled" in engine.metrics.render()


@pytest.fixture(scope="module")
def served_app(tmp_path_factory):
    model_dir = make_tiny_llama(str(tmp_path_factory.mktemp("msrv")))
    add_tiny_tokenizer(model_dir)
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            num_kv_pages=128,
            max_model_len=256,
            max_num_seqs=8,
        )
    )
    state = init_app_state(engine, served_model_name="tiny")
    yield lambda: build_app(state)
    engine.shutdown()


def test_metrics_endpoint_under_load(served_app):
    async def go(client):
        r = await client.post(
            "/v1/completions",
            json={
                "model": "tiny",
                "prompt": "hello world",
                "max_tokens": 8,
                "temperature": 0,
            },
        )
        assert r.status == 200
        r = await client.get("/metrics")
        text = await r.text()
        assert "vllm:generation_tokens_total" in text
        assert "vllm:time_to_first_token_seconds_bucket" in text
        assert "vllm:num_requests_running" in text

    async def run():
        server = TestServer(served_app())
        client = TestClient(server)
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(run())
