"""Ring attention (context parallelism) on the 8-device virtual mesh:
sequence-sharded causal attention must match single-device full
attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from vllm_distributed_tpu.ops.ring_attention import ring_attention
from vllm_distributed_tpu.testing import full_attention_reference as _reference


def _mesh(sp):
    return Mesh(np.array(jax.devices()[:sp]), axis_names=("sp",))


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_matches_full_attention(sp, hq, hkv):
    rng = np.random.default_rng(sp * 10 + hq)
    t, d = 64, 32
    q = jnp.asarray(rng.standard_normal((t, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    scale = d**-0.5
    want = np.asarray(_reference(q, k, v, scale))
    got = np.asarray(
        ring_attention(q, k, v, _mesh(sp), scale=scale)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_jit_and_sharded_inputs():
    """Under jit with sequence-sharded inputs (the real usage): the
    output stays sequence-sharded and correct."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(4)
    rng = np.random.default_rng(0)
    t, hq, hkv, d = 128, 8, 4, 64
    q = jnp.asarray(rng.standard_normal((t, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, hkv, d)), jnp.float32)
    scale = d**-0.5
    spec = NamedSharding(mesh, P("sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))

    fn = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, scale=scale)
    )
    got = fn(qs, ks, vs)
    assert got.sharding.spec == P("sp", None, None)
    want = np.asarray(_reference(q, k, v, scale))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_non_causal():
    mesh = _mesh(4)
    rng = np.random.default_rng(7)
    t, h, d = 32, 2, 16
    q = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((t, h, d)), jnp.float32)
    scale = d**-0.5
    want = np.asarray(_reference(q, k, v, scale, causal=False))
    got = np.asarray(
        ring_attention(q, k, v, mesh, scale=scale, causal=False)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
