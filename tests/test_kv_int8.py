"""Quantized (int8) KV cache: per-head quantization, kernel dequant,
flush-path quantization, and engine e2e under --kv-cache-dtype int8.

Parity: the reference drives vLLM's --kv-cache-dtype engine-arg surface
(/root/reference/src/launch.py:29 via AsyncEngineArgs.from_cli_args);
the TPU pool stores int8 rows + per-(token, kv-head) f32 scales so the
scale plane TP-shards over the same lane axis as the data plane
(ops/attention.py kv_scales_shape).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_pallas_attention import build_case
from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    kv_pool_shape,
    kv_scales_shape,
    paged_attention_reference,
    quantize_kv_heads,
    split_kv_pages,
    write_kv_pages,
)
from vllm_distributed_tpu.ops.pallas.kv_flush import kv_flush_cpu
from vllm_distributed_tpu.ops.pallas.paged_attention import paged_attention


def _quantize_pool(kv_pages, hkv):
    """Quantize a dense pool into the (int8 data, per-head scales) form
    via the production write path (token-row granularity)."""
    _, p, page, hd = kv_pages.shape
    data = jnp.zeros((2, p, page, hd), jnp.int8)
    scales = jnp.zeros(kv_scales_shape(p, page, hkv), jnp.float32)
    d = hd // hkv
    slots = jnp.arange(p * page, dtype=jnp.int32)
    k = kv_pages[0].reshape(p * page, hkv, d)
    v = kv_pages[1].reshape(p * page, hkv, d)
    return write_kv_pages((data, scales), k, v, slots)


def test_quantize_roundtrip_error():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 4 * 32)) * 3, jnp.float32)
    q, s = quantize_kv_heads(x, 4)
    deq = np.asarray(q, np.float32).reshape(64, 4, 32) * np.asarray(s)[
        ..., None
    ]
    err = np.abs(deq.reshape(64, -1) - np.asarray(x))
    # Symmetric int8: error bounded by scale/2 = absmax/254 per head.
    bound = np.asarray(s).max() * 0.51
    assert err.max() <= bound


def test_split_kv_pages_dequantizes():
    rng = np.random.default_rng(1)
    hkv, d, p, page = 2, 32, 4, 8
    kv = jnp.asarray(
        rng.standard_normal(kv_pool_shape(p, page, hkv, d)), jnp.float32
    )
    qpool = _quantize_pool(kv, hkv)
    k_deq, v_deq = split_kv_pages(qpool, hkv, d)
    k_ref, v_ref = split_kv_pages(kv, hkv, d)
    np.testing.assert_allclose(
        np.asarray(k_deq), np.asarray(k_ref), atol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(v_deq), np.asarray(v_ref), atol=0.05
    )


@pytest.mark.parametrize(
    "specs,hq,hkv",
    [
        ([(17, 1), (33, 1), (160, 1)], 4, 2),  # pure decode, GQA
        ([(24, 24), (7, 7)], 4, 2),  # prefill
        ([(50, 1), (20, 20), (33, 1)], 8, 2),  # mixed
        ([(21, 1), (9, 9)], 4, 4),  # MHA
    ],
)
def test_pallas_matches_reference_on_quantized_pool(specs, hq, hkv):
    """Kernel and reference read the SAME int8 pool, so they dequantize
    identical values — agreement is float-rounding tight, proving the
    in-kernel scale application (scores/probs side) is exact."""
    rng = np.random.default_rng(2)
    q, kv, meta, max_q, t_real, hkv = build_case(
        rng, seq_specs=specs, hq=hq, hkv=hkv
    )
    qpool = _quantize_pool(kv, hkv)
    ref = paged_attention_reference(
        q, qpool, meta, scale=0.125, num_kv_heads=hkv
    )
    got = paged_attention(
        q, qpool, meta, scale=0.125, num_kv_heads=hkv,
        max_q=max_q, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[:t_real]), np.asarray(ref[:t_real]),
        rtol=1e-4, atol=2e-5,
    )


def test_quantized_vs_f32_tolerance():
    """End-to-end numerics: attention over the quantized pool stays
    close to attention over the original f32 pool."""
    rng = np.random.default_rng(3)
    q, kv, meta, max_q, t_real, hkv = build_case(
        rng, seq_specs=[(40, 8), (64, 16), (100, 1)]
    )
    want = paged_attention_reference(
        q, kv, meta, scale=0.125, num_kv_heads=hkv
    )
    got = paged_attention(
        q, _quantize_pool(kv, hkv), meta, scale=0.125, num_kv_heads=hkv,
        max_q=max_q, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[:t_real]), np.asarray(want[:t_real]), atol=0.05
    )


def test_staged_side_buffer_on_quantized_pool():
    """Decode scan shape: int8 pool history + unquantized (model-dtype)
    side rows must match the reference with the same operands."""
    rng = np.random.default_rng(4)
    hq, hkv, d, page_size = 4, 2, 64, 16
    s_pad, k_steps, step_i = 4, 8, 5
    bases = [37, 21, 0, 5]
    num_pages = 32
    kv = jnp.asarray(
        rng.standard_normal(kv_pool_shape(num_pages, page_size, hkv, d)),
        jnp.float32,
    )
    qpool = _quantize_pool(kv, hkv)
    side = jnp.asarray(
        rng.standard_normal((s_pad, 2, k_steps, hkv * d)), jnp.float32
    )
    bt = np.zeros((s_pad, 8), np.int32)
    nxt = 1
    for i, b in enumerate(bases):
        if b <= 0:
            continue
        need = -(-(b + k_steps) // page_size)
        bt[i, :need] = np.arange(nxt, nxt + need)
        nxt += need
    pos = np.asarray([b + step_i if b > 0 else 0 for b in bases], np.int32)
    sid = np.asarray(
        [i if b > 0 else s_pad for i, b in enumerate(bases)], np.int32
    )
    q = jnp.asarray(rng.standard_normal((s_pad, hq, d)), jnp.float32)
    meta = AttentionMetadata(
        q_seq_ids=jnp.asarray(sid),
        q_positions=jnp.asarray(pos),
        slot_mapping=jnp.zeros(s_pad, jnp.int32),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.asarray(np.asarray(bases, np.int32)),
        logits_indices=jnp.arange(s_pad, dtype=jnp.int32),
        chunk_starts=jnp.asarray(pos),
    )
    side_len = jnp.asarray([step_i + 1], jnp.int32)
    want = paged_attention_reference(
        q, qpool, meta, scale=0.125, num_kv_heads=hkv,
        side_kv=side, side_len=side_len,
    )
    got = paged_attention(
        q, qpool, meta, scale=0.125, num_kv_heads=hkv,
        max_q=1, side_kv=side, side_len=side_len, interpret=True,
    )
    live = np.asarray([i for i, b in enumerate(bases) if b > 0])
    np.testing.assert_allclose(
        np.asarray(got)[live], np.asarray(want)[live],
        rtol=1e-4, atol=2e-5,
    )


def test_kv_flush_quantized_matches_functional_write():
    """The double-kernel flush (data planes + scale planes) must equal
    the functional quantized scatter over the same rows — EXACTLY,
    since both quantize per head with the same reduction."""
    rng = np.random.default_rng(5)
    hkv, d, page_size, num_pages = 2, 32, 16, 32
    s_pad, k_steps = 4, 8
    hd = hkv * d
    kv = jnp.asarray(
        rng.standard_normal(kv_pool_shape(num_pages, page_size, hkv, d)),
        jnp.float32,
    )
    qpool = _quantize_pool(kv, hkv)
    side = jnp.asarray(
        rng.standard_normal((s_pad, 2, k_steps, hd)), jnp.float32
    )
    bases = np.asarray([17, 40, 0, 3], np.int32)
    n_side = np.asarray([k_steps, 5, 0, k_steps], np.int32)
    bt = np.zeros((s_pad, 8), np.int32)
    nxt = 1
    for i, b in enumerate(bases):
        if b <= 0:
            continue
        need = -(-(int(b) + k_steps) // page_size)
        bt[i, :need] = np.arange(nxt, nxt + need)
        nxt += need

    got_data, got_scales = kv_flush_cpu(
        qpool,
        side,
        jnp.asarray(bt),
        jnp.asarray(bases),
        jnp.asarray(n_side),
    )

    want_data, want_scales = qpool
    for i, b in enumerate(bases):
        if b <= 0 or n_side[i] <= 0:
            continue
        for j in range(int(n_side[i])):
            p = int(b) + j
            slot = bt[i, p // page_size] * page_size + p % page_size
            want_data, want_scales = write_kv_pages(
                (want_data, want_scales),
                side[i, 0, j].reshape(1, hkv, d),
                side[i, 1, j].reshape(1, hkv, d),
                jnp.asarray([slot], jnp.int32),
            )
    # Page 0 is the dump page (dead rows scatter garbage there by
    # contract) — exclude it from the comparison.
    np.testing.assert_array_equal(
        np.asarray(got_data)[:, 1:], np.asarray(want_data)[:, 1:]
    )
    np.testing.assert_allclose(
        np.asarray(got_scales)[:, 1:],
        np.asarray(want_scales)[:, 1:],
        rtol=1e-6,
    )


def test_engine_e2e_int8_kv(tmp_path):
    """Whole engine with --kv-cache-dtype int8: the interpret-mode
    Pallas path and the XLA reference path must agree token-for-token
    (same quantized pool contents), and the run must complete."""
    from tests.utils import make_tiny_llama
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.ops.attention import (
        paged_attention_reference as ref_fn,
    )
    from vllm_distributed_tpu.sampling_params import SamplingParams

    model_dir = make_tiny_llama(str(tmp_path / "m"))

    def run(backend):
        config = EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
            max_num_seqs=8,
            max_num_batched_tokens=64,
            kv_cache_dtype="int8",
            num_decode_steps=4,
        ).create_engine_config()
        engine = LLMEngine(config)
        runner = engine.executor.worker.runner
        if backend == "pallas":
            from vllm_distributed_tpu.ops.pallas.kv_flush import (
                kv_flush_cpu,
            )
            from vllm_distributed_tpu.ops.pallas.paged_attention import (
                paged_attention_cpu,
            )

            runner._attn_fn = paged_attention_cpu
            runner._kv_flush_fn = kv_flush_cpu
            runner._staged_decode = True
        else:
            runner._attn_fn = ref_fn
            runner._kv_flush_fn = None
            runner._staged_decode = False
        prompts = [list(range(1, 30)), [5, 6, 7], list(range(40, 60))]
        for i, p in enumerate(prompts):
            engine.add_request(
                f"r{i}",
                prompt_token_ids=p,
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=6, ignore_eos=True
                ),
            )
        done = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
        return [done[f"r{i}"] for i in range(len(prompts))]

    ref_tokens = run("reference")
    assert all(len(t) == 6 for t in ref_tokens)
    # Pallas staged path quantizes at flush; reference quantizes in-step.
    # Both write identical per-head-quantized rows, so greedy tokens on
    # a tiny model should agree.
    assert run("pallas") == ref_tokens
