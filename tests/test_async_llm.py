"""AsyncLLM event-loop isolation (VERDICT r2 weak #3 / ADVICE r1 #1):
a slow engine step (multi-second prefill on a big model) must not freeze
the server's event loop — intake goes through a thread-safe queue, and
no lock is shared between the event loop and the engine thread."""

import asyncio
import time

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture()
def engine(tmp_path):
    eng = AsyncLLM.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    yield eng
    eng.shutdown()


async def _consume(agen):
    out = None
    async for item in agen:
        out = item
    return out


def test_event_loop_responsive_during_slow_step(engine):
    """Submissions + health stay <100ms while a 400ms step is mid-flight
    (the old shared lock serialized them behind the step)."""
    real_step = engine.engine.step

    def slow_step():
        time.sleep(0.4)
        return real_step()

    engine.engine.step = slow_step

    async def go():
        sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
        t1 = asyncio.create_task(
            _consume(engine.generate("a", prompt_token_ids=[1, 2, 3],
                                     sampling_params=sp))
        )
        await asyncio.sleep(0.1)  # engine thread is now inside slow_step
        # Event-loop responsiveness probes while the step blocks.
        worst = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            await engine.check_health()
            await asyncio.sleep(0.01)
            worst = max(worst, time.perf_counter() - t0)
        # Submitting a second request must not block either.
        t0 = time.perf_counter()
        t2 = asyncio.create_task(
            _consume(engine.generate("b", prompt_token_ids=[4, 5],
                                     sampling_params=sp))
        )
        await asyncio.sleep(0)
        submit_latency = time.perf_counter() - t0
        r1, r2 = await asyncio.gather(t1, t2)
        return worst, submit_latency, r1, r2

    worst, submit_latency, r1, r2 = asyncio.new_event_loop().run_until_complete(go())
    assert worst < 0.1, f"event loop stalled {worst:.3f}s behind the step"
    assert submit_latency < 0.1
    assert r1.finished and len(r1.outputs[0].token_ids) == 4
    assert r2.finished and len(r2.outputs[0].token_ids) == 4


def test_intake_error_surfaces_with_type(engine):
    """A too-long prompt raises ValueError out of generate() (the API
    layer maps ValueError -> 400), not a generic engine error."""

    async def go():
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        with pytest.raises(ValueError):
            await _consume(
                engine.generate(
                    "big", prompt_token_ids=list(range(500)),
                    sampling_params=sp,
                )
            )

    asyncio.new_event_loop().run_until_complete(go())


def test_cancel_aborts_request(engine):
    async def go():
        sp = SamplingParams(temperature=0.0, max_tokens=64, ignore_eos=True)
        task = asyncio.create_task(
            _consume(engine.generate("c", prompt_token_ids=[1, 2],
                                     sampling_params=sp))
        )
        await asyncio.sleep(0.3)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        # The abort drains through intake; the engine ends up idle.
        for _ in range(50):
            if not engine.engine.has_unfinished_requests():
                break
            await asyncio.sleep(0.05)
        assert not engine.engine.has_unfinished_requests()

    asyncio.new_event_loop().run_until_complete(go())
