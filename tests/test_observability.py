"""Flight recorder + XLA/device telemetry + profiling surface
(ISSUE 12): the always-on per-step ring and its dumps, the
/debug/flightrecorder and gated /debug/profile endpoints, and the
induced shape-bucket recompile observed through
vllm:xla_compiles_total on the mock runner."""

from __future__ import annotations

import asyncio
import json
import os

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockUniProcExecutor
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.flight_recorder import (
    FIELDS,
    FlightRecorder,
)
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
)
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _engine_args(model_dir: str, **kw) -> EngineArgs:
    args = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=64,
        max_model_len=128,
        num_decode_steps=1,
        distributed_executor_backend=MockUniProcExecutor,
    )
    args.update(kw)
    return EngineArgs(**args)


@pytest.fixture
def model_dir(tmp_path):
    return write_llama_config(str(tmp_path / "m"))


# ---------------------------------------------------------------------
# flight recorder unit behavior
# ---------------------------------------------------------------------
def test_ring_is_bounded_and_dump_prunes(tmp_path):
    fr = FlightRecorder(size=8, dump_dir=str(tmp_path))
    for i in range(50):
        fr.record_step(*([i] * len(FIELDS)))
    snap = fr.snapshot()
    assert len(snap["steps"]) == 8
    assert snap["steps"][-1][0] == 49
    assert snap["fields"] == list(FIELDS)
    paths = [fr.dump(f"r{i}") for i in range(20)]
    assert all(p is not None for p in paths)
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight")]
    assert len(dumps) <= 16  # pruned to the newest artifacts
    with open(paths[-1]) as f:
        payload = json.load(f)
    assert payload["reason"] == "r19"
    assert len(payload["steps"]) == 8


def test_disabled_recorder_is_noop(tmp_path):
    fr = FlightRecorder(size=0, dump_dir=str(tmp_path))
    fr.record_step(*([0] * len(FIELDS)))
    assert fr.dump("x") is None
    assert not os.listdir(tmp_path)


def test_engine_records_steps_and_dump_has_composition(
    model_dir, tmp_path, monkeypatch
):
    monkeypatch.setenv("VDT_FLIGHT_RECORDER_DIR", str(tmp_path / "fr"))
    engine = LLMEngine.from_engine_args(_engine_args(model_dir))
    try:
        engine.add_request(
            "r0",
            prompt_token_ids=[1, 2, 3],
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=6, ignore_eos=True
            ),
        )
        while engine.has_unfinished_requests():
            engine.step()
        snap = engine.flight_recorder.snapshot()
        assert snap["steps"], "no step records"
        by_field = [
            dict(zip(FIELDS, step)) for step in snap["steps"]
        ]
        assert any(s["num_new"] == 1 for s in by_field)  # admission
        assert any(s["scheduled_tokens"] > 0 for s in by_field)
        assert all(s["kv_free_pages"] > 0 for s in by_field)
        path = engine.flight_recorder.dump("test")
        assert path is not None and os.path.exists(path)
        # Bounded size: ring-limited records keep the artifact small.
        assert os.path.getsize(path) < 1 << 20
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------
# induced shape-bucket recompile observed via vllm:xla_compiles_total
# ---------------------------------------------------------------------
def test_mock_recompile_observed_in_metrics(model_dir):
    engine = LLMEngine.from_engine_args(_engine_args(model_dir))
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=2, ignore_eos=True
        )
        engine.add_request(
            "small", prompt_token_ids=[1, 2, 3], sampling_params=sp
        )
        while engine.has_unfinished_requests():
            engine.step()
        engine.refresh_device_telemetry()
        text = engine.metrics.render().decode()
        assert 'vllm:xla_compiles_total{kind="prefill"' in text

        def compiles(t: str) -> float:
            for line in t.splitlines():
                if line.startswith('vllm:xla_compiles_total{kind="prefill"'):
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        before = compiles(text)
        assert before >= 1.0
        # A much longer prompt lands in a new power-of-2 token bucket:
        # the mock runner records a fresh compile, the pull observes it.
        engine.add_request(
            "big",
            prompt_token_ids=list(range(1, 50)),
            sampling_params=sp.clone(),
        )
        while engine.has_unfinished_requests():
            engine.step()
        snap = engine.refresh_device_telemetry()
        assert snap is not None and snap["compiles"]["prefill"] >= 2
        after = compiles(engine.metrics.render().decode())
        assert after >= before + 1.0, (before, after)
        # Re-running the SAME bucket must not count again.
        engine.add_request(
            "again",
            prompt_token_ids=list(range(1, 50)),
            sampling_params=sp.clone(),
        )
        while engine.has_unfinished_requests():
            engine.step()
        engine.refresh_device_telemetry()
        assert compiles(engine.metrics.render().decode()) == after
        # Gauges landed too.
        text = engine.metrics.render().decode()
        assert "vllm:hbm_live_bytes" in text
        assert "vllm:step_roofline_frac" in text
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------
# HTTP surfaces: /debug/flightrecorder, /metrics pull, /debug/profile
# ---------------------------------------------------------------------
def test_http_observability_surfaces(model_dir, tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_FLIGHT_RECORDER_DIR", str(tmp_path / "fr"))
    engine = AsyncLLM.from_engine_args(_engine_args(model_dir))
    state = init_app_state(engine, served_model_name="obs")

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.post(
                "/v1/completions",
                json={
                    "prompt": [1, 2, 3],
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "ignore_eos": True,
                    "slo_class": "chat",
                },
            )
            assert r.status == 200
            # /metrics pulls device telemetry (compile counter present).
            text = await (await client.get("/metrics")).text()
            assert 'vllm:xla_compiles_total{kind="prefill"' in text
            # /slo serves the per-class view.
            slo = await (await client.get("/slo")).json()
            assert slo["classes"]["chat"]["requests"] == 1
            assert slo["timelines"]
            lean = await (
                await client.get("/slo?timelines=0")
            ).json()
            assert "timelines" not in lean
            # /debug/flightrecorder serves the ring; ?dump=1 writes.
            fr = await (await client.get("/debug/flightrecorder")).json()
            assert fr["steps"]
            fr = await (
                await client.get("/debug/flightrecorder?dump=1")
            ).json()
            assert fr["path"] and os.path.exists(fr["path"])
            # /debug/profile is gated: 404 while unconfigured.
            r = await client.post("/debug/profile?seconds=0.05")
            assert r.status == 404
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()


def test_debug_profile_enabled_returns_artifact(
    model_dir, tmp_path, monkeypatch
):
    profile_dir = str(tmp_path / "prof")
    engine = AsyncLLM.from_engine_args(
        _engine_args(model_dir, profile_dir=profile_dir)
    )
    state = init_app_state(engine, served_model_name="prof")

    async def go():
        server = TestServer(build_app(state))
        client = TestClient(server)
        await client.start_server()
        try:
            r = await client.post("/debug/profile?seconds=not-a-number")
            assert r.status == 400
            r = await client.post("/debug/profile?seconds=0")
            assert r.status == 400
            r = await client.post("/debug/profile?seconds=0.05")
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["path"].startswith(profile_dir)
            assert os.path.isdir(body["path"])
        finally:
            await client.close()

    try:
        _run(go())
    finally:
        engine.shutdown()
