"""Persistent AOT program cache (worker/aot_cache.py): warm-restart
parity — a fresh engine process-equivalent (new runner, same cache dir)
must load serialized jax.export artifacts instead of retracing, and
produce bit-identical greedy tokens.  SURVEY.md §5.4 (compile cache /
warm restarts)."""

import os
from unittest import mock

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

PROMPTS = [[1, 5, 9, 23, 77, 41, 3], [7, 2, 88, 14]]


def _greedy(model_dir, cache_dir):
    env = {"VDT_AOT_CACHE": "1", "VDT_COMPILE_CACHE_DIR": cache_dir}
    with mock.patch.dict(os.environ, env):
        engine = LLMEngine.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                num_kv_pages=64,
                max_model_len=128,
                max_num_seqs=8,
                num_decode_steps=4,
                warmup_decode=True,
            )
        )
        runner = engine.executor.worker.runner
        assert runner._aot.enabled
        for i, p in enumerate(PROMPTS):
            engine.add_request(
                f"r{i}",
                prompt_token_ids=p,
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=6, ignore_eos=True
                ),
            )
        done = {}
        while engine.has_unfinished_requests():
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
        return [done[f"r{i}"] for i in range(len(PROMPTS))]


def test_aot_artifacts_roundtrip(tmp_path):
    model_dir = make_tiny_llama(str(tmp_path / "m"))
    cache = str(tmp_path / "cache")
    first = _greedy(model_dir, cache)
    aot_dir = os.path.join(cache, "aot")
    arts = [f for f in os.listdir(aot_dir) if f.endswith(".bin")]
    assert arts, "no AOT artifacts were exported"
    mtimes = {
        f: os.path.getmtime(os.path.join(aot_dir, f)) for f in arts
    }
    # Second engine: same cache dir, fresh runner — must LOAD, not
    # re-export (artifact mtimes unchanged), and match token-for-token.
    second = _greedy(model_dir, cache)
    assert second == first
    for f, t in mtimes.items():
        assert os.path.getmtime(os.path.join(aot_dir, f)) == t


def test_aot_corrupt_artifact_falls_back(tmp_path):
    model_dir = make_tiny_llama(str(tmp_path / "m"))
    cache = str(tmp_path / "cache")
    first = _greedy(model_dir, cache)
    aot_dir = os.path.join(cache, "aot")
    for f in os.listdir(aot_dir):
        if f.endswith(".bin"):
            with open(os.path.join(aot_dir, f), "wb") as fh:
                fh.write(b"garbage")
    assert _greedy(model_dir, cache) == first
