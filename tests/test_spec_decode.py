"""Speculative decoding (ISSUE 11): n-gram prompt-lookup drafts
verified in one fused pass with greedy accept/reject.

Layers covered:
- proposer units (no match / prompt match / K-cap / output-history
  match / longest-n preference);
- the accept-length kernel (ops/sampling.spec_greedy_accept) against a
  Python oracle, including masking and full-accept/reject extremes;
- engine-level greedy bit-identity on the real tiny model — spec on vs
  off through heterogeneous budgets, EOS/stop mid-window, chunked
  prefill, and preemption/resume;
- deterministic acceptance control through the mock worker
  (VDT_MOCK_TOKEN_SEQ=seq:...): full-accept, full-reject, and
  mixed-acceptance batches;
- step-delta codec round trips with draft/accept fields (worker
  mirrors stay in lockstep without override warnings);
- supervisor journal replay with spec enabled;
- the deterministic bench gate: with device time modeled as cost×HBM
  passes (VDT_MOCK_HBM_PASS_SECONDS), spec decode on a fully
  repetitive stream must beat fused decode by >= 1.3x.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.mock_worker import MockUniProcExecutor
from vllm_distributed_tpu.config import EngineArgs, SchedulerConfig
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.engine.spec_decode import (
    NgramProposer,
    spec_eligible,
)
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.testing import write_llama_config

pytestmark = pytest.mark.spec


# ---------------------------------------------------------------------
# proposer units
# ---------------------------------------------------------------------
def test_propose_no_match():
    p = NgramProposer(k=4)
    assert p.propose([1, 2, 3, 4, 5]) == []
    assert p.propose([]) == []
    assert p.propose([7]) == []


def test_propose_prompt_match():
    # Tail [5, 6] recurs at the start; the continuation follows it.
    p = NgramProposer(k=4)
    assert p.propose([5, 6, 7, 9, 5, 6]) == [7, 9, 5, 6]


def test_propose_k_cap():
    p = NgramProposer(k=2)
    assert p.propose([5, 6, 7, 9, 5, 6]) == [7, 9]
    # max_draft caps below k too.
    assert p.propose([5, 6, 7, 9, 5, 6], max_draft=1) == [7]
    assert p.propose([5, 6, 7, 9, 5, 6], max_draft=0) == []


def test_propose_output_history_match():
    # The recurring n-gram lives entirely in generated output (the
    # part after the "prompt" [1, 2]): proposals must see it.
    p = NgramProposer(k=3)
    history = [1, 2] + [8, 3, 4, 8, 3]
    assert p.propose(history) == [4, 8, 3]


def test_propose_longest_ngram_wins():
    # 1-gram [6] matches at index 1 (continuation 9), but the 2-gram
    # [5, 6] match is more specific and must win.
    p = NgramProposer(k=1, min_n=1, max_n=3)
    assert p.propose([5, 6, 9, 5, 6]) == [9]


def test_propose_periodic_tail_overlap():
    # Period-1 repetition: the match ends one short of the tail, so
    # exactly the literal continuation is drafted.
    p = NgramProposer(k=5)
    assert p.propose([4, 4, 4, 4]) == [4]
    # Longer cycles: the earliest match has the whole cycle ahead.
    assert p.propose([1, 2, 3, 1, 2, 3, 1, 2]) == [3, 1, 2]


def test_proposer_validation():
    with pytest.raises(ValueError):
        NgramProposer(k=0)
    with pytest.raises(ValueError):
        NgramProposer(k=2, min_n=3, max_n=2)
    with pytest.raises(ValueError):
        NgramProposer(k=2, min_n=0, max_n=2)


def test_spec_eligible_gate():
    assert spec_eligible(SamplingParams(temperature=0.0))
    assert not spec_eligible(SamplingParams(temperature=0.7))
    assert not spec_eligible(SamplingParams(temperature=0.0, logprobs=1))
    assert not spec_eligible(
        SamplingParams(temperature=0.0, repetition_penalty=1.2)
    )
    assert not spec_eligible(
        SamplingParams(temperature=0.0, presence_penalty=0.5)
    )
    assert not spec_eligible(
        SamplingParams(temperature=0.0, frequency_penalty=0.5)
    )


# ---------------------------------------------------------------------
# accept kernel
# ---------------------------------------------------------------------
def _accept_oracle(logits, drafts, n_drafts):
    """Reference accept/reject: sequential greedy comparison."""
    greedy = np.argmax(logits, axis=-1)
    out_tokens, out_n = [], []
    for s in range(logits.shape[0]):
        a = 0
        while a < n_drafts[s] and drafts[s, a] == greedy[s, a]:
            a += 1
        out_tokens.append(greedy[s])
        out_n.append(a + 1)
    return np.stack(out_tokens), np.asarray(out_n)


def test_accept_kernel_extremes():
    from vllm_distributed_tpu.ops.sampling import spec_greedy_accept

    rng = np.random.default_rng(0)
    s, kp1, v = 4, 4, 16
    logits = rng.normal(size=(s, kp1, v)).astype(np.float32)
    greedy = np.argmax(logits, axis=-1)
    drafts = np.full((s, kp1 - 1), -1, np.int32)
    n_drafts = np.zeros(s, np.int32)
    # Row 0: full accept (drafts copy the greedy chain).
    drafts[0] = greedy[0, : kp1 - 1]
    n_drafts[0] = kp1 - 1
    # Row 1: full reject (first draft off-by-one).
    drafts[1] = (greedy[1, : kp1 - 1] + 1) % v
    n_drafts[1] = kp1 - 1
    # Row 2: partial (first matches, second diverges).
    drafts[2, 0] = greedy[2, 0]
    drafts[2, 1] = (greedy[2, 1] + 1) % v
    n_drafts[2] = 2
    # Row 3: no drafts (plain decode row).
    toks, n_emit = spec_greedy_accept(logits, drafts, n_drafts)
    assert list(np.asarray(n_emit)) == [kp1, 1, 2, 1]
    np.testing.assert_array_equal(np.asarray(toks), greedy)


def test_accept_kernel_matches_oracle_randomized():
    from vllm_distributed_tpu.ops.sampling import spec_greedy_accept

    rng = np.random.default_rng(7)
    for _ in range(10):
        s, kp1, v = 8, 8, 32
        logits = rng.normal(size=(s, kp1, v)).astype(np.float32)
        greedy = np.argmax(logits, axis=-1)
        n_drafts = rng.integers(0, kp1, size=s).astype(np.int32)
        drafts = np.full((s, kp1 - 1), -1, np.int32)
        for i in range(s):
            for j in range(n_drafts[i]):
                # Coin-flip between the matching token and a wrong one.
                drafts[i, j] = (
                    greedy[i, j]
                    if rng.random() < 0.6
                    else (greedy[i, j] + 1) % v
                )
        toks, n_emit = spec_greedy_accept(logits, drafts, n_drafts)
        want_toks, want_n = _accept_oracle(logits, drafts, n_drafts)
        np.testing.assert_array_equal(np.asarray(toks), want_toks)
        np.testing.assert_array_equal(np.asarray(n_emit), want_n)
        # The emitted prefix is exactly what sequential greedy decode
        # would produce — the bit-identity invariant.
        for i in range(s):
            m = int(want_n[i])
            assert 1 <= m <= n_drafts[i] + 1
            assert list(np.asarray(toks)[i, :m]) == list(greedy[i, :m])


# ---------------------------------------------------------------------
# engine-level bit-identity (real tiny model, dummy weights)
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return write_llama_config(
        str(tmp_path_factory.mktemp("spec") / "m")
    )


def _run(model_dir, reqs, *, spec_k=0, track_spec=None, **engine_kw):
    kw = dict(
        model=model_dir,
        skip_tokenizer_init=True,
        load_format="dummy",
        num_kv_pages=128,
        max_model_len=256,
        num_decode_steps=4,
        speculative_ngram_k=spec_k,
    )
    kw.update(engine_kw)
    engine = LLMEngine.from_engine_args(EngineArgs(**kw))
    for i, (prompt, sp_kw) in enumerate(reqs):
        engine.add_request(
            f"r{i}",
            prompt_token_ids=list(prompt),
            sampling_params=SamplingParams(**sp_kw),
        )
    results: dict[str, list[int]] = {}
    steps = 0
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                results[out.request_id] = out.outputs[0].token_ids
        steps += 1
        assert steps < 800
    if track_spec is not None:
        track_spec.append(
            (
                engine.scheduler.spec_drafted_tokens,
                engine.scheduler.spec_accepted_tokens,
            )
        )
        track_spec.append(engine.metrics.render().decode())
    engine.shutdown()
    return results


REPETITIVE = [3, 7, 11, 3, 7, 11, 3, 7]
PLAIN = [5, 9, 2, 4]


def test_spec_greedy_bit_identity_heterogeneous_budgets(model_dir):
    """Spec on vs off over a mixed batch — repetitive and plain
    prompts, budgets that end mid-window — must be bit-identical, and
    the verify passes must actually accept drafts."""
    reqs = [
        (REPETITIVE, dict(temperature=0.0, max_tokens=m, ignore_eos=True))
        for m in (3, 17, 40, 5)
    ] + [
        (PLAIN, dict(temperature=0.0, max_tokens=23, ignore_eos=True)),
    ]
    base = _run(model_dir, reqs, spec_k=0)
    stats: list = []
    spec = _run(model_dir, reqs, spec_k=4, track_spec=stats)
    assert spec == base
    drafted, accepted = stats[0]
    assert drafted > 0 and 0 < accepted <= drafted
    # Lengths exactly honor per-request budgets (no draft overshoot).
    assert sorted(len(v) for v in spec.values()) == [3, 5, 17, 23, 40]


def test_spec_stop_token_mid_window(model_dir):
    """A stop token accepted mid-verify-window must truncate exactly
    where the sequential engine would."""
    probe = _run(
        model_dir,
        [(REPETITIVE, dict(temperature=0.0, max_tokens=24, ignore_eos=True))],
    )["r0"]
    stop_tok = probe[7]
    reqs = [
        (
            REPETITIVE,
            dict(temperature=0.0, max_tokens=24, stop_token_ids=[stop_tok]),
        )
    ]
    assert _run(model_dir, reqs, spec_k=4) == _run(model_dir, reqs)


def test_spec_through_preemption_and_chunked_prefill(model_dir):
    """Starved page pool (preemption/resume) + tiny token budget
    (chunked prefill) with spec on must still match the unconstrained
    non-speculative run."""
    reqs = [
        (
            list(range(1, 30)) + REPETITIVE,
            dict(temperature=0.0, max_tokens=8, ignore_eos=True),
        ),
        (
            list(range(30, 55)) + REPETITIVE,
            dict(temperature=0.0, max_tokens=8, ignore_eos=True),
        ),
    ]
    rich = _run(model_dir, reqs, spec_k=0)
    poor = _run(
        model_dir,
        reqs,
        spec_k=4,
        num_kv_pages=10,
        max_num_batched_tokens=32,
        max_num_seqs=8,
    )
    assert poor == rich


def test_spec_sampling_requests_opt_out(model_dir):
    """Seeded sampling is spec-ineligible: with spec configured the
    batch falls back to the normal path and outputs stay identical."""
    reqs = [
        (
            REPETITIVE,
            dict(temperature=0.9, seed=41, max_tokens=12, ignore_eos=True),
        )
    ]
    stats: list = []
    spec = _run(model_dir, reqs, spec_k=4, track_spec=stats)
    assert spec == _run(model_dir, reqs, spec_k=0)
    assert stats[0] == (0, 0)  # nothing drafted for a sampling batch


def test_spec_metrics_and_registry(model_dir):
    """Spec counters flow to /metrics and the acceptance-length
    histogram observes once per verified window."""
    stats: list = []
    _run(
        model_dir,
        [(REPETITIVE, dict(temperature=0.0, max_tokens=16, ignore_eos=True))],
        spec_k=4,
        track_spec=stats,
    )
    drafted, accepted = stats[0]
    rendered = stats[1]
    assert drafted > 0
    assert (
        f'vllm:spec_decode_draft_tokens_total{{model_name="'
        in rendered.replace("\n", " ")
        or "vllm:spec_decode_draft_tokens_total" in rendered
    )

    def metric(name):
        for line in rendered.splitlines():
            if line.startswith(name + "{"):
                return float(line.rsplit(" ", 1)[1])
        return None

    assert metric("vllm:spec_decode_draft_tokens_total") == drafted
    assert metric("vllm:spec_decode_accepted_tokens_total") == accepted
    assert metric("vllm:spec_decode_acceptance_length_count") > 0


# ---------------------------------------------------------------------
# deterministic acceptance control (mock worker, VDT_MOCK_TOKEN_SEQ)
# ---------------------------------------------------------------------
def _mock_run(model_dir, prompts_and_budgets, *, spec_k, seq,
              monkeypatch, num_decode_steps=4, hbm_pass_seconds=None):
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", seq)
    if hbm_pass_seconds is not None:
        monkeypatch.setenv(
            "VDT_MOCK_HBM_PASS_SECONDS", str(hbm_pass_seconds)
        )
    else:
        monkeypatch.delenv("VDT_MOCK_HBM_PASS_SECONDS", raising=False)
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=64,
            max_model_len=256,
            num_decode_steps=num_decode_steps,
            speculative_ngram_k=spec_k,
            distributed_executor_backend=MockUniProcExecutor,
        )
    )
    for i, (prompt, max_tokens) in enumerate(prompts_and_budgets):
        engine.add_request(
            f"m{i}",
            prompt_token_ids=list(prompt),
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )
    results: dict[str, list[int]] = {}
    import time as _time

    t0 = _time.perf_counter()
    steps = 0
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                results[out.request_id] = out.outputs[0].token_ids
        steps += 1
        assert steps < 800
    elapsed = _time.perf_counter() - t0
    stats = (
        engine.scheduler.spec_drafted_tokens,
        engine.scheduler.spec_accepted_tokens,
    )
    engine.shutdown()
    return results, stats, elapsed


def test_mock_full_accept_batch(model_dir, monkeypatch):
    """Periodic stream whose prompt covers a full cycle: every draft
    verifies, acceptance rate is exactly 1.0, outputs bit-identical."""
    seq = "seq:7,8,9,10"
    work = [([7, 8, 9, 10, 7, 8, 9, 10], 16)]
    base, _, _ = _mock_run(
        model_dir, work, spec_k=0, seq=seq, monkeypatch=monkeypatch
    )
    spec, (drafted, accepted), _ = _mock_run(
        model_dir, work, spec_k=3, seq=seq, monkeypatch=monkeypatch
    )
    assert spec == base
    assert drafted > 0 and accepted == drafted
    # The stream really is the periodic continuation.
    assert spec["m0"] == [(7, 8, 9, 10)[p % 4] for p in range(8, 24)]


def test_mock_full_reject_window(model_dir, monkeypatch):
    """History whose recurring n-gram continues differently than the
    emitted stream: the verify window drafts and rejects everything
    (bonus token only), outputs still bit-identical."""
    # Prefill emits position 6 of the stream (7), making the history
    # tail [5,6,7] — which recurs at index 0 with continuation
    # [9,5,...]; the stream actually emits 80, 81, ... so every draft
    # is rejected.
    seq = "seq:5,6,7,9,5,6,7,80,81,82,83,84,85,86,87,88"
    work = [([5, 6, 7, 9, 5, 6], 4)]
    base, _, _ = _mock_run(
        model_dir, work, spec_k=3, seq=seq, monkeypatch=monkeypatch,
        num_decode_steps=1,
    )
    spec, (drafted, accepted), _ = _mock_run(
        model_dir, work, spec_k=3, seq=seq, monkeypatch=monkeypatch,
        num_decode_steps=1,
    )
    assert spec == base == {"m0": [7, 80, 81, 82]}
    assert drafted >= 2 and accepted == 0


def test_mock_mixed_acceptance_batch(model_dir, monkeypatch):
    """One full-accept request, one partial-accept request, one
    drafting-nothing request in the same batch."""
    # Stream period 8.  Request A's prompt is a full double period of
    # the first 4 -> its drafts continue [1,2,3,4] and fully accept
    # until the stream leaves the sub-cycle; request B's tail [1,2]
    # matches its own prompt start with continuation [3,4,...] but the
    # stream diverges at position 7 (9 != 4) -> partial accepts;
    # request C has no recurring n-gram and an aperiodic continuation.
    seq = "seq:1,2,3,4,1,2,3,9"
    work = [
        ([1, 2, 3, 4, 1, 2, 3, 9], 10),  # aligned: high acceptance
        ([1, 2, 3, 4, 1, 2], 6),  # diverges at the period boundary
        ([40, 50, 60], 4),  # nothing to look up at first
    ]
    base, _, _ = _mock_run(
        model_dir, work, spec_k=3, seq=seq, monkeypatch=monkeypatch
    )
    spec, (drafted, accepted), _ = _mock_run(
        model_dir, work, spec_k=3, seq=seq, monkeypatch=monkeypatch
    )
    assert spec == base
    assert drafted > 0
    assert 0 < accepted < drafted  # genuinely mixed acceptance


def test_spec_bench_gate_mock(model_dir, monkeypatch):
    """The deterministic throughput gate: with device time modeled as
    cost x HBM passes (fused decode pays one per micro-step, a verify
    window pays one total), spec decode on a fully repetitive stream
    must deliver >= 1.3x tokens/s at its measured acceptance rate."""
    seq = "seq:7,8,9,10"
    work = [([7, 8, 9, 10, 7, 8, 9, 10], 48)]
    base, _, base_s = _mock_run(
        model_dir, work, spec_k=0, seq=seq, monkeypatch=monkeypatch,
        num_decode_steps=4, hbm_pass_seconds=0.004,
    )
    spec, (drafted, accepted), spec_s = _mock_run(
        model_dir, work, spec_k=4, seq=seq, monkeypatch=monkeypatch,
        num_decode_steps=4, hbm_pass_seconds=0.004,
    )
    assert spec == base
    acceptance = accepted / max(drafted, 1)
    assert acceptance > 0.9  # fully repetitive stream
    speedup = base_s / spec_s
    assert speedup >= 1.3, (
        f"spec decode speedup {speedup:.2f}x < 1.3x "
        f"(acceptance {acceptance:.2f})"
    )


def test_spec_dormant_pipelining_resumes(model_dir, monkeypatch):
    """Hysteresis: non-repetitive greedy traffic with spec configured
    must fall back to the async dispatch pipeline after the dry limit
    instead of running synchronously forever — and still produce the
    oracle token stream."""
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.delenv("VDT_MOCK_HBM_PASS_SECONDS", raising=False)
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=64,
            max_model_len=256,
            num_decode_steps=4,
            speculative_ngram_k=3,
            distributed_executor_backend=MockUniProcExecutor,
        )
    )
    # Identity stream + distinct prompt tokens: no n-gram ever recurs,
    # so the proposer stays dry for the whole run.
    engine.add_request(
        "m0",
        prompt_token_ids=[100, 200, 300],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=60, ignore_eos=True
        ),
    )
    depths = []
    toks = None
    steps = 0
    while engine.has_unfinished_requests():
        depths.append(len(engine._pending))
        for out in engine.step():
            if out.finished:
                toks = out.outputs[0].token_ids
        steps += 1
        assert steps < 400
    engine.shutdown()
    assert toks == list(range(3, 63))
    assert engine.scheduler.spec_drafted_tokens == 0
    # The dispatch pipeline re-engaged during the dormant stretch.
    assert max(depths) >= 1


def test_spec_hysteresis_probe_reengages():
    """Scheduler-level hysteresis cycle: dry streak -> dormant
    (pipelining allowed) -> periodic probe -> repetitive text
    re-engages spec."""
    from vllm_distributed_tpu.config import CacheConfig
    from vllm_distributed_tpu.engine.request import Request
    from vllm_distributed_tpu.engine.scheduler import (
        _SPEC_DRY_LIMIT,
        _SPEC_PROBE_INTERVAL,
        Scheduler,
    )

    sched = Scheduler(
        SchedulerConfig(
            max_num_seqs=4,
            max_num_batched_tokens=256,
            enable_chunked_prefill=True,
            max_model_len=512,
            num_decode_steps=4,
            spec_ngram_k=3,
        ),
        CacheConfig(page_size=4),
        num_pages=128,
    )
    sched.add_request(
        Request(
            request_id="a",
            prompt_token_ids=[100, 200, 300],
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=400, ignore_eos=True
            ),
            eos_token_id=None,
        )
    )
    tok = 1000

    def drain_one(so):
        nonlocal tok
        n = so.num_scheduled_tokens.get("a", 0)
        req = sched.requests["a"]
        if req.num_computed_tokens + n >= req.num_tokens:
            toks = list(range(tok, tok + n))
            tok += n
            sched.update_from_output(so, {"a": toks})
        else:
            sched.update_from_output(so, {})

    drain_one(sched.schedule())  # prefill
    assert sched.spec_wants_sync()
    # Distinct tokens: the proposer stays dry until the limit trips.
    for _ in range(_SPEC_DRY_LIMIT):
        assert sched.spec_wants_sync()
        so = sched.schedule()
        assert not so.draft_token_ids
        drain_one(so)
    assert not sched.spec_wants_sync()  # dormant: pipelining allowed
    # Pipelined continuations (no update between schedules) count
    # toward the probe cadence; the FIRST dormant schedule still sees
    # inflight == 0 (a free probe) and does not count.
    pending = []
    for _ in range(_SPEC_PROBE_INTERVAL + 1):
        assert not sched.spec_wants_sync()
        pending.append(sched.schedule())
    assert sched.spec_wants_sync()  # probe drain due
    # Drain the window; the text now turns repetitive, so the probing
    # schedule finds drafts and spec re-engages.
    for so in pending:
        n = so.num_scheduled_tokens["a"]
        sched.update_from_output(so, {"a": [7] * n})
    so = sched.schedule()
    assert so.draft_token_ids.get("a")
    assert sched.spec_wants_sync()


# ---------------------------------------------------------------------
# step-delta codec: draft/accept fields keep mirrors in lockstep
# ---------------------------------------------------------------------
def test_step_delta_spec_roundtrip_lockstep():
    from vllm_distributed_tpu.config import CacheConfig
    from vllm_distributed_tpu.engine.request import Request
    from vllm_distributed_tpu.engine.scheduler import Scheduler
    from vllm_distributed_tpu.engine.step_delta import (
        StepDeltaEncoder,
        StepStateMirror,
    )

    sched = Scheduler(
        SchedulerConfig(
            max_num_seqs=8,
            max_num_batched_tokens=64,
            enable_chunked_prefill=True,
            max_model_len=256,
            num_decode_steps=1,
            spec_ngram_k=3,
        ),
        CacheConfig(page_size=4),
        num_pages=64,
    )
    encoder = StepDeltaEncoder()
    mirrors = [StepStateMirror(), StepStateMirror()]
    # Periodic prompt: the proposer drafts, the fake device accepts a
    # varying prefix (cycling 0..k accepted) to exercise every
    # spec_advance value.
    sched.add_request(
        Request(
            request_id="a",
            prompt_token_ids=[3, 7, 3, 7, 3],
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=14, ignore_eos=True
            ),
            eos_token_id=None,
        )
    )
    accept_cycle = [3, 0, 1, 2]
    spec_steps = 0
    saw_advance = False
    for step in range(200):
        so = sched.schedule()
        if so.is_empty:
            break
        frame = encoder.encode(so)
        assert frame.raw is None
        assert not frame.computed_overrides, (
            "spec steps must reconcile via spec_advance, not overrides"
        )
        if frame.spec_advance:
            saw_advance = True
        for mirror in mirrors:
            rebuilt = mirror.decode(frame)
            assert rebuilt == so
        sampled = {}
        for rid, n in so.num_scheduled_tokens.items():
            req = sched.requests[rid]
            d = so.draft_token_ids.get(rid)
            if d is not None:
                spec_steps += 1
                a = min(accept_cycle[spec_steps % 4], len(d))
                # Accepted drafts echo the drafted tokens (the argmax
                # chain equals them by definition of accept); the bonus
                # stays in the {3, 7} alphabet so the proposer keeps
                # finding matches and windows keep coming.
                sampled[rid] = list(d[:a]) + [7]
            elif req.num_computed_tokens + n >= req.num_tokens:
                sampled[rid] = [7 if step % 2 else 3]
        sched.update_from_output(so, sampled)
    assert spec_steps >= 2 and saw_advance
    assert encoder.num_mirrored == mirrors[0].num_mirrored
    assert mirrors[0].num_mirrored == mirrors[1].num_mirrored


# ---------------------------------------------------------------------
# supervisor replay with spec enabled
# ---------------------------------------------------------------------
def test_replay_equivalence_with_spec(tmp_path):
    """Kill-and-replay determinism with spec decode on: reference run
    to completion, twin stopped partway, journal replayed onto a fresh
    spec-enabled engine — final output bit-identical (and equal to the
    non-speculative run)."""
    from vllm_distributed_tpu.engine.supervisor import (
        EngineSupervisor,
        JournalEntry,
        RestartPolicy,
    )

    model = write_llama_config(str(tmp_path / "m"))
    sp = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)
    prompt = list(REPETITIVE)

    def engine(spec_k):
        return LLMEngine.from_engine_args(
            EngineArgs(
                model=model,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=64,
                max_model_len=128,
                num_decode_steps=2,
                speculative_ngram_k=spec_k,
            )
        )

    def drain(eng, rid):
        tokens = None
        while eng.has_unfinished_requests():
            for out in eng.step():
                if out.request_id == rid:
                    tokens = list(out.outputs[0].token_ids)
        return tokens

    ref = engine(spec_k=4)
    try:
        ref.add_request("x", prompt_token_ids=list(prompt),
                        sampling_params=sp.clone())
        reference = drain(ref, "x")
    finally:
        ref.shutdown()
    off = engine(spec_k=0)
    try:
        off.add_request("x", prompt_token_ids=list(prompt),
                        sampling_params=sp.clone())
        assert drain(off, "x") == reference
    finally:
        off.shutdown()

    cut = engine(spec_k=4)
    emitted: list[int] = []
    try:
        cut.add_request("x", prompt_token_ids=list(prompt),
                        sampling_params=sp.clone())
        while len(emitted) < 4:
            for out in cut.step():
                emitted = list(out.outputs[0].token_ids)
    finally:
        cut.shutdown()
    assert reference[: len(emitted)] == emitted

    class _Stub:
        def __init__(self):
            self._journal = {}
            self.errors = []

        def _to_request_queue(self, request_id, e):
            self.errors.append((request_id, e))

    new = engine(spec_k=4)
    try:
        stub = _Stub()
        sup = EngineSupervisor(
            stub,
            policy=RestartPolicy(
                max_restarts=3, backoff_base=0.1, backoff_cap=1.0,
                window=300,
            ),
        )
        entry = JournalEntry(
            request_id="x",
            prompt=None,
            prompt_token_ids=list(prompt),
            sampling_params=sp.clone(),
        )
        entry.admitted = True
        entry.emitted_token_ids = list(emitted)
        stub._journal["x"] = entry
        assert sup._replay(new) == 1
        final = drain(new, "x")
    finally:
        new.shutdown()
    assert final == reference, (final, reference)


# ---------------------------------------------------------------------
# config / env knobs
# ---------------------------------------------------------------------
def test_cli_and_env_knobs(model_dir, monkeypatch):
    import argparse

    parser = EngineArgs.add_cli_args(argparse.ArgumentParser())
    args = parser.parse_args(
        ["--model", model_dir, "--speculative-ngram-k", "5",
         "--speculative-ngram-max", "4", "--skip-tokenizer-init"]
    )
    cfg = EngineArgs.from_cli_args(args).create_engine_config()
    assert cfg.scheduler_config.spec_ngram_k == 5
    assert cfg.scheduler_config.spec_ngram_max == 4
    assert cfg.scheduler_config.spec_ngram_min == 1
    # Env fallback when the CLI flag is absent.
    monkeypatch.setenv("VDT_SPEC_NGRAM_K", "2")
    cfg = EngineArgs(
        model=model_dir, skip_tokenizer_init=True
    ).create_engine_config()
    assert cfg.scheduler_config.spec_ngram_k == 2


def test_scheduler_config_validation():
    with pytest.raises(ValueError, match="spec_ngram_k"):
        SchedulerConfig(spec_ngram_k=-1)
    with pytest.raises(ValueError, match="spec_ngram_min"):
        SchedulerConfig(spec_ngram_k=2, spec_ngram_min=3, spec_ngram_max=2)
    with pytest.raises(ValueError, match="verify window"):
        SchedulerConfig(
            spec_ngram_k=4096,
            max_num_batched_tokens=2048,
            max_num_seqs=8,
        )
    # Off (0) skips the min/max check entirely.
    SchedulerConfig(spec_ngram_k=0, spec_ngram_min=9, spec_ngram_max=1)


# ---------------------------------------------------------------------
# trace_summary surfaces acceptance
# ---------------------------------------------------------------------
def test_trace_summary_spec_section():
    import importlib

    ts = importlib.import_module("tools.trace_summary")
    traces = [
        {
            "trace_id": "t1",
            "spans": [
                {
                    "name": "engine.spec_decode",
                    "attributes": {"drafted": 6, "accepted": 4},
                },
                {
                    "name": "engine.spec_decode",
                    "attributes": {"drafted": 2, "accepted": 0},
                },
                {"name": "engine.decode", "start": 0, "duration": 0.5},
            ],
        }
    ]
    spec = ts.spec_summary(traces)
    assert spec == {
        "verify_steps": 2,
        "drafted": 8,
        "accepted": 4,
        "acceptance_rate": 0.5,
    }
    assert "acceptance" in ts.format_spec(spec)
    assert ts.spec_summary([{"spans": []}]) is None
