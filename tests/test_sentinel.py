"""Fleet sentinel unit tests (ISSUE 20): the event log contract, the
timeline-merge determinism pin (order-independent, bit-equal to a
union recompute), the multi-window burn-rate math against synthetic
attainment traces, and the router sentinel's anomaly scoring /
alerting on synthetic probe scrapes.  Everything runs on fake clocks —
no sleeps, no sockets."""

from __future__ import annotations

import json
import random

import pytest

from vllm_distributed_tpu.engine.sentinel import (
    BURN_WINDOWS,
    EVENT_KINDS,
    BurnRateTracker,
    SentinelLog,
)
from vllm_distributed_tpu.router.sentinel import (
    SIGNAL_EPS,
    SIGNALS,
    RouterSentinel,
    merge_timelines,
    parse_sentinel_samples,
    robust_zscores,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------
# SentinelLog
# ---------------------------------------------------------------------
def test_log_emit_shape_and_seq():
    clock, wall = FakeClock(10.0), FakeClock(1e9)
    log = SentinelLog("engine", maxlen=8, clock=clock, wall=wall)
    e1 = log.emit("qos_shed", count=3)
    clock.advance(0.5)
    e2 = log.emit("kv_handoff", replica_id="r1", trace_id="t1", pages=4)
    assert e1["seq"] == 1 and e2["seq"] == 2
    assert e1["source"] == "engine" and e1["kind"] == "qos_shed"
    assert e1["attrs"] == {"count": 3}
    assert "replica_id" not in e1  # empty ids are omitted
    assert e2["replica_id"] == "r1" and e2["trace_id"] == "t1"
    assert e2["ts_mono"] > e1["ts_mono"]
    assert len(log) == 2 and [e["seq"] for e in log.snapshot()] == [1, 2]


def test_log_bounded_ring_keeps_newest():
    log = SentinelLog("engine", maxlen=3)
    for i in range(10):
        log.emit("qos_shed", count=i)
    snap = log.snapshot()
    assert len(snap) == 3
    assert [e["attrs"]["count"] for e in snap] == [7, 8, 9]
    assert snap[-1]["seq"] == 10  # seq keeps counting past evictions


def test_log_rejects_unregistered_kind():
    log = SentinelLog("engine", maxlen=8)
    with pytest.raises(ValueError, match="unregistered"):
        log.emit("definitely_not_a_kind")


def test_log_disabled_is_inert():
    log = SentinelLog("engine", maxlen=0)
    assert not log.enabled
    assert log.emit("qos_shed") is None
    assert log.snapshot() == [] and len(log) == 0
    # Kind validation still applies while disabled — a typo must not
    # hide behind VDT_SENTINEL_EVENTS_SIZE=0 deployments.
    with pytest.raises(ValueError):
        log.emit("typo_kind")


def test_alert_kinds_are_registered():
    # Every alert the router raises mirrors into the timeline as
    # alert_<kind>; the vocabulary must contain them.
    for kind in ("slo_burn", "replica_degraded", "replica_unreachable"):
        assert f"alert_{kind}" in EVENT_KINDS


# ---------------------------------------------------------------------
# Timeline merge: order-independent, bit-equal to union recompute
# ---------------------------------------------------------------------
def _synthetic_logs(seed: int = 7) -> dict[str, list[dict]]:
    rng = random.Random(seed)
    kinds = sorted(EVENT_KINDS)
    parts: dict[str, list[dict]] = {}
    for owner in ("router", "r1", "r2", "r3"):
        events = []
        for seq in range(1, 40):
            events.append({
                "ts_mono": round(rng.uniform(0, 100), 6),
                # Deliberate collisions: identical ts_wall across
                # owners must still order totally.
                "ts_wall": round(rng.choice([1.0, 2.0, rng.uniform(0, 60)]), 6),
                "source": "router" if owner == "router" else "engine",
                "kind": rng.choice(kinds),
                "seq": seq,
                "attrs": {"n": seq},
            })
        parts[owner] = events
    return parts


def test_merge_is_order_independent_and_bit_equal():
    parts = _synthetic_logs()
    offsets = {"router": 0.0, "r1": 0.25, "r2": -1.5, "r3": 0.0}
    reference = merge_timelines(parts, offsets)
    ref_json = json.dumps(reference, sort_keys=True)

    rng = random.Random(123)
    for _ in range(10):
        shuffled = {}
        for owner, events in parts.items():
            ev = [dict(e) for e in events]
            rng.shuffle(ev)
            shuffled[owner] = ev
        # Present owners in a shuffled insertion order too.
        owners = list(shuffled)
        rng.shuffle(owners)
        again = merge_timelines(
            {o: shuffled[o] for o in owners}, offsets
        )
        assert json.dumps(again, sort_keys=True) == ref_json

    # Bit-equal to recomputing from the union: re-merging the merged
    # stream (grouped back by origin) reproduces itself.
    regrouped: dict[str, list[dict]] = {}
    for ev in reference:
        item = {
            k: v for k, v in ev.items() if k not in ("origin", "ts")
        }
        # The corrected ts must be reconstructible from ts_wall.
        regrouped.setdefault(ev["origin"], []).append(item)
    assert (
        json.dumps(merge_timelines(regrouped, offsets), sort_keys=True)
        == ref_json
    )


def test_merge_applies_clock_offsets():
    parts = {
        "router": [
            {"ts_wall": 100.0, "source": "router", "kind": "spawn", "seq": 1}
        ],
        "r1": [
            # r1's wall clock runs 5 s ahead of the router's: an event
            # it stamped at 103 actually happened at 98, BEFORE the
            # router's event.
            {"ts_wall": 103.0, "source": "engine", "kind": "ready", "seq": 1}
        ],
    }
    merged = merge_timelines(parts, {"router": 0.0, "r1": 5.0})
    assert [e["origin"] for e in merged] == ["r1", "router"]
    assert merged[0]["ts"] == 98.0
    # Without offsets the raw wall order wins.
    merged = merge_timelines(parts)
    assert [e["origin"] for e in merged] == ["router", "r1"]


# ---------------------------------------------------------------------
# Burn-rate math on synthetic attainment traces
# ---------------------------------------------------------------------
def _drive(tracker, clock, seconds, rps, err_rate, state):
    """Advance a cumulative (requests, goodput) trace; returns every
    alert fired along the way."""
    fired = []
    for _ in range(int(seconds / 10)):
        clock.advance(10)
        state["req"] += rps * 10
        state["good"] += int(rps * 10 * (1 - err_rate))
        fired += tracker.observe("rt", state["req"], state["good"])
    return fired


def test_burn_zero_on_perfect_attainment():
    clock = FakeClock()
    tracker = BurnRateTracker(
        objective=0.99, threshold=10.0, clock=clock
    )
    state = {"req": 0, "good": 0}
    assert _drive(tracker, clock, 3600, rps=10, err_rate=0.0, state=state) == []
    rates = tracker.burn_rates("rt")
    assert set(rates) == {w for w, _ in BURN_WINDOWS}
    assert all(r == 0.0 for r in rates.values())
    assert tracker.peak == 0.0


def test_burn_rate_value_matches_the_math():
    clock = FakeClock()
    tracker = BurnRateTracker(
        objective=0.99, threshold=10.0, clock=clock
    )
    state = {"req": 0, "good": 0}
    # A steady 2% error rate: burn = 0.02 / (1 - 0.99) = 2.0 on every
    # window once the trace spans them.
    _drive(tracker, clock, 3700, rps=10, err_rate=0.02, state=state)
    rates = tracker.burn_rates("rt")
    assert rates["5m"] == pytest.approx(2.0, rel=0.05)
    assert rates["1h"] == pytest.approx(2.0, rel=0.05)
    assert tracker.peak == pytest.approx(2.0, rel=0.1)


def test_short_burst_alone_does_not_page():
    clock = FakeClock()
    tracker = BurnRateTracker(
        objective=0.99, threshold=10.0, clock=clock
    )
    state = {"req": 0, "good": 0}
    _drive(tracker, clock, 3600, rps=10, err_rate=0.0, state=state)
    # 30 s of total failure: the 5m window burns at 10, the 1h window
    # at ~0.8 — no alert (this is the whole point of paired windows).
    fired = _drive(tracker, clock, 30, rps=10, err_rate=1.0, state=state)
    assert fired == []
    rates = tracker.burn_rates("rt")
    assert rates["5m"] == pytest.approx(10.0, rel=0.01)
    assert rates["1h"] < 10.0


def test_sustained_burn_fires_once_then_rearms():
    clock = FakeClock()
    tracker = BurnRateTracker(
        objective=0.99, threshold=10.0, clock=clock
    )
    state = {"req": 0, "good": 0}
    _drive(tracker, clock, 3600, rps=10, err_rate=0.0, state=state)
    # Total failure: the 1h window crosses burn 10 once >10% of its
    # requests have failed — ~6 min in, i.e. within two short windows.
    fired = _drive(tracker, clock, 600, rps=10, err_rate=1.0, state=state)
    assert len(fired) == 1
    alert = fired[0]
    assert alert["slo_class"] == "rt"
    assert alert["threshold"] == 10.0
    assert set(alert["burn"]) == {w for w, _ in BURN_WINDOWS}
    assert all(v >= 10.0 for v in alert["burn"].values())
    # Holding the breach does not re-fire (edge, not level).
    assert _drive(tracker, clock, 300, rps=10, err_rate=1.0, state=state) == []
    # Recovery clears the latch; a fresh excursion fires again.
    assert _drive(tracker, clock, 7200, rps=10, err_rate=0.0, state=state) == []
    fired = _drive(tracker, clock, 900, rps=10, err_rate=1.0, state=state)
    assert len(fired) == 1
    assert tracker.peak >= 10.0


def test_burn_snapshot_covers_all_classes():
    clock = FakeClock()
    tracker = BurnRateTracker(objective=0.9, threshold=10.0, clock=clock)
    tracker.observe("a", 100, 100)
    tracker.observe("b", 50, 40)
    snap = tracker.snapshot()
    assert set(snap) == {"a", "b"} and tracker.classes() == ["a", "b"]


# ---------------------------------------------------------------------
# Robust z-scores + scrape parsing
# ---------------------------------------------------------------------
def test_zscores_need_a_pool():
    assert robust_zscores({"a": 9.0, "b": 1.0}, eps=0.1) == {
        "a": 0.0,
        "b": 0.0,
    }


def test_zscores_flag_the_outlier_even_with_zero_mad():
    # Identical pool + one outlier: MAD is 0, the eps floor keeps the
    # z finite while still flagging the victim.
    values = {"a": 10.0, "b": 10.0, "c": 10.0, "sick": 500.0}
    z = robust_zscores(values, eps=SIGNAL_EPS["itl_p99_ms"])
    assert z["a"] == z["b"] == z["c"] == 0.0
    assert z["sick"] == pytest.approx((500 - 10) / 5.0)
    # ...and sub-eps jitter stays unflagged.
    jitter = {"a": 10.0, "b": 10.0, "c": 10.0, "d": 10.4}
    assert all(
        abs(v) < 1.0
        for v in robust_zscores(
            jitter, eps=SIGNAL_EPS["itl_p99_ms"]
        ).values()
    )


def _scrape(itl=20.0, roofline=0.5, compiles=3, breaks=0, queries=100,
            host_hits=40, slo=None):
    lines = [
        "# HELP vllm:itl_p99_ms engine-merged p99",
        f"vllm:itl_p99_ms {itl}",
        f"vllm:step_roofline_frac {roofline}",
        f'vllm:xla_compiles_total{{kind="prefill"}} {compiles}',
        f"vllm:pipeline_breaks_total {breaks}",
        f"vllm:prefix_cache_queries_total {queries}",
        f'vllm:prefix_cache_hits_total{{tier="hbm"}} 50',
        f'vllm:prefix_cache_hits_total{{tier="host"}} {host_hits}',
    ]
    for cls, (req, good) in (slo or {}).items():
        lines.append(
            f'vllm:slo_requests_total{{model_name="m",slo_class="{cls}"}} {req}'
        )
        lines.append(
            f'vllm:goodput_requests_total{{model_name="m",slo_class="{cls}"}} {good}'
        )
    return "\n".join(lines) + "\n"


def test_parse_sentinel_samples():
    out = parse_sentinel_samples(
        _scrape(itl=33.5, roofline=0.62, compiles=7, breaks=2,
                queries=200, host_hits=80, slo={"rt": (100, 90)})
    )
    assert out["itl_p99_ms"] == 33.5
    assert out["roofline_frac"] == 0.62
    assert out["compiles"] == 7 and out["pipeline_breaks"] == 2
    assert out["prefix_queries"] == 200
    assert out["host_hits"] == 80  # host tier only, hbm excluded
    assert out["slo"] == {"rt": [100.0, 90.0]}


# ---------------------------------------------------------------------
# RouterSentinel end-to-end on synthetic probes
# ---------------------------------------------------------------------
class FakeManager:
    def __init__(self):
        self.recommended = []

    def note_recycle_recommendation(self, rid, **detail):
        self.recommended.append((rid, detail))


def _probe_all(sentinel, clock, itl_by_rid, **kw):
    for rid, itl in itl_by_rid.items():
        sentinel.note_probe(rid, _scrape(itl=itl, **kw))


def test_anomaly_scoring_singles_out_the_degraded_replica():
    clock = FakeClock()
    sentinel = RouterSentinel(
        anomaly_threshold=4.0, clock=clock, wall=FakeClock(2e9)
    )
    manager = FakeManager()
    sentinel.manager = manager
    healthy = {"r1": 20.0, "r2": 22.0, "r3": 19.0}
    _probe_all(sentinel, clock, healthy)
    assert sentinel.outliers() == set()
    # r2 degrades hard: ITL p99 jumps 20ms -> 400ms.
    clock.advance(5)
    _probe_all(sentinel, clock, {**healthy, "r2": 400.0})
    assert sentinel.outliers() == {"r2"}
    assert abs(sentinel.scores["r2"]["itl_p99_ms"]) >= 4.0
    degraded = [
        a for a in sentinel.alerts_snapshot()
        if a["kind"] == "replica_degraded"
    ]
    assert len(degraded) == 1 and degraded[0]["replica_id"] == "r2"
    assert degraded[0]["signal"] == "itl_p99_ms"
    assert manager.recommended and manager.recommended[0][0] == "r2"
    # Still degraded on the next probe: edge-triggered, no new alert.
    clock.advance(5)
    _probe_all(sentinel, clock, {**healthy, "r2": 400.0})
    assert len([
        a for a in sentinel.alerts_snapshot()
        if a["kind"] == "replica_degraded"
    ]) == 1
    # Recovery drops it out of the outlier set and re-arms the alert.
    clock.advance(5)
    _probe_all(sentinel, clock, healthy)
    assert sentinel.outliers() == set()
    # The timeline carries the typed alert event.
    kinds = [e["kind"] for e in sentinel.log.snapshot()]
    assert "alert_replica_degraded" in kinds


def test_rate_signals_come_from_probe_deltas():
    clock = FakeClock()
    sentinel = RouterSentinel(
        anomaly_threshold=4.0, clock=clock, wall=FakeClock(2e9)
    )
    sentinel.note_probe("r1", _scrape(compiles=10))
    clock.advance(10)
    sentinel.note_probe("r1", _scrape(compiles=30))
    assert sentinel.signals["r1"]["compile_rate"] == pytest.approx(2.0)
    assert sentinel.signals["r1"]["pipeline_break_rate"] == 0.0


def test_fleet_burn_sums_replica_counters():
    clock = FakeClock()
    sentinel = RouterSentinel(
        anomaly_threshold=4.0, clock=clock, wall=FakeClock(2e9)
    )
    sentinel.burn = BurnRateTracker(
        objective=0.99, threshold=10.0, clock=clock
    )
    sentinel.note_probe("r1", _scrape(slo={"rt": (100, 100)}))
    sentinel.note_probe("r2", _scrape(slo={"rt": (50, 50)}))
    # Fleet trail saw 150/150 — now r2 fails everything for 10 min.
    for _ in range(60):
        clock.advance(10)
        sentinel.note_probe("r1", _scrape(slo={"rt": (100, 100)}))
        sentinel.note_probe(
            "r2", _scrape(slo={"rt": (50 + 100, 50)})
        )
    burn_alerts = [
        a for a in sentinel.alerts_snapshot() if a["kind"] == "slo_burn"
    ]
    assert len(burn_alerts) == 1
    assert burn_alerts[0]["slo_class"] == "rt"
    assert sentinel.burn.peak >= 10.0


def test_state_and_breaker_hooks_alert():
    clock = FakeClock()
    sentinel = RouterSentinel(clock=clock, wall=FakeClock(2e9))
    sentinel.note_replica_state("r1", "healthy", "unreachable")
    sentinel.note_replica_state("r2", "stopping", "unreachable")  # expected
    sentinel.note_breaker("r3", "open")
    sentinel.note_breaker("r3", "half_open")
    kinds = [(a["kind"], a["replica_id"]) for a in sentinel.alerts_snapshot()]
    assert ("replica_unreachable", "r1") in kinds
    assert all(rid != "r2" for _, rid in kinds)
    assert ("replica_degraded", "r3") in kinds
    timeline = [e["kind"] for e in sentinel.log.snapshot()]
    assert timeline.count("breaker_transition") == 2
    assert timeline.count("replica_state") == 2


def test_forget_replica_clears_every_map():
    clock = FakeClock()
    sentinel = RouterSentinel(clock=clock, wall=FakeClock(2e9))
    sentinel.note_probe("r1", _scrape(slo={"rt": (10, 10)}))
    sentinel.forget_replica("r1")
    assert "r1" not in sentinel.signals
    assert "r1" not in sentinel.scores
    assert "r1" not in sentinel._prev
    assert "r1" not in sentinel._slo_counts


def test_signal_catalog_matches_eps():
    assert set(SIGNALS) == set(SIGNAL_EPS)


def test_snapshot_shape():
    sentinel = RouterSentinel(wall=FakeClock(2e9))
    sentinel.note_probe("r1", _scrape())
    snap = sentinel.snapshot()
    assert set(snap) == {
        "scores", "degraded", "burn", "burn_peak", "alerts", "events"
    }
    assert "r1" in snap["scores"]


# ---------------------------------------------------------------------------
# fleet_doctor: ranked diagnosis from the two sentinel endpoints.
# ---------------------------------------------------------------------------


def _doctor_payloads():
    """Synthetic /router/alerts + /router/timeline dumps: r2 degraded
    (huge itl z-score, one alert naming it), rt class burning."""
    alerts_payload = {
        "alerts": [
            {
                "ts_wall": 1000.0,
                "kind": "replica_degraded",
                "replica_id": "r2",
                "signal": "itl_p99_ms",
                "score": 97.9,
            },
            {
                "ts_wall": 1010.0,
                "kind": "slo_burn",
                "replica_id": None,
                "slo_class": "rt",
                "burn": {"5m": 12.0, "1h": 11.0},
            },
        ],
        "burn": {"rt": {"5m": 12.0, "1h": 11.0}, "batch": {"5m": 0.0, "1h": 0.0}},
        "burn_peak": 12.0,
        "anomaly_scores": {
            "r1": {"itl_p99_ms": -0.3, "waiting": 0.1},
            "r2": {"itl_p99_ms": 97.9, "waiting": 5.2},
            "r3": {"itl_p99_ms": 0.2, "waiting": -0.4},
        },
    }
    timeline_payload = {
        "events": [
            {"ts_wall": 990.0, "origin": "router", "source": "router",
             "kind": "breaker_transition", "replica_id": "r2",
             "attrs": {"state": "open"}, "seq": 1},
            {"ts_wall": 995.0, "origin": "r2", "source": "engine",
             "kind": "qos_shed", "attrs": {"count": 7}, "seq": 4},
            {"ts_wall": 1000.1, "origin": "router", "source": "router",
             "kind": "alert_replica_degraded", "replica_id": "r2", "seq": 2},
            {"ts_wall": 500.0, "origin": "r1", "source": "engine",
             "kind": "recovery_success", "seq": 9},
        ],
    }
    return alerts_payload, timeline_payload


def test_fleet_doctor_ranks_degraded_replica_first():
    from tools.fleet_doctor import diagnose, format_report

    alerts_payload, timeline_payload = _doctor_payloads()
    diag = diagnose(alerts_payload, timeline_payload)

    # r2 leads the ranking: named by an alert AND the worst |z|.
    assert diag["replicas"][0]["replica_id"] == "r2"
    assert diag["replicas"][0]["worst_signal"] == "itl_p99_ms"
    assert diag["replicas"][0]["flagged"] is True
    assert diag["flagged"] == ["r2"]
    # Only rt burns on every window; batch stays quiet.
    assert [cls for cls, _ in diag["burning_classes"]] == ["rt"]

    report = format_report(diag)
    assert "DEGRADED -> r2" in report
    assert "class rt" in report


def test_fleet_doctor_correlates_timeline_context():
    from tools.fleet_doctor import diagnose

    alerts_payload, timeline_payload = _doctor_payloads()
    diag = diagnose(alerts_payload, timeline_payload)

    degraded = next(
        f for f in diag["findings"]
        if f["alert"]["kind"] == "replica_degraded"
    )
    kinds = [ev["kind"] for ev in degraded["context"]]
    # Nearby causes surface; the alert's own mirror and far-away
    # events do not.
    assert "breaker_transition" in kinds
    assert "qos_shed" in kinds
    assert "alert_replica_degraded" not in kinds
    assert "recovery_success" not in kinds


def test_fleet_doctor_healthy_fleet_is_quiet():
    from tools.fleet_doctor import diagnose, format_report

    diag = diagnose(
        {"alerts": [], "burn": {"rt": {"5m": 0.5, "1h": 0.2}},
         "burn_peak": 0.5,
         "anomaly_scores": {"r1": {"waiting": 0.2}, "r2": {"waiting": -0.2}}},
        {"events": []},
    )
    assert diag["flagged"] == []
    assert diag["burning_classes"] == []
    report = format_report(diag)
    assert "diagnosis: healthy" in report
