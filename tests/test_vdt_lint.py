"""Unit tests for tools/vdt_lint (ISSUE 6): per-rule fixture corpus,
waiver and baseline round-trips, the registry↔README cross-check, and
the CLI contract (exit codes + rule id + file:line in the output).

Fixture protocol (tests/lint_fixtures/): `<rule>_bad.py` lines that
must be flagged carry a trailing `# EXPECT`; `<rule>_good.py` must
produce zero findings of that rule.  Fixtures are parsed, never
imported.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools.vdt_lint import (
    DEFAULT_BASELINE_PATH,
    PACKAGE_ROOT,
    REPO_ROOT,
    load_baseline,
    run_lint,
    save_baseline,
)

pytestmark = pytest.mark.lint

FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"

RULES = {
    "async-blocking": "VDT001",
    "lock-across-await": "VDT002",
    "unbounded-wait": "VDT003",
    "env-registry": "VDT004",
    "thread-leak": "VDT005",
    "silent-except": "VDT006",
    "orphan-span": "VDT007",
    "unbounded-queue": "VDT008",
    "bounded-cardinality": "VDT009",
    "resilient-http": "VDT010",
    "sentinel-emitter": "VDT011",
}

# Rules whose scope excludes distributed/ seed into a directory where
# they DO apply (VDT010 only checks the router's outbound data plane,
# VDT011 the engine/router timeline emitters).
SEED_DIRS = {
    "resilient_http_bad.py": "router",
    "resilient_http_good.py": "router",
    "sentinel_emitter_bad.py": "router",
    "sentinel_emitter_good.py": "router",
}


def _seed(tmp_path: Path, fixture: str, transform=None) -> tuple[Path, Path]:
    """Copy one fixture into a synthetic package tree under
    ``distributed/`` (so every rule's scope applies — the acceptance
    criterion seeds positives into distributed/), or the rule's own
    scope directory when distributed/ is outside it."""
    pkg = tmp_path / "pkg"
    subdir = SEED_DIRS.get(fixture, "distributed")
    (pkg / subdir).mkdir(parents=True, exist_ok=True)
    text = (FIXTURES / fixture).read_text()
    if transform is not None:
        text = transform(text)
    dest = pkg / subdir / fixture
    dest.write_text(text)
    return pkg, dest


def _expected_lines(path: Path) -> set[int]:
    return {
        i
        for i, line in enumerate(path.read_text().splitlines(), 1)
        if "# EXPECT" in line
    }


def _findings(pkg: Path, rule: str):
    report = run_lint([pkg], baseline=None)
    return [f for f in report.new if f.rule == rule]


# Finding count of the VDT003 positive corpus, derived from its EXPECT
# markers so growing the corpus can't silently break these tests.
N_UNBOUNDED = len(_expected_lines(FIXTURES / "unbounded_wait_bad.py"))


# ---- fixture corpus ----
@pytest.mark.parametrize("rule", sorted(RULES))
def test_positive_corpus_is_flagged(tmp_path, rule):
    fixture = f"{rule.replace('-', '_')}_bad.py"
    pkg, dest = _seed(tmp_path, fixture)
    findings = _findings(pkg, rule)
    assert {f.line for f in findings} == _expected_lines(dest), [
        f.render() for f in findings
    ]
    # One finding per marked line: a leaf that matches both the await
    # path and the sync-call path must be reported once, not twice.
    assert len(findings) == len(_expected_lines(dest)), [
        f.render() for f in findings
    ]
    assert all(f.code == RULES[rule] for f in findings)
    # The finding names the file so the CLI/gate output is actionable.
    assert all(f.path.endswith(fixture) for f in findings)


@pytest.mark.parametrize("rule", sorted(RULES))
def test_negative_corpus_is_clean(tmp_path, rule):
    fixture = f"{rule.replace('-', '_')}_good.py"
    pkg, _ = _seed(tmp_path, fixture)
    assert _findings(pkg, rule) == []


# ---- waivers ----
def _waive_expects(marker: str):
    def transform(text: str) -> str:
        return text.replace("# EXPECT", f"# vdt-lint: disable={marker}")

    return transform


@pytest.mark.parametrize(
    "marker", ["unbounded-wait", "VDT003", "all"]
)
def test_trailing_waiver_silences_by_rule_code_or_all(tmp_path, marker):
    pkg, _ = _seed(
        tmp_path, "unbounded_wait_bad.py", _waive_expects(marker)
    )
    report = run_lint([pkg], baseline=None)
    assert [f for f in report.new if f.rule == "unbounded-wait"] == []
    assert len(report.waived) == N_UNBOUNDED


def test_wrong_rule_waiver_does_not_silence(tmp_path):
    pkg, dest = _seed(
        tmp_path, "unbounded_wait_bad.py", _waive_expects("orphan-span")
    )
    findings = _findings(pkg, "unbounded-wait")
    assert len(findings) == N_UNBOUNDED


def test_full_line_waiver_applies_to_next_code_line(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "distributed").mkdir(parents=True)
    (pkg / "distributed" / "mod.py").write_text(
        "async def f(fut):\n"
        "    # vdt-lint: disable=unbounded-wait — bounded by the caller\n"
        "    await fut\n"
    )
    report = run_lint([pkg], baseline=None)
    assert report.new == []
    assert len(report.waived) == 1


@pytest.mark.parametrize(
    "comment",
    [
        # em-dash, ASCII hyphen, and plain-word justifications must all
        # leave the rule name intact (only the first word is the rule).
        "# vdt-lint: disable=unbounded-wait,thread-leak — already done",
        "# vdt-lint: disable=unbounded-wait - bounded by the caller",
        "# vdt-lint: disable=VDT003 because the caller bounds it",
    ],
)
def test_waiver_with_justification_text_parses(tmp_path, comment):
    pkg = tmp_path / "pkg"
    (pkg / "distributed").mkdir(parents=True)
    (pkg / "distributed" / "mod.py").write_text(
        f"async def f(fut):\n    await fut  {comment}\n"
    )
    report = run_lint([pkg], baseline=None)
    assert report.new == []
    assert len(report.waived) == 1


# ---- baseline ----
def test_baseline_round_trip(tmp_path):
    pkg, dest = _seed(tmp_path, "unbounded_wait_bad.py")
    first = run_lint([pkg], baseline=None)
    assert len(first.new) == N_UNBOUNDED
    baseline_file = tmp_path / "baseline.json"
    save_baseline(baseline_file, first.new)

    second = run_lint([pkg], baseline=load_baseline(baseline_file))
    assert second.new == []
    assert len(second.baselined) == N_UNBOUNDED

    # A NEW finding is not masked by the old baseline.
    dest.write_text(
        dest.read_text() + "\n\nasync def extra(fut):\n    await fut\n"
    )
    third = run_lint([pkg], baseline=load_baseline(baseline_file))
    assert len(third.new) == 1
    assert len(third.baselined) == N_UNBOUNDED


def test_committed_baseline_loads_and_is_versioned():
    entries = load_baseline(DEFAULT_BASELINE_PATH)
    assert isinstance(entries, list)


def test_parse_error_is_baselinable(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "distributed").mkdir(parents=True)
    (pkg / "distributed" / "vendored.py").write_text(
        "def f(:\n    pass\n"  # unparseable on purpose
    )
    first = run_lint([pkg], baseline=None)
    errors = [f for f in first.new if f.code == "VDT000"]
    assert len(errors) == 1

    # The escape hatch works: once baselined, the gate goes green.
    baseline_file = tmp_path / "baseline.json"
    save_baseline(baseline_file, first.new)
    second = run_lint([pkg], baseline=load_baseline(baseline_file))
    assert second.new == []
    assert len(second.baselined) == 1


# ---- env-registry project half ----
def test_registry_readme_cross_check(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "envs.py").write_text(
        "environment_variables = {\n"
        '    "VDT_DOCUMENTED": lambda: 1,\n'
        '    "VDT_MISSING": lambda: 2,\n'
        '    "VDT_DOC": lambda: 3,\n'  # prefix of VDT_DOCUMENTED
        "}\n"
    )
    (tmp_path / "README.md").write_text("docs mention VDT_DOCUMENTED only")
    report = run_lint([pkg], baseline=None)
    missing = [f for f in report.new if f.rule == "env-registry"]
    # VDT_DOC must not pass on a substring hit inside VDT_DOCUMENTED.
    assert sorted(f.message.split()[2] for f in missing) == [
        "VDT_DOC",
        "VDT_MISSING",
    ]


def test_real_registry_is_fully_documented():
    report = run_lint()  # committed (empty) baseline
    assert not any(f.rule == "env-registry" for f in report.new)


# ---- acceptance criterion: seeding a positive into the real tree ----
def test_seeded_positive_in_real_distributed_fails_gate(tmp_path):
    tree = tmp_path / "vllm_distributed_tpu"
    shutil.copytree(PACKAGE_ROOT, tree)
    seeded = tree / "distributed" / "seeded_bad.py"
    seeded.write_text((FIXTURES / "unbounded_wait_bad.py").read_text())
    report = run_lint([tree])  # committed baseline, real waivers active
    hits = [f for f in report.new if f.path.endswith("seeded_bad.py")]
    assert len(hits) == N_UNBOUNDED
    assert all(f.code == "VDT003" for f in hits)
    # Everything that was clean stays clean: only the seed is new.
    assert {f.path for f in report.new} == {hits[0].path}


def test_vdt003_scope_covers_qos_modules(tmp_path):
    """ISSUE 16: the QoS subsystem sits inside the deadline discipline
    — engine/qos.py via its own scope entry, router/qos.py via the
    router/ scope — while the rest of engine/ stays out of VDT003."""
    text = (FIXTURES / "unbounded_wait_bad.py").read_text()
    pkg = tmp_path / "pkg"
    for rel in ("engine/qos.py", "router/qos.py", "engine/not_qos.py"):
        dest = pkg / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_text(text)
    report = run_lint([pkg], baseline=None)
    hits = [f for f in report.new if f.rule == "unbounded-wait"]
    flagged = {f.path for f in hits}
    assert any(p.endswith("engine/qos.py") for p in flagged)
    assert any(p.endswith("router/qos.py") for p in flagged)
    # The scope entry is the one file, not all of engine/.
    assert not any(p.endswith("not_qos.py") for p in flagged)
    assert len(hits) == 2 * N_UNBOUNDED


def test_vdt003_scope_covers_router_persist(tmp_path):
    """ISSUE 17: the router WAL (router/persist.py) sits inside the
    deadline discipline via the router/ scope — its fsync/rotation
    waits are control-plane waits — and the shipped module itself is
    clean (no baseline entry hides a wedging wait)."""
    text = (FIXTURES / "unbounded_wait_bad.py").read_text()
    pkg = tmp_path / "pkg"
    dest = pkg / "router" / "persist.py"
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text(text)
    report = run_lint([pkg], baseline=None)
    hits = [f for f in report.new if f.rule == "unbounded-wait"]
    assert len(hits) == N_UNBOUNDED
    assert all(f.path.endswith("router/persist.py") for f in hits)
    # The real module passes the gate with no baseline at all: the WAL
    # never bought itself a waiver.
    real = run_lint(
        [PACKAGE_ROOT / "router" / "persist.py"], baseline=None
    )
    assert [f for f in real.new if f.rule == "unbounded-wait"] == []


# ---- CLI ----
def _run_cli(*argv: str):
    return subprocess.run(
        [sys.executable, "-m", "tools.vdt_lint", *argv],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )


def test_cli_exits_nonzero_with_rule_id_and_location(tmp_path):
    pkg, dest = _seed(tmp_path, "silent_except_bad.py")
    proc = _run_cli(str(pkg))
    assert proc.returncode == 1
    line = min(_expected_lines(dest))
    assert "VDT006" in proc.stdout
    assert f"silent_except_bad.py:{line}" in proc.stdout


def test_cli_json_format(tmp_path):
    pkg, _ = _seed(tmp_path, "thread_leak_bad.py")
    proc = _run_cli(str(pkg), "--format", "json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert {f["code"] for f in payload["new"]} == {"VDT005"}
    assert all(f["line"] for f in payload["new"])


def test_cli_clean_on_merged_tree():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for code in RULES.values():
        assert code in proc.stdout


def test_cli_broken_pipe_preserves_exit_code(tmp_path):
    # `vdt-lint | head` under pipefail: a consumer closing stdout
    # mid-report must not turn findings into exit 0.
    pkg, _ = _seed(tmp_path, "silent_except_bad.py")
    script = (
        "import sys\n"
        "from tools.vdt_lint.cli import main\n"
        "class ClosedPipe:\n"
        "    def write(self, s): raise BrokenPipeError\n"
        "    def flush(self): pass\n"
        "    def fileno(self): return 1\n"
        "sys.stdout = ClosedPipe()\n"
        f"sys.exit(main([{str(pkg)!r}]))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stderr
