"""Unit + single-host engine tests for supervised recovery (ISSUE 4):
restart-policy bounds, journal replay math and output splicing, the
_run_aux death race (aux futures must never hang), and the
stuck-engine-thread shutdown contract.  The multihost kill→recover
end-to-end lives in tests/test_fault_injection.py."""

import asyncio
import threading
import time

import pytest

from tests.utils import make_tiny_llama
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.distributed.failure import (
    PHASE_CONNECT,
    PHASE_HEARTBEAT,
    HostFailure,
)
from vllm_distributed_tpu.engine.async_llm import AsyncLLM, EngineDeadError
from vllm_distributed_tpu.engine.supervisor import (
    EngineSupervisor,
    JournalEntry,
    RestartPolicy,
)
from vllm_distributed_tpu.outputs import CompletionOutput, RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams


# ---------------------------------------------------------------------
# restart policy
# ---------------------------------------------------------------------
def test_backoff_schedule_is_exponential_and_capped():
    policy = RestartPolicy(
        max_restarts=5, backoff_base=1.0, backoff_cap=4.0, window=300
    )
    assert [policy.backoff(i) for i in range(5)] == [1, 2, 4, 4, 4]


def test_can_recover_bounds_and_window():
    policy = RestartPolicy(
        max_restarts=2, backoff_base=0.1, backoff_cap=1.0, window=5.0
    )
    sup = EngineSupervisor(None, policy=policy)
    failure = HostFailure(1, "('h', 1)", PHASE_HEARTBEAT, "missed")
    assert sup.can_recover(failure)
    # Non-control-plane deaths are never recovered.
    assert not sup.can_recover(None)
    # Attribution-free connect collapse: rebuild would just repeat it.
    assert not sup.can_recover(
        HostFailure(-1, "", PHASE_CONNECT, "0/3 agents")
    )
    # Budget spent within the window -> terminal.
    now = time.monotonic()
    sup._restart_times.extend([now, now])
    assert not sup.can_recover(failure)
    # Restarts older than the window are forgotten.
    sup._restart_times.clear()
    sup._restart_times.extend([now - 100.0, now - 99.0])
    assert sup.can_recover(failure)


def test_zero_max_restarts_disables_recovery():
    policy = RestartPolicy(
        max_restarts=0, backoff_base=1.0, backoff_cap=1.0, window=300
    )
    sup = EngineSupervisor(None, policy=policy)
    assert not sup.can_recover(
        HostFailure(1, "a", PHASE_HEARTBEAT, "missed")
    )


def test_retry_after_tracks_backoff():
    policy = RestartPolicy(
        max_restarts=3, backoff_base=0.2, backoff_cap=8.0, window=300
    )
    sup = EngineSupervisor(None, policy=policy)
    assert sup.retry_after_seconds() == 1  # never below 1s
    sup._current_backoff = 6.4
    assert sup.retry_after_seconds() == 7


# ---------------------------------------------------------------------
# request journal: replay as synthetic preemption-resume
# ---------------------------------------------------------------------
def _entry(**kw):
    defaults = dict(
        request_id="r",
        prompt=None,
        prompt_token_ids=[1, 2, 3],
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=10, min_tokens=5, ignore_eos=True
        ),
    )
    defaults.update(kw)
    return JournalEntry(**defaults)


def _out(req_id, token_ids, text="", prompt_ids=(1, 2, 3),
         finished=False):
    return RequestOutput(
        request_id=req_id,
        prompt=None,
        prompt_token_ids=list(prompt_ids),
        outputs=[
            CompletionOutput(
                index=0,
                text=text,
                token_ids=list(token_ids),
                finish_reason="length" if finished else None,
            )
        ],
        finished=finished,
    )


class _StubAsyncLLM:
    """Just enough AsyncLLM surface for EngineSupervisor._replay."""

    def __init__(self):
        self._journal = {}
        self.errors = []

    def _to_request_queue(self, request_id, e):
        self.errors.append((request_id, e))


def _tiny_engine(tmp_path, name="m"):
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine

    return LLMEngine(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / name)),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
            num_decode_steps=1,
        ).create_engine_config()
    )


def test_replay_restores_output_state_not_prompt(tmp_path):
    """Replay must preserve the prompt/output boundary: emitted tokens
    re-enter as OUTPUT tokens via the preemption-resume path, so
    penalties/stop/EOS/budget see the same request an uninterrupted
    engine would."""
    from vllm_distributed_tpu.engine.request import RequestStatus

    engine = _tiny_engine(tmp_path)
    try:
        stub = _StubAsyncLLM()
        sup = EngineSupervisor(
            stub,
            policy=RestartPolicy(
                max_restarts=3, backoff_base=0.1, backoff_cap=1.0,
                window=300,
            ),
        )
        entry = _entry()
        entry.admitted = True
        entry.observe(_out("r", [10, 11, 12, 13]))
        stub._journal["r"] = entry
        assert sup._replay(engine) == 1
        req = engine.scheduler.requests["r"]
        assert req.prompt_token_ids == [1, 2, 3]  # original boundary
        assert req.output_token_ids == [10, 11, 12, 13]
        assert req.resume_target == 7  # re-prefill prompt + emitted
        assert req.status == RequestStatus.PREEMPTED
        # Budget untouched: 10 max_tokens, 4 already produced.
        assert req.max_total_tokens == 13
        assert entry.sampling_params.max_tokens == 10  # original intact
        assert not stub.errors
    finally:
        engine.shutdown()


def test_replay_skips_finished_and_unadmitted(tmp_path):
    engine = _tiny_engine(tmp_path)
    try:
        stub = _StubAsyncLLM()
        sup = EngineSupervisor(
            stub,
            policy=RestartPolicy(
                max_restarts=3, backoff_base=0.1, backoff_cap=1.0,
                window=300,
            ),
        )
        done = _entry(request_id="done")
        done.admitted = True
        done.observe(_out("done", [10], finished=True))
        pending = _entry(request_id="pending")  # add still in intake
        stub._journal = {"done": done, "pending": pending}
        assert sup._replay(engine) == 0
        assert "done" not in engine.scheduler.requests
        assert "pending" not in engine.scheduler.requests
    finally:
        engine.shutdown()


def _drain_engine(engine, request_id):
    tokens = None
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.request_id == request_id:
                tokens = list(out.outputs[0].token_ids)
    return tokens


def test_replay_equivalence_with_penalties(tmp_path):
    """End-to-end determinism on the real tiny model WITH output-token
    penalties — the case that breaks if replay folds emitted tokens
    into the prompt: run greedy+penalties to completion (reference),
    run a twin engine only partway ("host died"), replay the journal
    onto a third engine, and require bit-identical final output."""
    sp = SamplingParams(
        temperature=0.0,
        max_tokens=10,
        ignore_eos=True,
        repetition_penalty=1.3,
        frequency_penalty=0.6,
        presence_penalty=0.2,
    )
    prompt = [1, 5, 9]

    ref_engine = _tiny_engine(tmp_path, "ref")
    try:
        ref_engine.add_request(
            "x", prompt_token_ids=list(prompt), sampling_params=sp.clone()
        )
        reference = _drain_engine(ref_engine, "x")
    finally:
        ref_engine.shutdown()
    assert reference is not None and len(reference) == 10

    # Interrupted run: stop after ~4 tokens, as if the host died there.
    cut_engine = _tiny_engine(tmp_path, "cut")
    emitted = []
    try:
        cut_engine.add_request(
            "x", prompt_token_ids=list(prompt), sampling_params=sp.clone()
        )
        while len(emitted) < 4:
            for out in cut_engine.step():
                emitted = list(out.outputs[0].token_ids)
    finally:
        cut_engine.shutdown()
    assert reference[: len(emitted)] == emitted

    new_engine = _tiny_engine(tmp_path, "new")
    try:
        stub = _StubAsyncLLM()
        sup = EngineSupervisor(
            stub,
            policy=RestartPolicy(
                max_restarts=3, backoff_base=0.1, backoff_cap=1.0,
                window=300,
            ),
        )
        entry = _entry(
            prompt_token_ids=list(prompt), sampling_params=sp.clone()
        )
        entry.request_id = "x"
        entry.admitted = True
        entry.emitted_token_ids = list(emitted)
        stub._journal["x"] = entry
        assert sup._replay(new_engine) == 1
        final = _drain_engine(new_engine, "x")
    finally:
        new_engine.shutdown()
    assert final == reference, (final, reference)


# ---------------------------------------------------------------------
# aux death race + shutdown contract (engine-level, uniproc)
# ---------------------------------------------------------------------
@pytest.fixture()
def engine(tmp_path):
    eng = AsyncLLM.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    yield eng
    eng.shutdown()


async def _consume(agen):
    out = None
    async for item in agen:
        out = item
    return out


def test_aux_after_death_raises_instead_of_hanging(engine):
    """Satellite regression: an aux call that reaches a dead engine —
    even one enqueued after the engine thread's post-death intake sweep
    already ran — must resolve with a typed error, never hang."""

    async def go():
        # Non-control-plane failure (no HostFailure): the supervisor
        # will not absorb it, so the death is terminal.
        engine.engine.executor._notify_failure(None)
        for _ in range(100):
            if engine._dead is not None:
                break
            await asyncio.sleep(0.05)
        assert engine._dead is not None
        engine._thread.join(timeout=5)
        assert not engine._thread.is_alive()
        # The engine thread (and its sweep) are gone; this aux can only
        # be resolved by the re-check / event-loop sweep.
        with pytest.raises(EngineDeadError):
            await asyncio.wait_for(engine.embed([1, 2, 3]), timeout=5)

    asyncio.new_event_loop().run_until_complete(go())


def test_fail_all_queues_sweeps_intake_aux(engine):
    """The event-loop sweep itself: an aux future sitting in the intake
    when _fail_all_queues runs is failed, not orphaned."""

    async def go():
        loop = asyncio.get_running_loop()
        engine._loop = loop
        # Kill the engine first so its own drain can't race us for the
        # queued aux — this models the exact satellite scenario: the
        # enqueue lands after the engine thread's post-death sweep.
        engine.engine.executor._notify_failure(None)
        while engine._dead is None:
            await asyncio.sleep(0.05)
        engine._thread.join(timeout=5)
        fut = loop.create_future()
        engine._intake.put(("aux", (lambda: None, (), fut)))
        engine._fail_all_queues(EngineDeadError("dead"))
        with pytest.raises(EngineDeadError):
            await asyncio.wait_for(fut, timeout=2)

    asyncio.new_event_loop().run_until_complete(go())


def test_clean_shutdown_resolves_queued_aux(engine):
    """An aux enqueued while the engine thread is mid-step when
    shutdown lands is failed by the clean-shutdown sweep."""

    async def go():
        gate = threading.Event()
        real_step = engine.engine.step

        def blocking_step():
            gate.wait(10)
            return real_step()

        engine.engine.step = blocking_step
        sp = SamplingParams(
            temperature=0.0, max_tokens=8, ignore_eos=True
        )
        task = asyncio.create_task(
            _consume(
                engine.generate(
                    "a", prompt_token_ids=[1, 2], sampling_params=sp
                )
            )
        )
        await asyncio.sleep(0.3)  # engine thread is inside blocking_step
        aux = asyncio.ensure_future(engine.embed([1, 2]))
        await asyncio.sleep(0.05)
        engine._shutdown = True
        engine._wake.set()
        gate.set()
        with pytest.raises(EngineDeadError, match="shutting down"):
            await asyncio.wait_for(aux, timeout=5)
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, EngineDeadError):
            pass

    asyncio.new_event_loop().run_until_complete(go())


def test_shutdown_stuck_thread_skips_device_teardown(tmp_path):
    """Satellite: a failed 5s join must not fall through into
    engine.shutdown() and race the stuck thread for the device — it
    logs the stuck phase and skips teardown."""
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=make_tiny_llama(str(tmp_path / "m")),
            skip_tokenizer_init=True,
            num_kv_pages=64,
            max_model_len=128,
        )
    )
    engine.SHUTDOWN_JOIN_SECONDS = 0.5  # test-sized join budget
    release = threading.Event()

    def wedged_step():
        release.wait(30)
        return []

    engine.engine.step = wedged_step
    teardowns = []
    real_engine_shutdown = engine.engine.shutdown
    engine.engine.shutdown = lambda: teardowns.append(1)

    async def go():
        sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
        task = asyncio.create_task(
            _consume(
                engine.generate(
                    "w", prompt_token_ids=[1, 2], sampling_params=sp
                )
            )
        )
        await asyncio.sleep(0.3)  # engine thread is now wedged in step
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    asyncio.new_event_loop().run_until_complete(go())
    t0 = time.monotonic()
    engine.shutdown()
    assert time.monotonic() - t0 < 5
    assert teardowns == []  # device teardown skipped
    assert engine._thread.is_alive()  # the wedge is real
    assert engine._phase == "step"  # the warning names this phase
    # Unwedge and clean up for real.
    release.set()
    engine._thread.join(timeout=5)
    real_engine_shutdown()
