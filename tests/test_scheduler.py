from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
from vllm_distributed_tpu.engine.request import Request, RequestStatus
from vllm_distributed_tpu.engine.scheduler import Scheduler
from vllm_distributed_tpu.sampling_params import SamplingParams


def make_scheduler(
    max_num_seqs=8,
    max_num_batched_tokens=64,
    num_pages=64,
    page_size=4,
    max_model_len=256,
    chunked=True,
):
    return Scheduler(
        SchedulerConfig(
            max_num_seqs=max_num_seqs,
            max_num_batched_tokens=max_num_batched_tokens,
            enable_chunked_prefill=chunked,
            max_model_len=max_model_len,
        ),
        CacheConfig(page_size=page_size),
        num_pages=num_pages,
    )


def make_req(rid, prompt_len=8, max_tokens=8):
    return Request(
        request_id=rid,
        prompt_token_ids=list(range(prompt_len)),
        sampling_params=SamplingParams(max_tokens=max_tokens),
        eos_token_id=None,
    )


def run_step(sched, sampled=None):
    out = sched.schedule()
    # Simulate workers: every running decode request samples token 7;
    # prefill-completing requests also sample.
    tokens = {}
    for req_id, n in out.num_scheduled_tokens.items():
        req = sched.requests[req_id]
        if req.num_computed_tokens + n >= req.num_prompt_tokens + req.num_output_tokens:
            tokens[req_id] = [7] if sampled is None else sampled.get(req_id, [7])
    finished = sched.update_from_output(out, tokens)
    return out, finished


def test_prefill_then_decode():
    sched = make_scheduler()
    req = make_req("a", prompt_len=8, max_tokens=2)
    sched.add_request(req)
    out, _ = run_step(sched)
    assert out.total_num_scheduled_tokens == 8
    assert len(out.new_requests) == 1
    assert req.num_computed_tokens == 8
    assert req.num_output_tokens == 1  # sampled when prefill completed
    # Decode step processes the sampled token and samples output #2 ->
    # max_tokens reached.
    out2, finished = run_step(sched)
    assert out2.num_scheduled_tokens["a"] == 1
    assert finished and finished[0].request_id == "a"
    assert req.status == RequestStatus.FINISHED_LENGTH
    assert not sched.has_unfinished_requests()


def test_chunked_prefill():
    sched = make_scheduler(max_num_batched_tokens=16)
    req = make_req("a", prompt_len=40, max_tokens=1)
    sched.add_request(req)
    out, _ = run_step(sched)
    assert out.num_scheduled_tokens["a"] == 16
    assert req.num_computed_tokens == 16
    out2, _ = run_step(sched)
    assert out2.num_scheduled_tokens["a"] == 16
    # Delta goes through cached_requests, not new_requests.
    assert len(out2.new_requests) == 0
    assert len(out2.cached_requests) == 1
    out3, _ = run_step(sched)
    assert out3.num_scheduled_tokens["a"] == 8
    assert req.num_output_tokens == 1


def test_batch_budget_shared():
    sched = make_scheduler(max_num_batched_tokens=16)
    for i in range(4):
        sched.add_request(make_req(f"r{i}", prompt_len=8, max_tokens=4))
    out, _ = run_step(sched)
    # Only two 8-token prefills fit.
    assert out.total_num_scheduled_tokens == 16
    assert set(out.num_scheduled_tokens) == {"r0", "r1"}
    out2, _ = run_step(sched)
    # r0/r1 decode (1 token each) + r2 prefill (8) + r3 partial (6).
    assert out2.num_scheduled_tokens["r0"] == 1
    assert out2.num_scheduled_tokens["r1"] == 1
    assert out2.num_scheduled_tokens["r2"] == 8
    assert out2.num_scheduled_tokens["r3"] == 6
    assert out2.total_num_scheduled_tokens == 16


def test_max_num_seqs_cap():
    sched = make_scheduler(max_num_seqs=2, max_num_batched_tokens=64)
    for i in range(4):
        sched.add_request(make_req(f"r{i}", prompt_len=4))
    out, _ = run_step(sched)
    assert len(out.new_requests) == 2


def test_preemption_and_resume():
    # 15 usable pages of 4 slots = 60 slots; each request peaks at
    # 12 + 20 = 32 tokens = 8 pages, so both together (16) exceed the pool.
    sched = make_scheduler(num_pages=16, page_size=4, max_num_batched_tokens=32)
    r1 = make_req("r1", prompt_len=12, max_tokens=20)
    r2 = make_req("r2", prompt_len=12, max_tokens=20)
    sched.add_request(r1)
    sched.add_request(r2)
    out, _ = run_step(sched)
    assert set(out.num_scheduled_tokens) == {"r1", "r2"}
    # Decode until pages run out: each req grows to 16 slots = 4 pages;
    # 4+4 > 7 so someone gets preempted eventually.
    preempted_seen = False
    for _ in range(40):
        out, _ = run_step(sched)
        if out.preempted_req_ids:
            preempted_seen = True
            break
    assert preempted_seen
    # The preempted request eventually resumes and finishes.
    for _ in range(80):
        out, finished = run_step(sched)
        if not sched.has_unfinished_requests():
            break
    assert not sched.has_unfinished_requests()
    assert r1.status.is_finished and r2.status.is_finished
    assert r1.num_output_tokens == 20
    assert r2.num_output_tokens == 20


def test_abort():
    sched = make_scheduler()
    req = make_req("a", prompt_len=8, max_tokens=100)
    sched.add_request(req)
    run_step(sched)
    sched.abort_request("a")
    assert not sched.has_unfinished_requests()
    out = sched.schedule()
    assert out.is_empty
    # Empty outputs are never dispatched, so the finish notice is HELD —
    # it must ride the next step that actually reaches the workers.
    assert out.finished_req_ids == []
    sched.add_request(make_req("b", prompt_len=4, max_tokens=1))
    out2 = sched.schedule()
    assert not out2.is_empty
    assert "a" in out2.finished_req_ids


def test_finished_ids_propagate_next_step():
    sched = make_scheduler()
    req = make_req("a", prompt_len=4, max_tokens=1)
    sched.add_request(req)
    run_step(sched)  # prefill + sample -> finished (max_tokens=1)
    assert req.status.is_finished
    # The next dispatched step carries the notice alongside its work.
    sched.add_request(make_req("b", prompt_len=4, max_tokens=2))
    out = sched.schedule()
    assert not out.is_empty
    assert "a" in out.finished_req_ids


def test_notices_held_across_empty_steps():
    """Finish notices survive any number of empty schedule() calls and
    arrive exactly once on the next dispatched (non-empty) step."""
    sched = make_scheduler()
    req = make_req("a", prompt_len=4, max_tokens=1)
    sched.add_request(req)
    run_step(sched)  # finishes (max_tokens=1)
    assert req.status.is_finished
    for _ in range(3):
        out = sched.schedule()
        assert out.is_empty
        assert out.finished_req_ids == []
    sched.add_request(make_req("b", prompt_len=4, max_tokens=2))
    out = sched.schedule()
    assert not out.is_empty
    assert out.finished_req_ids == ["a"]
    # Delivered once, not re-sent.
    sched.update_from_output(out, {"b": [7]})
    out2 = sched.schedule()
    assert "a" not in out2.finished_req_ids
