"""SLO/goodput accounting (ISSUE 12): log-bucket histogram merge
properties (associative, order-independent, bit-recomputable from raw
timelines), class-target parsing, cardinality bounding, EngineMetrics
integration, and the mocked 2-replica acceptance run — the router's
/router/slo fleet histograms must be bit-equal to recomputing directly
from both replicas' raw timelines."""

from __future__ import annotations

import asyncio
import json
import random

import pytest
from aiohttp.test_utils import TestClient, TestServer

from tests.mock_worker import MockUniProcExecutor
from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.slo import (
    DEFAULT_CLASS,
    OVERFLOW_CLASS,
    LogBucketHistogram,
    SloAccounting,
    bucket_index,
    bucket_value_ms,
    merge_class_views,
    parse_class_targets,
    sanitize_class,
)
from vllm_distributed_tpu.entrypoints.openai.api_server import (
    build_app,
    init_app_state,
    serve_http,
)
from vllm_distributed_tpu.metrics import EngineMetrics
from vllm_distributed_tpu.outputs import RequestMetrics
from vllm_distributed_tpu.router.app import RouterState, build_router_app
from vllm_distributed_tpu.testing import write_llama_config
from vllm_distributed_tpu.utils import get_open_port


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------
# log-bucket histogram units + merge properties
# ---------------------------------------------------------------------
def test_bucket_index_monotonic_and_invertible():
    values = [0.001, 0.01, 0.5, 1.0, 7.3, 100.0, 5000.0, 9e6]
    indices = [bucket_index(v) for v in values]
    assert indices == sorted(indices)
    for v, i in zip(values, indices):
        # The representative value sits within one octave of the input
        # (8 sub-buckets/octave ⇒ ~9% resolution; the mid-point rep
        # value is within ~±6%).
        assert 0.8 * v <= bucket_value_ms(i) <= 1.25 * v
    assert bucket_index(0.0) == 0
    assert bucket_index(-1.0) == 0
    assert bucket_index(float("nan")) == 0


def test_histogram_percentiles():
    h = LogBucketHistogram()
    for ms in (10.0,) * 90 + (1000.0,) * 10:
        h.observe_ms(ms)
    p50 = h.percentile_ms(0.5)
    p99 = h.percentile_ms(0.99)
    assert 8.0 < p50 < 12.0
    assert 800.0 < p99 < 1200.0
    assert LogBucketHistogram().percentile_ms(0.5) is None


def test_merge_is_associative_and_order_independent():
    rng = random.Random(12345)
    hists = []
    for _ in range(5):
        h = LogBucketHistogram()
        for _ in range(rng.randrange(1, 200)):
            h.observe_ms(rng.uniform(0.01, 60_000))
        hists.append(h)
    a, b, c, d, e = hists
    left = a.merge(b).merge(c).merge(d).merge(e)
    right = a.merge(b.merge(c.merge(d.merge(e))))
    shuffled = hists[:]
    rng.shuffle(shuffled)
    other = LogBucketHistogram()
    for h in shuffled:
        other = other.merge(h)
    assert left == right == other
    assert left.total == sum(h.total for h in hists)
    # Inputs untouched (merge returns a new histogram).
    assert a.total == hists[0].total


def test_split_and_merge_recomputes_exactly():
    """Observations split arbitrarily across k 'replicas' and merged in
    any order are bit-equal to one histogram over the union — the
    property the fleet merge contract rests on."""
    rng = random.Random(999)
    for _ in range(10):
        observations = [rng.uniform(0.05, 120_000) for _ in range(500)]
        whole = LogBucketHistogram()
        for v in observations:
            whole.observe_ms(v)
        k = rng.randrange(2, 6)
        parts = [LogBucketHistogram() for _ in range(k)]
        for v in observations:
            parts[rng.randrange(k)].observe_ms(v)
        rng.shuffle(parts)
        merged = LogBucketHistogram()
        for p in parts:
            merged = merged.merge(p)
        assert merged == whole
        # Wire round-trip preserves bit-equality too.
        assert LogBucketHistogram.from_dict(merged.to_dict()) == whole


# ---------------------------------------------------------------------
# targets, class hygiene, accounting units
# ---------------------------------------------------------------------
def test_parse_class_targets():
    assert parse_class_targets("") == {}
    assert parse_class_targets("500") == {"default": 500.0}
    assert parse_class_targets("default:500,chat:200.5,batch:5000") == {
        "default": 500.0,
        "chat": 200.5,
        "batch": 5000.0,
    }
    # Unparseable/disabled entries are dropped, not fatal.
    assert parse_class_targets("chat:nope,batch:0,ok:10") == {"ok": 10.0}


def test_sanitize_class_bounds_hostile_names():
    assert sanitize_class(None) == DEFAULT_CLASS
    assert sanitize_class("") == DEFAULT_CLASS
    assert sanitize_class("chat-v2.1_x") == "chat-v2.1_x"
    assert sanitize_class('inj"}bad{label="x') == "injbadlabelx"
    assert len(sanitize_class("x" * 500)) <= 48
    assert sanitize_class("{}\"'\n") == DEFAULT_CLASS


def test_class_cardinality_is_capped():
    acc = SloAccounting(
        ttft_targets={}, itl_targets={}, max_classes=4
    )
    resolved = {acc.resolve(f"class{i}") for i in range(20)}
    assert len(resolved) <= 5  # 4 distinct + the overflow class
    assert OVERFLOW_CLASS in resolved


def test_attainment_and_goodput():
    acc = SloAccounting(
        ttft_targets={"chat": 100.0}, itl_targets={"chat": 10.0}
    )
    cls = acc.resolve("chat")
    # Within both targets, completed -> goodput.
    assert acc.record_finished(cls, 0.05, 0.005, {}, "stop") == (
        True, True, True,
    )
    # TTFT blown.
    assert acc.record_finished(cls, 0.5, 0.005, {}, "stop") == (
        False, True, False,
    )
    # ITL blown.
    assert acc.record_finished(cls, 0.05, 0.5, {}, "length") == (
        True, False, False,
    )
    # Within targets but shed: attained, NOT goodput.
    assert acc.record_finished(cls, 0.05, 0.005, {}, "timeout") == (
        True, True, False,
    )
    # Single-token request: no ITL intervals -> vacuously attained.
    assert acc.record_finished(cls, 0.05, None, None, "stop") == (
        True, True, True,
    )
    # Untargeted class: trivially attained.
    other = acc.resolve("bulk")
    assert acc.record_finished(other, 99.0, 99.0, {}, "stop") == (
        True, True, True,
    )
    snap = acc.snapshot()
    chat = snap["classes"]["chat"]
    assert chat["requests"] == 5
    assert chat["goodput"] == 2
    assert chat["ttft_attained"] == 4
    assert chat["itl_attained"] == 4
    assert len(snap["timelines"]) == 6


def test_engine_metrics_slo_families(monkeypatch):
    monkeypatch.setenv("VDT_SLO_TTFT_MS", "chat:200")
    monkeypatch.setenv("VDT_SLO_ITL_MS", "chat:50")
    m = EngineMetrics("m", enabled=True)
    rm = RequestMetrics(arrival_time=100.0, arrival_time_mono=100.0)
    rm.slo_class = "chat"
    rm.first_token_time_mono = 100.1  # TTFT 100ms <= 200ms
    m.record_new_tokens(rm, 1, now=100.1)
    m.record_new_tokens(rm, 4, now=100.2)  # ITL 25ms <= 50ms
    rm.finished_time_mono = 100.5
    m.record_finished(rm, "stop")
    text = m.render().decode()
    assert 'vllm:slo_requests_total{model_name="m",slo_class="chat"} 1.0' in text
    assert 'vllm:goodput_requests_total{model_name="m",slo_class="chat"} 1.0' in text
    assert 'vllm:slo_ttft_attained_total{model_name="m",slo_class="chat"} 1.0' in text
    assert 'vllm:slo_itl_attained_total{model_name="m",slo_class="chat"} 1.0' in text
    assert 'vllm:slo_ttft_ms_count{model_name="m",slo_class="chat"} 1.0' in text
    assert 'vllm:slo_itl_ms_count{model_name="m",slo_class="chat"} 4.0' in text
    snap = m.slo_snapshot()
    chat = snap["classes"]["chat"]
    assert chat["ttft_hist"]["total"] == 1
    assert chat["itl_hist"]["total"] == 4
    assert chat["ttft_target_ms"] == 200.0
    # The request's own timeline carries its ITL bucket tally, and
    # recomputing the class histogram from it is bit-exact.
    tl = snap["timelines"][0]
    assert tl["slo_class"] == "chat" and tl["goodput"] is True
    recomputed = LogBucketHistogram(
        {int(k): v for k, v in tl["itl_buckets"].items()}
    )
    assert recomputed == LogBucketHistogram.from_dict(chat["itl_hist"])


def test_merge_class_views_sums_counters():
    va = {
        "classes": {
            "chat": {
                "requests": 3, "goodput": 2, "ttft_attained": 3,
                "itl_attained": 2, "ttft_target_ms": 100.0,
                "ttft_hist": {"counts": {"10": 3}, "total": 3},
                "itl_hist": {"counts": {"5": 6}, "total": 6},
            }
        }
    }
    vb = {
        "classes": {
            "chat": {
                "requests": 1, "goodput": 1, "ttft_attained": 1,
                "itl_attained": 1,
                "ttft_hist": {"counts": {"10": 1, "12": 0}, "total": 1},
                "itl_hist": {"counts": {"7": 2}, "total": 2},
            },
            "batch": {
                "requests": 2, "goodput": 2, "ttft_attained": 2,
                "itl_attained": 2,
                "ttft_hist": {"counts": {}, "total": 0},
                "itl_hist": {"counts": {}, "total": 0},
            },
        }
    }
    merged = merge_class_views([va, vb])
    assert merged["chat"]["requests"] == 4
    assert merged["chat"]["goodput"] == 3
    assert merged["chat"]["goodput_ratio"] == 0.75
    assert merged["chat"]["ttft_hist"]["counts"] == {"10": 4}
    assert merged["chat"]["itl_hist"]["counts"] == {"5": 6, "7": 2}
    assert merged["chat"]["ttft_target_ms"] == 100.0
    assert merged["batch"]["requests"] == 2
    # Order independence of the fold.
    assert merge_class_views([vb, va])["chat"] == merged["chat"]


# ---------------------------------------------------------------------
# slo_report rendering
# ---------------------------------------------------------------------
def test_slo_report_renders_both_shapes(tmp_path, capsys):
    from tools.slo_report import class_rows, main

    replica_view = {
        "classes": {
            "chat": {
                "requests": 4, "goodput": 3, "ttft_attained": 4,
                "itl_attained": 3, "ttft_target_ms": 200.0,
                "itl_target_ms": 50.0,
                "ttft_hist": {"counts": {"100": 4}, "total": 4},
                "itl_hist": {"counts": {"80": 12}, "total": 12},
            }
        }
    }
    rows = class_rows(replica_view)
    assert rows[0]["class"] == "chat"
    assert rows[0]["goodput_ratio"] == 0.75
    assert rows[0]["ttft_p99_ms"] is not None
    dump = tmp_path / "slo.json"
    dump.write_text(json.dumps(replica_view))
    assert main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "chat" in out and "75.0%" in out


# ---------------------------------------------------------------------
# mocked 2-replica acceptance: router fleet merge is bit-equal to
# recomputing from both replicas' raw timelines
# ---------------------------------------------------------------------
def _mk_engine(model_dir: str) -> AsyncLLM:
    return AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            num_kv_pages=64,
            max_model_len=128,
            num_decode_steps=1,
            distributed_executor_backend=MockUniProcExecutor,
        )
    )


@pytest.mark.router
def test_router_fleet_slo_bit_equal(tmp_path, monkeypatch):
    monkeypatch.setenv("VDT_MOCK_TOKEN_SEQ", "1")
    monkeypatch.setenv("VDT_SLO_TTFT_MS", "default:10000,chat:10000")
    monkeypatch.setenv("VDT_SLO_ITL_MS", "default:10000,chat:10000")
    model_dir = write_llama_config(str(tmp_path / "m"))

    async def go():
        engines, runners, urls = [], [], []
        client = None
        try:
            for i in range(2):
                engine = _mk_engine(model_dir)
                state = init_app_state(
                    engine,
                    served_model_name="slo",
                    replica_id=f"replica-{i}",
                )
                port = get_open_port()
                runner = await serve_http(
                    build_app(state), host="127.0.0.1", port=port
                )
                engines.append(engine)
                runners.append(runner)
                urls.append(f"http://127.0.0.1:{port}")
            state = RouterState(
                urls,
                policy="round_robin",
                health_interval=0.5,
                connect_timeout=2.0,
                read_timeout=20.0,
            )
            server = TestServer(build_router_app(state))
            client = TestClient(server)
            await client.start_server()

            for i in range(8):
                body = {
                    "prompt": [1, 2, 3, i + 1],
                    "max_tokens": 5,
                    "temperature": 0.0,
                    "ignore_eos": True,
                    "slo_class": "chat" if i % 2 else "default",
                }
                r = await client.post("/v1/completions", json=body)
                assert r.status == 200, await r.text()
                await r.read()

            # Both replicas served (round robin) — the merge is real.
            per_replica = []
            import aiohttp

            async with aiohttp.ClientSession() as s:
                for u in urls:
                    async with s.get(f"{u}/slo") as r:
                        assert r.status == 200
                        per_replica.append(await r.json())
            assert all(
                v["classes"].get("chat", {}).get("requests", 0) > 0
                or v["classes"].get("default", {}).get("requests", 0) > 0
                for v in per_replica
            )

            fleet = await (await client.get("/router/slo")).json()
            assert sorted(fleet["replicas_merged"]) == [
                "replica-0", "replica-1",
            ]

            # Recompute the fleet histograms DIRECTLY from the raw
            # per-request timelines of both replicas; the router's
            # merged histograms must be bit-equal.
            recomputed: dict[str, dict[str, LogBucketHistogram]] = {}
            counts: dict[str, dict[str, int]] = {}
            for view in per_replica:
                for tl in view["timelines"]:
                    cls = tl["slo_class"]
                    h = recomputed.setdefault(
                        cls,
                        {
                            "ttft": LogBucketHistogram(),
                            "itl": LogBucketHistogram(),
                        },
                    )
                    c = counts.setdefault(
                        cls, {"requests": 0, "goodput": 0}
                    )
                    c["requests"] += 1
                    c["goodput"] += bool(tl["goodput"])
                    if tl["ttft_ms"] is not None:
                        h["ttft"].observe_ms(tl["ttft_ms"])
                    for idx, n in (tl["itl_buckets"] or {}).items():
                        h["itl"].observe_bucket(int(idx), n)
            assert set(fleet["classes"]) == {"chat", "default"}
            for cls, d in fleet["classes"].items():
                assert (
                    LogBucketHistogram.from_dict(d["ttft_hist"])
                    == recomputed[cls]["ttft"]
                ), cls
                assert (
                    LogBucketHistogram.from_dict(d["itl_hist"])
                    == recomputed[cls]["itl"]
                ), cls
                assert d["requests"] == counts[cls]["requests"]
                assert d["goodput"] == counts[cls]["goodput"]
                # Generous targets: everything completed is goodput.
                assert d["goodput_ratio"] == 1.0

            # The router /metrics view: every new per-class histogram
            # family appears EXACTLY once (one TYPE line) with both
            # replica labels under it, and the fleet gauges render.
            text = await (await client.get("/metrics")).text()
            for family in ("vllm:slo_ttft_ms", "vllm:slo_itl_ms"):
                assert text.count(f"# TYPE {family} histogram") == 1
                for rid in ("replica-0", "replica-1"):
                    assert f'{family}_bucket{{' in text
                    assert f'replica="{rid}"' in text
            assert "vdt_router:fleet_goodput_ratio" in text
            assert "vdt_router:fleet_ttft_p99_ms" in text
        finally:
            if client is not None:
                await client.close()
            for runner in runners:
                try:
                    await runner.cleanup()
                except Exception:  # noqa: BLE001 — teardown
                    pass
            for engine in engines:
                engine.shutdown()

    _run(go())
