"""Pallas ragged paged-attention kernel — the TPU hot path.

The TPU-native replacement for the CUDA PagedAttention/FlashAttention
kernels the reference inherits from the vLLM image (SURVEY.md §2.2 and
BASELINE.json north_star: "PagedAttention is a Pallas kernel").  One
kernel serves both decode (1 query token/seq) and chunked prefill
(many): queries are grouped per sequence and attention runs flash-style
(online softmax) over the sequence's paged KV.

Design (tuned for DMA efficiency + VMEM budget on v5e):
- grid = (S, q_blocks, kv_blocks): kv blocks iterate innermost so the
  flash state (m, l, acc) lives in VMEM scratch across kv steps; q
  blocks tile long prefill chunks so scratch fits VMEM.
- All KV heads are processed inside one program, so each page is ONE
  contiguous [page_size, Hkv, D] DMA from HBM instead of per-head
  slivers.  KV pool layout is slot-major ``[P, page, Hkv, D]``
  (ops/attention.py): `.at[page]` is a major-dim slice, and the same
  layout lets the in-place Pallas writer (kv_update.py) target single
  token rows.
- Double buffering: program (s, qb, b) waits for the block prefetched
  by (s, qb, b-1) and prefetches block b+1, overlapping DMA + compute.
- Causal skip: kv blocks entirely above the q block's last position are
  skipped (no DMA, no compute) — half the work on prefill.
- Queries are pre-grouped to [S, Hkv, maxq × G, D] (GQA groups share
  their KV head's program); q-block rows ≥ 8 (f32 sublane tile).

Numerics: scores/softmax/accumulation in float32 regardless of cache
dtype; output cast back to q.dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu.ops.attention import AttentionMetadata
from vllm_distributed_tpu.utils import cdiv, next_power_of_2

import os
SKIP_COMPUTE = os.environ.get("ABL_SKIP_COMPUTE") == "1"
SKIP_DMA = os.environ.get("ABL_SKIP_DMA") == "1"


_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128
# Per-buffer VMEM budget for each of K and V (bytes).
_KV_BUF_BYTES = 512 * 1024
# Budget for the f32 flash state (m, l, acc across all heads).
_STATE_BYTES = 6 * 1024 * 1024


def _kernel(
    # scalar prefetch
    block_tables_ref,  # [S, max_pages] int32 (SMEM)
    seq_lens_ref,  # [S] int32
    chunk_starts_ref,  # [S] int32
    # inputs
    q_ref,  # [1, Hkv, QROWS, D] VMEM block
    k_pages_ref,  # [P, page, Hkv, D] in HBM/ANY
    v_pages_ref,
    # outputs
    out_ref,  # [1, Hkv, QROWS, D] VMEM block
    # scratch
    k_vmem,  # [2, BLK, Hkv, D]
    v_vmem,  # [2, BLK, Hkv, D]
    m_scr,  # [Hkv, QROWS, LANES] f32
    l_scr,  # [Hkv, QROWS, LANES] f32
    acc_scr,  # [Hkv, QROWS, D] f32
    sems,  # DMA sems [2, 2]  (k/v × buffer)
    *,
    scale: float,
    soft_cap: float | None,
    page_size: int,
    pages_per_blk: int,
    group_size: int,
    num_kv_heads: int,
    q_tokens_per_blk: int,
    cross_seq_prefetch: bool,
):
    s = pl.program_id(0)
    qb = pl.program_id(1)
    kvb = pl.program_id(2)
    num_seqs = pl.num_programs(0)
    num_kvb = pl.num_programs(2)
    blk = pages_per_blk * page_size
    seq_len = seq_lens_ref[s]
    chunk_start = chunk_starts_ref[s]
    # Last absolute position any query row of this q block can hold.
    q_pos_max = chunk_start + (qb + 1) * q_tokens_per_blk - 1

    def is_active(b):
        return (b * blk < seq_len) & (b * blk <= q_pos_max)

    def block_dma(block_idx, buf, seq=None):
        """One DMA per page, each covering every head: [page, Hkv, D]."""
        seq = s if seq is None else seq
        copies = []
        if SKIP_DMA:
            return copies
        for i in range(pages_per_blk):
            page = block_tables_ref[seq, block_idx * pages_per_blk + i]
            copies.append(
                pltpu.make_async_copy(
                    k_pages_ref.at[page],
                    k_vmem.at[buf, pl.ds(i * page_size, page_size)],
                    sems.at[0, buf],
                )
            )
            copies.append(
                pltpu.make_async_copy(
                    v_pages_ref.at[page],
                    v_vmem.at[buf, pl.ds(i * page_size, page_size)],
                    sems.at[1, buf],
                )
            )
        return copies

    @pl.when(kvb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _MASK)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        # First block of this (seq, q-block): start its DMA here unless a
        # previous grid slice already prefetched it (cross-seq mode).
        first_cond = (
            (seq_len > 0) & (s == 0)
            if cross_seq_prefetch
            else (seq_len > 0)
        )

        @pl.when(first_cond)
        def _():
            for cp in block_dma(0, 0):
                cp.start()

    block_start = kvb * blk
    active = is_active(kvb) & (seq_len > 0)

    # Prefetch the next block while this one computes.
    @pl.when(active & (kvb + 1 < num_kvb) & is_active(kvb + 1))
    def _prefetch():
        for cp in block_dma(kvb + 1, (kvb + 1) % 2):
            cp.start()

    @pl.when(active)
    def _compute():
        buf = kvb % 2
        for cp in block_dma(kvb, buf):
            cp.wait()
        if SKIP_COMPUTE:
            return
        rows = acc_scr.shape[1]
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0)
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        q_pos = (
            chunk_start
            + qb * q_tokens_per_blk
            + row_ids // group_size
        )
        c_pos = block_start + col_ids
        mask = (c_pos <= q_pos) & (c_pos < seq_len)

        for h in range(num_kv_heads):
            q = q_ref[0, h].astype(jnp.float32)  # [QROWS, D]
            k = k_vmem[buf, :, h, :].astype(jnp.float32)  # [BLK, D]
            v = v_vmem[buf, :, h, :].astype(jnp.float32)
            scores = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [QROWS, BLK]
            if soft_cap is not None:
                scores = jnp.tanh(scores / soft_cap) * soft_cap
            scores = jnp.where(mask, scores, _MASK)

            m_prev = m_scr[h, :, 0:1]
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)
            p = jnp.where(mask, p, 0.0)
            l_new = l_scr[h, :, 0:1] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_scr[h] = acc_scr[h] * alpha + pv
            m_scr[h] = jnp.broadcast_to(m_new, m_scr[h].shape)
            l_scr[h] = jnp.broadcast_to(l_new, l_scr[h].shape)

    if cross_seq_prefetch:
        # Decode-shape fast path (one q block, >=2 kv blocks): start the
        # NEXT sequence's block-0 DMA during this sequence's last kv
        # step, hiding the per-sequence first-block latency that the
        # sequential grid otherwise exposes.  Buffer-safety invariant:
        # this block is emitted AFTER _compute in program order, so when
        # the last active block index is even (buf 0 read in THIS step)
        # the overwrite is ordered behind the read; num_qb == 1
        # guarantees no later q block re-reads buf 0 for this sequence.
        # Do NOT hoist above _compute.
        @pl.when((kvb == num_kvb - 1) & (s + 1 < num_seqs))
        def _prefetch_next_seq():
            @pl.when(seq_lens_ref[s + 1] > 0)
            def _():
                for cp in block_dma(0, 0, seq=s + 1):
                    cp.start()

    @pl.when(kvb == num_kvb - 1)
    def _finalize():
        for h in range(num_kv_heads):
            denom = jnp.maximum(l_scr[h, :, 0:1], 1e-30)
            out_ref[0, h] = (acc_scr[h] / denom).astype(out_ref.dtype)


def _pow2_floor(x: int) -> int:
    return 1 << (max(x, 1).bit_length() - 1)


def paged_attention(
    q: jax.Array,  # [T, Hq, D] flat
    k_pages: jax.Array,  # [P, page, Hkv, D]
    v_pages: jax.Array,
    metadata: AttentionMetadata,
    *,
    scale: float,
    soft_cap: float | None = None,
    max_q: int = 1,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for paged_attention_reference (same contract), running the
    flash kernel.  `max_q` is the static per-sequence query bound for this
    step (the runner's padded max chunk length)."""
    t, hq, d_q = q.shape
    p_total, page_size, hkv, d = k_pages.shape
    s, max_pages = metadata.block_tables.shape
    g = hq // hkv
    if d > d_q:
        # Lane-padded pool (see write_kv_pages): pad q to match; padded
        # lanes are zero on both sides so scores/outputs are unchanged.
        q = jnp.pad(q, [(0, 0), (0, 0), (0, d - d_q)])

    # maxq padded so total rows are at least the 8-row sublane tile.
    maxq = next_power_of_2(max_q)
    while maxq * g < 8:
        maxq *= 2

    # Tile q into blocks whose f32 flash state fits the VMEM budget.
    state_per_row = hkv * (2 * _LANES + d) * 4
    qrows_cap = max(_pow2_floor(_STATE_BYTES // state_per_row), 8)
    mq_blk = maxq
    while mq_blk * g > qrows_cap and (mq_blk // 2) * g >= 8:
        mq_blk //= 2
    num_qb = maxq // mq_blk
    qrows = mq_blk * g

    # ---- group flat queries per sequence ----
    # Padding tokens carry q_seq_ids == S (one past the end); route their
    # scatter to an out-of-bounds column so it is DROPPED instead of
    # clobbering a real row (scatter drops OOB updates under jit).
    valid = metadata.q_seq_ids < s
    seq_idx = jnp.minimum(metadata.q_seq_ids, s - 1)
    tok_in_chunk = metadata.q_positions - metadata.chunk_starts[seq_idx]
    col = jnp.where(valid, tok_in_chunk, maxq)
    q_grouped = jnp.zeros((s, maxq, hq, d), q.dtype)
    q_grouped = q_grouped.at[seq_idx, col].set(q, mode="drop")
    # [S, maxq, Hkv, G, D] -> [S, Hkv, maxq*G, D], row r = m*G + g.
    q_grouped = q_grouped.reshape(s, maxq, hkv, g, d).transpose(0, 2, 1, 3, 4)
    q_grouped = q_grouped.reshape(s, hkv, maxq * g, d)

    # ---- kv blocking: size blocks to the VMEM budget ----
    kv_bytes_per_token = hkv * d * jnp.dtype(k_pages.dtype).itemsize
    blk_tokens = max(_KV_BUF_BYTES // kv_bytes_per_token, page_size)
    blk_tokens = min(_pow2_floor(blk_tokens), max_pages * page_size)
    pages_per_blk = max(blk_tokens // page_size, 1)
    num_kvb = cdiv(max_pages, pages_per_blk)
    blk = pages_per_blk * page_size
    if max_pages % pages_per_blk:
        # Pad the table so block_dma never reads a page id out of bounds
        # (padding pages are id 0 — a real page, masked out of scores).
        pad = pages_per_blk - max_pages % pages_per_blk
        block_tables = jnp.pad(metadata.block_tables, ((0, 0), (0, pad)))
    else:
        block_tables = metadata.block_tables

    grid = (s, num_qb, num_kvb)
    kernel = functools.partial(
        _kernel,
        scale=scale,
        soft_cap=soft_cap,
        page_size=page_size,
        pages_per_blk=pages_per_blk,
        group_size=g,
        num_kv_heads=hkv,
        q_tokens_per_blk=mq_blk,
        # Cross-seq prefetch relies on intra-step ordering (the prefetch
        # is emitted after _compute) plus single-q-block grids; >= 2 kv
        # blocks so the same step never waits on the buffer it refills.
        cross_seq_prefetch=(num_qb == 1 and num_kvb >= 2),
    )
    out_grouped = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, hkv, qrows, d),
                    # Scalar-prefetch refs ride along after grid indices.
                    lambda s_, qb_, b_, *refs: (s_, 0, qb_, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, hkv, qrows, d),
                lambda s_, qb_, b_, *refs: (s_, 0, qb_, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((2, blk, hkv, d), k_pages.dtype),
                pltpu.VMEM((2, blk, hkv, d), v_pages.dtype),
                pltpu.VMEM((hkv, qrows, _LANES), jnp.float32),
                pltpu.VMEM((hkv, qrows, _LANES), jnp.float32),
                pltpu.VMEM((hkv, qrows, d), jnp.float32),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s, hkv, maxq * g, d), q.dtype),
        interpret=interpret,
    )(
        block_tables,
        metadata.seq_lens,
        metadata.chunk_starts,
        q_grouped,
        k_pages,
        v_pages,
    )

    # ---- back to the flat layout ----
    out = out_grouped.reshape(s, hkv, maxq, g, d).transpose(0, 2, 1, 3, 4)
    out = out.reshape(s, maxq, hq, d)
    return out[seq_idx, jnp.clip(tok_in_chunk, 0, maxq - 1), :, :d_q]


paged_attention.needs_max_q = True


def paged_attention_cpu(*args, **kwargs):
    """Interpret-mode entry for CPU tests."""
    return paged_attention(*args, interpret=True, **kwargs)


paged_attention_cpu.needs_max_q = True
