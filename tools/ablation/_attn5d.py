"""Pallas ragged paged-attention kernel — the TPU hot path.

The TPU-native replacement for the CUDA PagedAttention/FlashAttention
kernels the reference inherits from the vLLM image (SURVEY.md §2.2 and
BASELINE.json north_star: "PagedAttention is a Pallas kernel").  One
kernel serves both decode (1 query token/seq) and chunked prefill
(many): queries are grouped per sequence and attention runs flash-style
(online softmax) over the sequence's paged KV.

Design (v2 — rebuilt after the round-3 on-chip ablation, PERF.md):
the round-3 kernel was COMPUTE-bound, not DMA-bound (DMA-only ablation
ran at 779 GB/s while the full kernel ran at ~250 GB/s): decode issued
8 separate per-head op chains on 8-row tiles, so the VPU (softmax, mask,
state updates) and tiny 6%-utilized MXU calls dominated while the DMA
queues idled.  v2 changes, in order of impact:

- **Folded-head block-diagonal compute.**  KV heads are processed in
  fold groups of F heads per matmul: queries are laid out
  block-diagonally as [rows = F*G*mq, F*D] so ONE dot per kv block
  computes F heads' scores ([rows, BLK]), and the whole softmax/state
  chain runs on one wide tile instead of per-head slivers.  For decode
  (mq=1) F grows to put all heads in a single chain (1B: 32 rows, 8
  heads, one chain vs 8); for prefill rows are already plentiful and F
  stays at the 128-lane alignment minimum.  The off-diagonal lanes are
  zeros, so scores are exact; outputs are extracted by diagonal einsum
  outside the kernel.
- **Combined flat KV pool** ``[P, 2, page, HD]`` (ops/attention.py):
  one descriptor per page covers K and V for all heads, and a 64-wide
  head dim is stored unpadded inside HD (the r3 layout padded each head
  to 128 lanes — 2× wasted bytes on Llama-1B-class models).
- **Globally rotating triple buffer.**  Buffer index = (number of
  active blocks completed so far) % 3, tracked in SMEM — never resets
  per sequence, so the cross-sequence block-0 prefetch can never target
  the buffer the current (or previous) step reads.  This replaces the
  r3 order-dependent safety argument (ADVICE r3 medium) with a
  structural invariant.
- Causal skip: kv blocks entirely above the q block's last position are
  skipped (no DMA, no compute) — half the work on prefill.

Numerics: scores/softmax/accumulation in float32 regardless of cache
dtype; output cast back to q.dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu.ops.attention import AttentionMetadata
from vllm_distributed_tpu.utils import cdiv, next_power_of_2

_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128
# Per-buffer-slot VMEM budget for the combined K+V block (bytes).
_KV_BUF_BYTES = 1024 * 1024
_NBUF = 3
# Budget for the f32 flash accumulator across all fold groups.
_ACC_BYTES = 4 * 1024 * 1024
# Decode-shape fold target: grow F until a block's softmax chain has at
# least this many rows (amortizes VPU op issue over more elements).
_ROWS_TARGET = 32


def _kernel(
    # scalar prefetch
    block_tables_ref,  # [S, max_pages] int32 (SMEM)
    seq_lens_ref,  # [S] int32
    chunk_starts_ref,  # [S] int32
    # inputs
    q_ref,  # [1, 1, NF, ROWS, FD] VMEM block (block-diagonal queries)
    kv_pages_ref,  # [P, 2, page, HD1, LANES] in HBM/ANY
    # outputs
    out_ref,  # [1, 1, NF, ROWS, FD] VMEM block
    # scratch
    kv_vmem,  # [NBUF, 2, BLK, HD1, LANES]
    m_scr,  # [NF, ROWS, LANES] f32
    l_scr,  # [NF, ROWS, LANES] f32
    acc_scr,  # [NF, ROWS, FD] f32
    sems,  # DMA sems [NBUF]
    cnt,  # SMEM [2] int32 — [active blocks completed (global; the
    #                         buffer-rotation cursor), prefetch-pending
    #                         flag for the next active step's block]
    *,
    scale: float,
    soft_cap: float | None,
    page_size: int,
    pages_per_blk: int,
    group_size: int,
    num_fold: int,
    fold_width: int,
    mq_blk: int,
):
    s = pl.program_id(0)
    qb = pl.program_id(1)
    kvb = pl.program_id(2)
    num_seqs = pl.num_programs(0)
    num_qb = pl.num_programs(1)
    num_kvb = pl.num_programs(2)
    blk = pages_per_blk * page_size
    seq_len = seq_lens_ref[s]
    chunk_start = chunk_starts_ref[s]
    # Number of active kv blocks for (s, qb): the causal skip bound.
    q_pos_max = chunk_start + (qb + 1) * mq_blk - 1
    span = jnp.minimum(seq_len, q_pos_max + 1)
    nb = jnp.where(seq_len > 0, (span + blk - 1) // blk, 0)
    active = kvb < nb

    @pl.when((s == 0) & (qb == 0) & (kvb == 0))
    def _boot():
        cnt[0] = 0
        cnt[1] = 0

    def block_dma(seq, block_idx, buf):
        """One descriptor per page, covering K AND V for every head."""
        copies = []
        for i in range(pages_per_blk):
            page = block_tables_ref[seq, block_idx * pages_per_blk + i]
            copies.append(
                pltpu.make_async_copy(
                    kv_pages_ref.at[page],
                    kv_vmem.at[buf, :, pl.ds(i * page_size, page_size)],
                    sems.at[buf],
                )
            )
        return copies

    @pl.when(kvb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _MASK)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Bootstrap / gap-recovery: if no predecessor prefetched this block
    # (first active step, or the one-step lookahead hit an empty
    # sequence), issue the DMA here and eat the stall.
    @pl.when(active & (cnt[1] == 0))
    def _bootstrap_dma():
        for cp in block_dma(s, kvb, cnt[0] % _NBUF):
            cp.start()

    # Prefetch the NEXT active block (same q block, next q block, or the
    # next sequence) into the next rotation slot while this one computes.
    next_in_qb = kvb + 1 < nb
    # (s, qb+1) restarts from kv block 0; (s+1) likewise.  An empty
    # sequence between live ones defeats the one-step lookahead; the
    # bootstrap above recovers (correctness never depends on lookahead).
    have_next_qb = (qb + 1 < num_qb) & (seq_len > 0)
    next_seq_ok = (s + 1 < num_seqs) & (
        seq_lens_ref[jnp.minimum(s + 1, num_seqs - 1)] > 0
    )
    has_next = next_in_qb | have_next_qb | next_seq_ok
    next_s = jnp.where(next_in_qb | have_next_qb, s, s + 1)
    next_kvb = jnp.where(next_in_qb, kvb + 1, 0)

    @pl.when(active & has_next)
    def _prefetch():
        for cp in block_dma(next_s, next_kvb, (cnt[0] + 1) % _NBUF):
            cp.start()

    block_start = kvb * blk

    @pl.when(active)
    def _compute():
        buf = cnt[0] % _NBUF
        for cp in block_dma(s, kvb, buf):
            cp.wait()
        rows = acc_scr.shape[1]
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0)
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        # Row layout: r = (hl*G + g)*mq + m  →  token index m = r % mq.
        q_pos = chunk_start + qb * mq_blk + row_ids % mq_blk
        c_pos = block_start + col_ids
        mask = (c_pos <= q_pos) & (c_pos < seq_len)

        lanes = kv_vmem.shape[-1]
        f1 = fold_width // lanes
        for nf in range(num_fold):
            qn = q_ref[0, 0, nf].astype(jnp.float32)  # [ROWS, FD]
            scores = None
            for j in range(f1):
                kj = kv_vmem[buf, 0, :, nf * f1 + j, :].astype(jnp.float32)
                sj = jax.lax.dot_general(
                    qn[:, j * lanes : (j + 1) * lanes], kj,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                scores = sj if scores is None else scores + sj
            scores = scores * scale  # [ROWS, BLK]
            if soft_cap is not None:
                scores = jnp.tanh(scores / soft_cap) * soft_cap
            scores = jnp.where(mask, scores, _MASK)

            m_prev = m_scr[nf, :, 0:1]
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)
            p = jnp.where(mask, p, 0.0)
            l_new = l_scr[nf, :, 0:1] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            for j in range(f1):
                vj = kv_vmem[buf, 1, :, nf * f1 + j, :].astype(jnp.float32)
                pv = jax.lax.dot_general(
                    p, vj, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                sl = slice(j * lanes, (j + 1) * lanes)
                acc_scr[nf, :, sl] = acc_scr[nf, :, sl] * alpha + pv
            m_scr[nf] = jnp.broadcast_to(m_new, m_scr[nf].shape)
            l_scr[nf] = jnp.broadcast_to(l_new, l_scr[nf].shape)
        cnt[0] = cnt[0] + 1
        cnt[1] = has_next.astype(jnp.int32)

    @pl.when(kvb == num_kvb - 1)
    def _finalize():
        for nf in range(num_fold):
            denom = jnp.maximum(l_scr[nf, :, 0:1], 1e-30)
            out_ref[0, 0, nf] = (acc_scr[nf] / denom).astype(out_ref.dtype)


def _pow2_floor(x: int) -> int:
    return 1 << (max(x, 1).bit_length() - 1)


def _pick_fold(hkv: int, d: int, hd_pad: int, g: int, mq_blk: int):
    """Fold factor F (heads per matmul), fold width (lanes), NF groups.

    Constraints: F divides hkv; F*D is a multiple of 128 lanes (so the
    in-kernel lane slice is tile-aligned); the f32 accumulator
    (hkv*g*mq_blk*F*D*4 bytes) stays under budget.  When hkv*D itself
    is not 128-aligned the whole (padded) width is one fold group.
    """
    if (hkv * d) % _LANES or hd_pad != hkv * d:
        return hkv, hd_pad, 1
    f = 1
    while (f * d) % _LANES:
        f *= 2
    if hkv % f:  # cannot align within the head count: single group
        return hkv, hd_pad, 1

    def acc_bytes(f_):
        return hkv * g * mq_blk * f_ * d * 4

    while (
        f * g * mq_blk < _ROWS_TARGET
        and hkv % (2 * f) == 0
        and acc_bytes(2 * f) <= _ACC_BYTES
    ):
        f *= 2
    return f, f * d, hkv // f


def paged_attention(
    q: jax.Array,  # [T, Hq, D] flat
    kv_pages: jax.Array,  # [P, 2, page, HD]
    metadata: AttentionMetadata,
    *,
    scale: float,
    soft_cap: float | None = None,
    num_kv_heads: int | None = None,
    max_q: int = 1,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for paged_attention_reference (same contract), running the
    flash kernel.  `max_q` is the static per-sequence query bound for this
    step (the runner's padded max chunk length)."""
    t, hq, d = q.shape
    p_total, _, page_size, hd1, lanes = kv_pages.shape
    hd_pad = hd1 * lanes
    s, max_pages = metadata.block_tables.shape
    hkv = num_kv_heads if num_kv_heads is not None else hq
    g = hq // hkv

    # maxq padded so a q block always has >= 8 rows (f32 sublane tile).
    maxq = next_power_of_2(max_q)
    while maxq * g * hkv < 8:
        maxq *= 2

    # Split maxq into q blocks whose accumulator fits the budget, then
    # pick the head fold factor.
    mq_blk = maxq
    while hkv * g * mq_blk * d * 4 > _ACC_BYTES and mq_blk > 1:
        mq_blk //= 2
    f, fd, nf = _pick_fold(hkv, d, hd_pad, g, mq_blk)
    while f * g * mq_blk < 8:  # tiny-model corner: widen the q block
        mq_blk *= 2
        maxq = max(maxq, mq_blk)
    num_qb = maxq // mq_blk
    rows = f * g * mq_blk

    # ---- group flat queries per sequence ----
    # Padding tokens carry q_seq_ids == S (one past the end); route their
    # scatter to an out-of-bounds column so it is DROPPED instead of
    # clobbering a real row (scatter drops OOB updates under jit).
    valid = metadata.q_seq_ids < s
    seq_idx = jnp.minimum(metadata.q_seq_ids, s - 1)
    tok_in_chunk = metadata.q_positions - metadata.chunk_starts[seq_idx]
    col = jnp.where(valid, tok_in_chunk, maxq)
    q_grouped = jnp.zeros((s, maxq, hq, d), q.dtype)
    q_grouped = q_grouped.at[seq_idx, col].set(q, mode="drop")

    # ---- block-diagonal fold:  [S, NQB, NF, ROWS, FD] ----
    q7 = q_grouped.reshape(s, num_qb, mq_blk, nf, f, g, d)
    q7 = q7.transpose(0, 1, 3, 4, 5, 2, 6)  # [S,NQB,NF,F,G,mq,D]
    eye = jnp.eye(f, dtype=q.dtype)
    q_bd = (
        q7[:, :, :, :, :, :, None, :]
        * eye[None, None, None, :, None, None, :, None]
    ).reshape(s, num_qb, nf, rows, f * d)
    if fd > f * d:  # padded single-group case: zero lanes at the end
        q_bd = jnp.pad(
            q_bd, [(0, 0), (0, 0), (0, 0), (0, 0), (0, fd - f * d)]
        )

    # ---- kv blocking: size blocks to the VMEM budget ----
    kv_bytes_per_token = 2 * hd_pad * jnp.dtype(kv_pages.dtype).itemsize
    blk_tokens = max(_KV_BUF_BYTES // kv_bytes_per_token, page_size)
    blk_tokens = min(_pow2_floor(blk_tokens), max_pages * page_size)
    pages_per_blk = max(blk_tokens // page_size, 1)
    num_kvb = cdiv(max_pages, pages_per_blk)
    blk = pages_per_blk * page_size
    if max_pages % pages_per_blk:
        # Pad the table so block_dma never reads a page id out of bounds
        # (padding pages are id 0 — a real page, masked out of scores).
        pad = pages_per_blk - max_pages % pages_per_blk
        block_tables = jnp.pad(metadata.block_tables, ((0, 0), (0, pad)))
    else:
        block_tables = metadata.block_tables

    grid = (s, num_qb, num_kvb)
    kernel = functools.partial(
        _kernel,
        scale=scale,
        soft_cap=soft_cap,
        page_size=page_size,
        pages_per_blk=pages_per_blk,
        group_size=g,
        num_fold=nf,
        fold_width=fd,
        mq_blk=mq_blk,
    )
    out_bd = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, nf, rows, fd),
                    # Scalar-prefetch refs ride along after grid indices.
                    lambda s_, qb_, b_, *refs: (s_, qb_, 0, 0, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, nf, rows, fd),
                lambda s_, qb_, b_, *refs: (s_, qb_, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((_NBUF, 2, blk, hd1, lanes), kv_pages.dtype),
                pltpu.VMEM((nf, rows, _LANES), jnp.float32),
                pltpu.VMEM((nf, rows, _LANES), jnp.float32),
                pltpu.VMEM((nf, rows, fd), jnp.float32),
                pltpu.SemaphoreType.DMA((_NBUF,)),
                pltpu.SMEM((2,), jnp.int32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s, num_qb, nf, rows, fd), q.dtype),
        interpret=interpret,
    )(
        block_tables,
        metadata.seq_lens,
        metadata.chunk_starts,
        q_bd,
        kv_pages,
    )

    # ---- extract the diagonal blocks back to the flat layout ----
    ob = out_bd[..., : f * d].reshape(s, num_qb, nf, f, g, mq_blk, f, d)
    # diagonal over (F_row, F_lane): row block i holds head i's output
    # in lane block i; everything off-diagonal is cross-head garbage.
    out7 = jnp.einsum("abcfgmfd->abcfgmd", ob)  # [S,NQB,NF,F,G,mq,D]
    out = out7.transpose(0, 1, 5, 2, 3, 4, 6).reshape(s, maxq, hq, d)
    return out[seq_idx, jnp.clip(tok_in_chunk, 0, maxq - 1)]


paged_attention.needs_max_q = True


def paged_attention_cpu(*args, **kwargs):
    """Interpret-mode entry for CPU tests."""
    return paged_attention(*args, interpret=True, **kwargs)


paged_attention_cpu.needs_max_q = True
