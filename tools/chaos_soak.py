"""Chaos soak for supervised engine recovery (ISSUE 4 CI satellite).

Loops N kill→recover cycles against a loopback mock multi-host
deployment: each cycle kills the remote agent mid-generation, a
compose-style respawner restarts it, the in-process EngineSupervisor
rebuilds the executor and replays the interrupted request, and the tool
checks the stream completed with the exact greedy token sequence an
uninterrupted run produces (the mock worker's VDT_MOCK_TOKEN_SEQ mode
makes that falsifiable).  Reports recovery-latency percentiles and
replay-correctness failures as one JSON line.

Run from the repo root:

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --cycles 5

A 2-cycle smoke runs inside the fault suite
(tests/test_fault_injection.py::test_chaos_soak_smoke); longer loops
carry the ``soak`` pytest marker and stay out of tier-1.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import statistics
import sys
import tempfile
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

AGENT_ENV = {
    "VDT_ADVERTISE_NUM_CHIPS": "4",
    "VDT_ADVERTISE_PLATFORM": "cpu",
    "VDT_MOCK_TOKEN_SEQ": "1",
    "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
}


def _agent_main(port: int, env: dict[str, str]) -> None:
    for k, v in env.items():
        os.environ[k] = v
    from vllm_distributed_tpu.distributed.agent import remote_main

    remote_main("127.0.0.1", port)


def spawn_agent(port: int, extra_env: dict | None = None):
    proc = multiprocessing.Process(
        target=_agent_main,
        args=(port, {**AGENT_ENV, **(extra_env or {})}),
        daemon=True,
    )
    proc.start()
    return proc


class RespawningAgent:
    """Compose-style supervisor for one mock agent process: whenever the
    agent exits (killed by a cycle, or fail-fast after a driver-side
    teardown), start a fresh one that redials — exactly the external
    restart loop a real deployment's `restart: unless-stopped` runs."""

    def __init__(self, port: int, extra_env: dict | None = None,
                 spawn=spawn_agent):
        self._port = port
        self._env = extra_env
        self._spawn = spawn
        self._stop = threading.Event()
        self.current = spawn(port, extra_env)
        self.respawns = 0
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.is_set():
            self.current.join()
            if self._stop.is_set():
                return
            time.sleep(0.1)
            if self._stop.is_set():
                return
            self.current = self._spawn(self._port, self._env)
            self.respawns += 1

    def kill_current(self) -> None:
        self.current.terminate()

    def stop(self) -> None:
        self._stop.set()
        if self.current.is_alive():
            self.current.terminate()
        self._thread.join(timeout=10)
        # The watcher may have respawned one last agent before it saw
        # the stop flag; reap whatever is current now.
        if self.current.is_alive():
            self.current.terminate()
        self.current.join(timeout=5)


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def _rss_mb() -> float:
    """Resident set size in MiB (Linux).  The overload phase asserts
    this plateaus — an unbounded waiting queue shows up here first."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def run_soak(
    cycles: int = 5,
    *,
    model_dir: str | None = None,
    prompt: list[int] | None = None,
    max_tokens: int = 14,
    kill_after_tokens: int = 3,
    hb_interval: float = 0.5,
    backoff: float = 0.2,
    overload_rps: float = 0.0,
    overload_cap: int = 8,
) -> dict:
    """Run the kill→recover loop; returns the report dict.  Mutates (and
    restores) os.environ — call from a dedicated process or a test that
    tolerates env churn.

    ``overload_rps`` > 0 arms the ISSUE 8 overload phase: open-loop
    Poisson arrivals at that rate run CONCURRENTLY with the kill→recover
    cycles (admission caps at ``overload_cap``), and the report asserts
    the overload-resilience contract — sheds happen (typed 429-path
    rejections, not hangs), the waiting queue stays under the cap, and
    RSS plateaus instead of growing with offered load."""
    import asyncio
    import random

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.engine.overload import EngineOverloadedError
    from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    class SoakExecutor(MultiHostExecutor):
        worker_cls = "tests.mock_worker.MockWorker"

    prompt = prompt or [1, 2, 3]
    port = get_open_port()
    env = {
        "VDT_SERVER_PORT": str(port),
        "VDT_HEARTBEAT_INTERVAL_SECONDS": str(hb_interval),
        "VDT_HEARTBEAT_MISS_THRESHOLD": "3",
        "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS": "5",
        "VDT_CONNECT_TIMEOUT_SECONDS": "30",
        "VDT_MAX_ENGINE_RESTARTS": str(cycles + 2),
        "VDT_ENGINE_RESTART_BACKOFF_SECONDS": str(backoff),
        "VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS": "2",
        # Generous window: the budget above covers every cycle anyway.
        "VDT_CRASH_LOOP_WINDOW_SECONDS": "3600",
        "VDT_MOCK_TOKEN_SEQ": "1",
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
    }
    if overload_rps > 0:
        env["VDT_MAX_WAITING_REQUESTS"] = str(overload_cap)
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    agents = None
    engine = None
    # The mock's deterministic sequence: token i = absolute position.
    expected = list(range(len(prompt), len(prompt) + max_tokens))
    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )

    async def one_cycle(idx: int, kill: bool):
        # Under the overload phase the victim competes with the offered
        # load for admission slots; a well-behaved client retries 429s,
        # so the victim does too (a reject carries no partial state —
        # whole-request retry is safe).
        for _ in range(100):
            try:
                return await _one_cycle_admitted(idx, kill)
            except EngineOverloadedError:
                await asyncio.sleep(0.1)
        raise RuntimeError("victim request never admitted under overload")

    async def _one_cycle_admitted(idx: int, kill: bool):
        tokens: list[int] = []
        killed = False
        last_arrival = time.monotonic()
        worst_stall = 0.0
        async for out in engine.generate(
            f"soak-{idx}",
            prompt_token_ids=list(prompt),
            sampling_params=sp.clone(),
        ):
            now = time.monotonic()
            if killed:
                worst_stall = max(worst_stall, now - last_arrival)
            last_arrival = now
            tokens = list(out.outputs[0].token_ids)
            if kill and not killed and len(tokens) >= kill_after_tokens:
                agents.kill_current()
                killed = True
        return tokens, worst_stall

    # A hung replay is exactly the failure class this harness hunts —
    # bound each cycle so it reports instead of stalling CI forever.
    cycle_timeout = 60.0

    # Overload phase (ISSUE 8): sustained over-capacity offered load
    # riding across the kill→recover cycles.
    load_stats = {
        "offered": 0,
        "completed": 0,
        "rejected": 0,
        "dead_errors": 0,
        "other_errors": 0,
        "max_waiting_depth": 0,
    }

    async def one_load_request(idx: int) -> None:
        try:
            async for _ in engine.generate(
                f"load-{idx}",
                prompt_token_ids=list(prompt),
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=4, ignore_eos=True
                ),
            ):
                pass
            load_stats["completed"] += 1
        except EngineOverloadedError:
            load_stats["rejected"] += 1
        except Exception as e:  # noqa: BLE001 — accounted, not fatal
            from vllm_distributed_tpu.engine.async_llm import (
                EngineDeadError,
            )

            if isinstance(e, EngineDeadError):
                load_stats["dead_errors"] += 1
            else:
                load_stats["other_errors"] += 1

    async def offered_load(stop: "asyncio.Event") -> None:
        rng = random.Random(7)
        inflight: set = set()
        idx = 0
        while not stop.is_set():
            load_stats["offered"] += 1
            t = asyncio.ensure_future(one_load_request(idx))
            inflight.add(t)
            t.add_done_callback(inflight.discard)
            idx += 1
            load_stats["max_waiting_depth"] = max(
                load_stats["max_waiting_depth"],
                len(engine.engine.scheduler.waiting),
            )
            await asyncio.sleep(rng.expovariate(overload_rps))
        # Sheds resolve fast; completions are bounded by max_tokens=4.
        if inflight:
            await asyncio.wait(list(inflight), timeout=30)

    async def go():
        latencies: list[float] = []
        failures = 0
        # Cycle 0: uninterrupted sanity run (also warms the deployment).
        tokens, _ = await asyncio.wait_for(
            one_cycle(-1, kill=False), timeout=cycle_timeout
        )
        if tokens != expected:
            raise RuntimeError(
                f"baseline run wrong: {tokens} != {expected}"
            )
        stop_load = asyncio.Event()
        load_task = (
            asyncio.ensure_future(offered_load(stop_load))
            if overload_rps > 0
            else None
        )
        try:
            for i in range(cycles):
                tokens, stall = await asyncio.wait_for(
                    one_cycle(i, kill=True), timeout=cycle_timeout
                )
                latencies.append(stall)
                if tokens != expected:
                    failures += 1
                    print(
                        f"cycle {i}: REPLAY MISMATCH {tokens} != {expected}",
                        file=sys.stderr,
                    )
        finally:
            if load_task is not None:
                stop_load.set()
                await load_task
        return latencies, failures

    # Setup happens inside the try so a failed boot (port race, connect
    # timeout) still reaps the respawner and restores the env — a leaked
    # RespawningAgent would redial a dead port for the rest of the
    # process, and the env mutations would bleed into later tests.
    try:
        if model_dir is None:
            tmpdir = tempfile.mkdtemp(prefix="vdt_soak_")
            model_dir = write_llama_config(os.path.join(tmpdir, "m"))
        agents = RespawningAgent(port)
        engine_kwargs = {}
        if overload_rps > 0:
            # Constrain capacity so the configured rate is genuinely
            # over-capacity on the mock deployment.
            engine_kwargs["max_num_seqs"] = 4
        engine = AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_hosts=2,
                num_decode_steps=1,
                max_model_len=512,
                distributed_executor_backend=SoakExecutor,
                **engine_kwargs,
            )
        )
        rss_before = _rss_mb()
        threads_before = threading.active_count()
        latencies, failures = (
            asyncio.new_event_loop().run_until_complete(go())
        )
        report = {
            "cycles": cycles,
            "replay_failures": failures,
            "recovery_seconds": {
                "p50": round(_percentile(latencies, 0.5), 3),
                "p90": round(_percentile(latencies, 0.9), 3),
                "max": round(max(latencies), 3) if latencies else 0.0,
                "mean": (
                    round(statistics.fmean(latencies), 3)
                    if latencies else 0.0
                ),
            },
            "restarts_total": engine.supervisor.restarts_total,
            "agent_respawns": agents.respawns,
        }
        if overload_rps > 0:
            rss_after = _rss_mb()
            report["overload"] = {
                "offered_rps": overload_rps,
                "cap": overload_cap,
                **load_stats,
                "rss_before_mb": round(rss_before, 1),
                "rss_after_mb": round(rss_after, 1),
                "rss_growth_mb": round(rss_after - rss_before, 1),
                "threads_before": threads_before,
                "threads_after": threading.active_count(),
                # The contract the smoke test asserts: the cap held
                # (bounded memory) and load was actually shed.
                "bounded": (
                    load_stats["max_waiting_depth"] <= overload_cap
                    and load_stats["rejected"] > 0
                ),
            }
        return report
    finally:
        try:
            if engine is not None:
                engine.shutdown()
        finally:
            try:
                if agents is not None:
                    agents.stop()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--cycles", type=int, default=5)
    parser.add_argument("--max-tokens", type=int, default=14)
    parser.add_argument("--kill-after-tokens", type=int, default=3)
    parser.add_argument("--backoff", type=float, default=0.2)
    parser.add_argument(
        "--overload-rps",
        type=float,
        default=0.0,
        help="arm the overload phase: open-loop Poisson offered load "
        "at this rate rides across the kill-recover cycles "
        "(admission caps on; 0 = off)",
    )
    parser.add_argument(
        "--overload-cap",
        type=int,
        default=8,
        help="VDT_MAX_WAITING_REQUESTS for the overload phase",
    )
    args = parser.parse_args()
    report = run_soak(
        cycles=args.cycles,
        max_tokens=args.max_tokens,
        kill_after_tokens=args.kill_after_tokens,
        backoff=args.backoff,
        overload_rps=args.overload_rps,
        overload_cap=args.overload_cap,
    )
    print(json.dumps(report))
    if report["replay_failures"]:
        sys.exit(1)
    overload = report.get("overload")
    if overload is not None and not overload["bounded"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
