"""Chaos soak for supervised engine recovery (ISSUE 4 CI satellite).

Loops N kill→recover cycles against a loopback mock multi-host
deployment: each cycle kills the remote agent mid-generation, a
compose-style respawner restarts it, the in-process EngineSupervisor
rebuilds the executor and replays the interrupted request, and the tool
checks the stream completed with the exact greedy token sequence an
uninterrupted run produces (the mock worker's VDT_MOCK_TOKEN_SEQ mode
makes that falsifiable).  Reports recovery-latency percentiles and
replay-correctness failures as one JSON line.

Run from the repo root:

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --cycles 5

A 2-cycle smoke runs inside the fault suite
(tests/test_fault_injection.py::test_chaos_soak_smoke); longer loops
carry the ``soak`` pytest marker and stay out of tier-1.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

AGENT_ENV = {
    "VDT_ADVERTISE_NUM_CHIPS": "4",
    "VDT_ADVERTISE_PLATFORM": "cpu",
    "VDT_MOCK_TOKEN_SEQ": "1",
    "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
}


def _agent_main(port: int, env: dict[str, str]) -> None:
    for k, v in env.items():
        os.environ[k] = v
    from vllm_distributed_tpu.distributed.agent import remote_main

    remote_main("127.0.0.1", port)


def spawn_agent(port: int, extra_env: dict | None = None):
    proc = multiprocessing.Process(
        target=_agent_main,
        args=(port, {**AGENT_ENV, **(extra_env or {})}),
        daemon=True,
    )
    proc.start()
    return proc


class RespawningAgent:
    """Compose-style supervisor for one mock agent process: whenever the
    agent exits (killed by a cycle, or fail-fast after a driver-side
    teardown), start a fresh one that redials — exactly the external
    restart loop a real deployment's `restart: unless-stopped` runs."""

    def __init__(self, port: int, extra_env: dict | None = None,
                 spawn=spawn_agent):
        self._port = port
        self._env = extra_env
        self._spawn = spawn
        self._stop = threading.Event()
        self.current = spawn(port, extra_env)
        self.respawns = 0
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.is_set():
            self.current.join()
            if self._stop.is_set():
                return
            time.sleep(0.1)
            if self._stop.is_set():
                return
            self.current = self._spawn(self._port, self._env)
            self.respawns += 1

    def kill_current(self) -> None:
        self.current.terminate()

    def stop(self) -> None:
        self._stop.set()
        if self.current.is_alive():
            self.current.terminate()
        self._thread.join(timeout=10)
        # The watcher may have respawned one last agent before it saw
        # the stop flag; reap whatever is current now.
        if self.current.is_alive():
            self.current.terminate()
        self.current.join(timeout=5)


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def _rss_mb() -> float:
    """Resident set size in MiB (Linux).  The overload phase asserts
    this plateaus — an unbounded waiting queue shows up here first."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def run_soak(
    cycles: int = 5,
    *,
    model_dir: str | None = None,
    prompt: list[int] | None = None,
    max_tokens: int = 14,
    kill_after_tokens: int = 3,
    hb_interval: float = 0.5,
    backoff: float = 0.2,
    overload_rps: float = 0.0,
    overload_cap: int = 8,
) -> dict:
    """Run the kill→recover loop; returns the report dict.  Mutates (and
    restores) os.environ — call from a dedicated process or a test that
    tolerates env churn.

    ``overload_rps`` > 0 arms the ISSUE 8 overload phase: open-loop
    Poisson arrivals at that rate run CONCURRENTLY with the kill→recover
    cycles (admission caps at ``overload_cap``), and the report asserts
    the overload-resilience contract — sheds happen (typed 429-path
    rejections, not hangs), the waiting queue stays under the cap, and
    RSS plateaus instead of growing with offered load."""
    import asyncio
    import random

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.engine.overload import EngineOverloadedError
    from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    class SoakExecutor(MultiHostExecutor):
        worker_cls = "tests.mock_worker.MockWorker"

    prompt = prompt or [1, 2, 3]
    port = get_open_port()
    env = {
        "VDT_SERVER_PORT": str(port),
        "VDT_HEARTBEAT_INTERVAL_SECONDS": str(hb_interval),
        "VDT_HEARTBEAT_MISS_THRESHOLD": "3",
        "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS": "5",
        "VDT_CONNECT_TIMEOUT_SECONDS": "30",
        "VDT_MAX_ENGINE_RESTARTS": str(cycles + 2),
        "VDT_ENGINE_RESTART_BACKOFF_SECONDS": str(backoff),
        "VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS": "2",
        # Generous window: the budget above covers every cycle anyway.
        "VDT_CRASH_LOOP_WINDOW_SECONDS": "3600",
        "VDT_MOCK_TOKEN_SEQ": "1",
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.05",
        # Flight-recorder artifacts (ISSUE 12) land in a fresh dir so
        # the report can count the dumps this soak's kill cycles
        # produced (one per HostFailure + one per recovery cycle).
        "VDT_FLIGHT_RECORDER_DIR": tempfile.mkdtemp(
            prefix="vdt_soak_fr_"
        ),
    }
    if overload_rps > 0:
        env["VDT_MAX_WAITING_REQUESTS"] = str(overload_cap)
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    agents = None
    engine = None
    # The mock's deterministic sequence: token i = absolute position.
    expected = list(range(len(prompt), len(prompt) + max_tokens))
    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )

    async def one_cycle(idx: int, kill: bool):
        # Under the overload phase the victim competes with the offered
        # load for admission slots; a well-behaved client retries 429s,
        # so the victim does too (a reject carries no partial state —
        # whole-request retry is safe).
        for _ in range(100):
            try:
                return await _one_cycle_admitted(idx, kill)
            except EngineOverloadedError:
                await asyncio.sleep(0.1)
        raise RuntimeError("victim request never admitted under overload")

    async def _one_cycle_admitted(idx: int, kill: bool):
        tokens: list[int] = []
        killed = False
        last_arrival = time.monotonic()
        worst_stall = 0.0
        async for out in engine.generate(
            f"soak-{idx}",
            prompt_token_ids=list(prompt),
            sampling_params=sp.clone(),
        ):
            now = time.monotonic()
            if killed:
                worst_stall = max(worst_stall, now - last_arrival)
            last_arrival = now
            tokens = list(out.outputs[0].token_ids)
            if kill and not killed and len(tokens) >= kill_after_tokens:
                agents.kill_current()
                killed = True
        return tokens, worst_stall

    # A hung replay is exactly the failure class this harness hunts —
    # bound each cycle so it reports instead of stalling CI forever.
    cycle_timeout = 60.0

    # Overload phase (ISSUE 8): sustained over-capacity offered load
    # riding across the kill→recover cycles.
    load_stats = {
        "offered": 0,
        "completed": 0,
        "rejected": 0,
        "dead_errors": 0,
        "other_errors": 0,
        "max_waiting_depth": 0,
    }

    async def one_load_request(idx: int) -> None:
        try:
            async for _ in engine.generate(
                f"load-{idx}",
                prompt_token_ids=list(prompt),
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=4, ignore_eos=True
                ),
            ):
                pass
            load_stats["completed"] += 1
        except EngineOverloadedError:
            load_stats["rejected"] += 1
        except Exception as e:  # noqa: BLE001 — accounted, not fatal
            from vllm_distributed_tpu.engine.async_llm import (
                EngineDeadError,
            )

            if isinstance(e, EngineDeadError):
                load_stats["dead_errors"] += 1
            else:
                load_stats["other_errors"] += 1

    async def offered_load(stop: "asyncio.Event") -> None:
        rng = random.Random(7)
        inflight: set = set()
        idx = 0
        while not stop.is_set():
            load_stats["offered"] += 1
            t = asyncio.ensure_future(one_load_request(idx))
            inflight.add(t)
            t.add_done_callback(inflight.discard)
            idx += 1
            load_stats["max_waiting_depth"] = max(
                load_stats["max_waiting_depth"],
                len(engine.engine.scheduler.waiting),
            )
            await asyncio.sleep(rng.expovariate(overload_rps))
        # Sheds resolve fast; completions are bounded by max_tokens=4.
        if inflight:
            await asyncio.wait(list(inflight), timeout=30)

    async def go():
        latencies: list[float] = []
        failures = 0
        # Cycle 0: uninterrupted sanity run (also warms the deployment).
        tokens, _ = await asyncio.wait_for(
            one_cycle(-1, kill=False), timeout=cycle_timeout
        )
        if tokens != expected:
            raise RuntimeError(
                f"baseline run wrong: {tokens} != {expected}"
            )
        stop_load = asyncio.Event()
        load_task = (
            asyncio.ensure_future(offered_load(stop_load))
            if overload_rps > 0
            else None
        )
        try:
            for i in range(cycles):
                tokens, stall = await asyncio.wait_for(
                    one_cycle(i, kill=True), timeout=cycle_timeout
                )
                latencies.append(stall)
                if tokens != expected:
                    failures += 1
                    print(
                        f"cycle {i}: REPLAY MISMATCH {tokens} != {expected}",
                        file=sys.stderr,
                    )
        finally:
            if load_task is not None:
                stop_load.set()
                await load_task
        return latencies, failures

    # Setup happens inside the try so a failed boot (port race, connect
    # timeout) still reaps the respawner and restores the env — a leaked
    # RespawningAgent would redial a dead port for the rest of the
    # process, and the env mutations would bleed into later tests.
    try:
        if model_dir is None:
            tmpdir = tempfile.mkdtemp(prefix="vdt_soak_")
            model_dir = write_llama_config(os.path.join(tmpdir, "m"))
        agents = RespawningAgent(port)
        engine_kwargs = {}
        if overload_rps > 0:
            # Constrain capacity so the configured rate is genuinely
            # over-capacity on the mock deployment.
            engine_kwargs["max_num_seqs"] = 4
        engine = AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_hosts=2,
                num_decode_steps=1,
                max_model_len=512,
                distributed_executor_backend=SoakExecutor,
                **engine_kwargs,
            )
        )
        rss_before = _rss_mb()
        threads_before = threading.active_count()
        latencies, failures = (
            asyncio.new_event_loop().run_until_complete(go())
        )
        report = {
            "cycles": cycles,
            "replay_failures": failures,
            "recovery_seconds": {
                "p50": round(_percentile(latencies, 0.5), 3),
                "p90": round(_percentile(latencies, 0.9), 3),
                "max": round(max(latencies), 3) if latencies else 0.0,
                "mean": (
                    round(statistics.fmean(latencies), 3)
                    if latencies else 0.0
                ),
            },
            "restarts_total": engine.supervisor.restarts_total,
            "agent_respawns": agents.respawns,
            # ISSUE 12 contract: every kill cycle leaves a post-mortem
            # artifact behind (host_failure and/or recovery dumps).
            "flightrecorder_dumps": len(
                [
                    f
                    for f in os.listdir(env["VDT_FLIGHT_RECORDER_DIR"])
                    if f.startswith("flightrecorder-")
                ]
            ),
        }
        if overload_rps > 0:
            rss_after = _rss_mb()
            report["overload"] = {
                "offered_rps": overload_rps,
                "cap": overload_cap,
                **load_stats,
                "rss_before_mb": round(rss_before, 1),
                "rss_after_mb": round(rss_after, 1),
                "rss_growth_mb": round(rss_after - rss_before, 1),
                "threads_before": threads_before,
                "threads_after": threading.active_count(),
                # The contract the smoke test asserts: the cap held
                # (bounded memory) and load was actually shed.
                "bounded": (
                    load_stats["max_waiting_depth"] <= overload_cap
                    and load_stats["rejected"] > 0
                ),
            }
        return report
    finally:
        try:
            if engine is not None:
                engine.shutdown()
        finally:
            try:
                if agents is not None:
                    agents.stop()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v


# ---------------------------------------------------------------------
# Tiered-KV spill soak (ISSUE 14): kill→recover cycles with an ACTIVE
# host-DRAM tier.  Disjoint chains cycled through a constrained pool
# keep spill→restore traffic flowing; every cycle kills the remote host
# mid-stream and asserts the recovered engine still produces the exact
# deterministic token streams (the mock worker's page-content
# verification raises on any stale or mis-restored page served as a
# hit), the worker's host dict stays bounded by the configured pool
# across recoveries, and RSS plateaus (no host-memory leak).
# ---------------------------------------------------------------------
def run_kv_spill_soak(
    cycles: int = 3,
    *,
    model_dir: str | None = None,
    chains: int = 6,
    chain_len: int = 19,
    max_tokens: int = 6,
    num_kv_pages: int = 12,
    host_pages: int = 32,
    hb_interval: float = 0.5,
    backoff: float = 0.2,
) -> dict:
    """Run the spill-phase kill→recover loop; returns the report dict.
    Mutates (and restores) os.environ like run_soak."""
    import asyncio

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    class SoakExecutor(MultiHostExecutor):
        worker_cls = "tests.mock_worker.MockWorker"

    port = get_open_port()
    env = {
        "VDT_SERVER_PORT": str(port),
        "VDT_HEARTBEAT_INTERVAL_SECONDS": str(hb_interval),
        "VDT_HEARTBEAT_MISS_THRESHOLD": "3",
        "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS": "5",
        "VDT_CONNECT_TIMEOUT_SECONDS": "30",
        "VDT_MAX_ENGINE_RESTARTS": str(cycles + 2),
        "VDT_ENGINE_RESTART_BACKOFF_SECONDS": str(backoff),
        "VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS": "2",
        "VDT_CRASH_LOOP_WINDOW_SECONDS": "3600",
        "VDT_MOCK_TOKEN_SEQ": "1",
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.03",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    agents = None
    engine = None
    prompts = [
        [100 * (i + 1) + j for j in range(chain_len)]
        for i in range(chains)
    ]
    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )
    stats = {
        "spill_pages": 0,
        "restore_pages": 0,
        "host_hit_tokens": 0,
        "host_slots_max": 0,
        "replay_failures": 0,
    }

    async def one_chain(tag: str, prompt: list[int], kill_at: int = -1):
        expected = list(
            range(len(prompt), len(prompt) + max_tokens)
        )
        tokens: list[int] = []
        killed = False
        async for out in engine.generate(
            tag, prompt_token_ids=list(prompt), sampling_params=sp.clone()
        ):
            tokens = list(out.outputs[0].token_ids)
            if kill_at >= 0 and not killed and len(tokens) >= kill_at:
                agents.kill_current()
                killed = True
        if tokens != expected:
            stats["replay_failures"] += 1
            print(
                f"{tag}: TOKEN MISMATCH {tokens} != {expected}",
                file=sys.stderr,
            )

    async def go():
        for cycle in range(cycles):
            sched = engine.engine.scheduler
            spill0 = sched.kv_spill_pages
            restore0 = sched.kv_restore_pages
            host0 = sched.prefix_cache_hits_host
            # Warm loop: cycle every chain twice so late chains evict
            # early ones (spill) and the second pass restores them.
            for rnd in range(2):
                for i, p in enumerate(prompts):
                    await asyncio.wait_for(
                        one_chain(f"c{cycle}-r{rnd}-{i}", p), timeout=60
                    )
            sched = engine.engine.scheduler
            stats["spill_pages"] += sched.kv_spill_pages - spill0
            stats["restore_pages"] += sched.kv_restore_pages - restore0
            stats["host_hit_tokens"] += (
                sched.prefix_cache_hits_host - host0
            )
            info = engine.engine.executor.collective_rpc(
                "get_kv_tier_info",
                unique_reply_rank=engine.engine.executor.output_rank,
                timeout=10.0,
            )
            if isinstance(info, dict):
                stats["host_slots_max"] = max(
                    stats["host_slots_max"], info.get("host_slots", 0)
                )
                if info.get("host_slots", 0) > host_pages:
                    stats["replay_failures"] += 1
                    print(
                        f"cycle {cycle}: host tier over budget "
                        f"{info['host_slots']} > {host_pages}",
                        file=sys.stderr,
                    )
            # Kill the remote host mid-stream with the tier active; the
            # supervisor rebuild must come back clean (fresh tiers both
            # sides) and replay bit-identically.
            await asyncio.wait_for(
                one_chain(f"kill-{cycle}", prompts[0], kill_at=2),
                timeout=60,
            )

    try:
        if model_dir is None:
            tmpdir = tempfile.mkdtemp(prefix="vdt_spill_soak_")
            model_dir = write_llama_config(os.path.join(tmpdir, "m"))
        agents = RespawningAgent(port)
        engine = AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_hosts=2,
                num_decode_steps=1,
                page_size=4,
                max_model_len=512,
                enable_prefix_caching=True,
                num_kv_pages=num_kv_pages,
                kv_spill_host_pages=host_pages,
                kv_spill_restore_min_tokens=4,
                distributed_executor_backend=SoakExecutor,
            )
        )
        rss_before = _rss_mb()
        asyncio.new_event_loop().run_until_complete(go())
        rss_after = _rss_mb()
        return {
            "cycles": cycles,
            "chains": chains,
            "num_kv_pages": num_kv_pages,
            "host_pages": host_pages,
            **stats,
            "restarts_total": engine.supervisor.restarts_total,
            "agent_respawns": agents.respawns,
            "rss_before_mb": round(rss_before, 1),
            "rss_after_mb": round(rss_after, 1),
            "rss_growth_mb": round(rss_after - rss_before, 1),
            # The contract the smoke test asserts: the tier was ACTIVE
            # (spills AND restores happened), stayed bounded, and every
            # stream — including the killed ones — was bit-identical.
            "active": (
                stats["spill_pages"] > 0 and stats["restore_pages"] > 0
            ),
            "bounded": (
                stats["replay_failures"] == 0
                and stats["host_slots_max"] <= host_pages
            ),
        }
    finally:
        try:
            if engine is not None:
                engine.shutdown()
        finally:
            try:
                if agents is not None:
                    agents.stop()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v


# ---------------------------------------------------------------------
# Router soak (ISSUE 10): kill/drain replicas BEHIND the router under
# load and assert zero lost admitted work + bounded client stall.
# ---------------------------------------------------------------------
ROUTER_AGENT_ENV = {
    "VDT_MOCK_TOKEN_SEQ": "1",
    "VDT_MOCK_EXECUTE_SLEEP_SECONDS": "0.03",
}


def run_router_soak(
    replicas: int = 2,
    cycles: int = 4,
    *,
    max_tokens: int = 14,
    kill_after_tokens: int = 3,
    load_concurrency: int = 3,
    policy: str = "least_loaded",
    stall_bound_s: float = 15.0,
) -> dict:
    """N mock uniproc replicas behind the router; each cycle kills
    (even cycles) or drains (odd cycles) the replica serving a
    mid-stream victim request while background load runs, then revives
    it.  Every admitted stream must complete with the mock worker's
    exact position-token sequence (VDT_MOCK_TOKEN_SEQ) — a migration
    that drops, duplicates, or restarts tokens is a mismatch — and the
    client-visible stall across the migration must stay bounded.

    Mutates (and restores) os.environ; call from a dedicated process or
    a test that tolerates env churn."""
    import asyncio

    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    saved = {k: os.environ.get(k) for k in ROUTER_AGENT_ENV}
    os.environ.update(ROUTER_AGENT_ENV)
    tmpdir = tempfile.mkdtemp(prefix="vdt_router_soak_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    prompt = [1, 2, 3]
    expected = list(range(len(prompt), len(prompt) + max_tokens))

    def mk_engine() -> AsyncLLM:
        return AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=128,
                max_model_len=256,
                num_decode_steps=1,
                distributed_executor_backend=MockUniProcExecutor,
            )
        )

    stats = {
        "admitted": 0,
        "completed": 0,
        "mismatches": 0,
        "lost": 0,  # admitted but never finished (the contract breach)
        "rejected": 0,
    }
    stalls: list[float] = []

    async def go() -> dict:
        import aiohttp

        engines: list = [mk_engine() for _ in range(replicas)]
        ports = [get_open_port() for _ in range(replicas)]
        runners: list = [None] * replicas

        async def start_replica(i: int) -> None:
            state = init_app_state(
                engines[i],
                served_model_name="router-soak",
                replica_id=f"replica-{i}",
            )
            # Tiny shutdown_timeout: "kill" must sever live streams,
            # not wait them out.
            for attempt in range(50):
                try:
                    runners[i] = await serve_http(
                        build_app(state),
                        host="127.0.0.1",
                        port=ports[i],
                        shutdown_timeout=0.05,
                    )
                    return
                except OSError:
                    # The killed predecessor's socket may linger a beat.
                    await asyncio.sleep(0.1)
            raise RuntimeError(f"could not rebind replica {i}")

        for i in range(replicas):
            await start_replica(i)
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        router_state = RouterState(
            urls,
            policy=policy,
            health_interval=0.3,
            connect_timeout=2,
            read_timeout=30,
        )
        router_port = get_open_port()
        router_runner = await serve_http(
            build_router_app(router_state),
            host="127.0.0.1",
            port=router_port,
        )
        router_url = f"http://127.0.0.1:{router_port}"
        timeout = aiohttp.ClientTimeout(total=None, sock_read=60)

        async def one_stream(
            session, tag: str, on_tokens=None, served: dict | None = None
        ) -> None:
            """Drive one streaming completion through the router; assert
            the exact token sequence.  ``on_tokens(count)`` fires as
            tokens arrive (the victim uses it to trigger the kill);
            ``served`` receives the serving replica id so the chaos
            targets the replica actually holding the stream."""
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status == 429:
                        stats["rejected"] += 1
                        return
                    if resp.status != 200:
                        stats["lost"] += 1
                        return
                    if served is not None:
                        served["id"] = resp.headers.get(
                            "X-VDT-Replica-Id", ""
                        )
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    last = time.monotonic()
                    worst_gap = 0.0
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            break  # router gave up: lost work
                        now = time.monotonic()
                        worst_gap = max(worst_gap, now - last)
                        last = now
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                        if on_tokens is not None:
                            await on_tokens(len(toks))
                    stalls.append(worst_gap)
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                        print(
                            f"{tag}: TOKEN MISMATCH {toks} != {expected}",
                            file=sys.stderr,
                        )
                    else:
                        stats["completed"] += 1
            except Exception as e:  # noqa: BLE001 — an admitted stream erroring out IS lost work
                stats["lost"] += 1
                print(f"{tag}: stream error {e}", file=sys.stderr)

        async def cycle(n: int) -> None:
            mode = "drain" if n % 2 else "kill"
            fired = asyncio.Event()
            served: dict = {}
            killed: dict = {}

            async def trigger(count: int) -> None:
                # Kill/drain the replica ACTUALLY serving the victim
                # stream (the X-VDT-Replica-Id the router echoed).
                if fired.is_set() or count < kill_after_tokens:
                    return
                fired.set()
                victim = int(served["id"].rsplit("-", 1)[1])
                killed["index"] = victim
                if mode == "kill":
                    runner, runners[victim] = runners[victim], None
                    await runner.cleanup()
                    engines[victim].shutdown()
                else:
                    async with session.post(
                        f"{urls[victim]}/drain",
                        params={"timeout": "0"},
                        timeout=aiohttp.ClientTimeout(total=30),
                    ) as dr:
                        await dr.read()

            loaders = [
                one_stream(session, f"cycle{n}-load{j}")
                for j in range(load_concurrency)
            ]
            await asyncio.wait_for(
                asyncio.gather(
                    one_stream(
                        session, f"cycle{n}-victim", trigger, served
                    ),
                    *loaders,
                ),
                timeout=120,
            )
            # Revive the victim for the next cycle (a drained engine
            # stays up but rejects admission, so it is swapped for a
            # fresh one either way — the restart a deployment would do).
            victim = killed.get("index")
            if victim is None:
                return
            runner, runners[victim] = runners[victim], None
            if runner is not None:
                await runner.cleanup()
            try:
                engines[victim].shutdown()
            except Exception:  # noqa: BLE001 — already-dead engine
                pass
            engines[victim] = mk_engine()
            await start_replica(victim)
            # Let the health poll re-admit the revived replica.
            await asyncio.sleep(0.5)

        async with aiohttp.ClientSession() as session:
            # Warm-up sanity stream before any chaos.
            await asyncio.wait_for(
                one_stream(session, "warmup"), timeout=60
            )
            for n in range(cycles):
                await cycle(n)
            async with session.get(
                f"{router_url}/router/state",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                router_counters = (await resp.json())["counters"]
        await router_runner.cleanup()
        for runner in runners:
            if runner is not None:
                await runner.cleanup()
        for engine in engines:
            try:
                engine.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        return router_counters

    try:
        router_counters = (
            asyncio.new_event_loop().run_until_complete(go())
        )
        migrations = sum(
            v
            for k, v in router_counters.items()
            if k.startswith("migrations.")
        )
        report = {
            "mode": "router",
            "replicas": replicas,
            "cycles": cycles,
            "policy": policy,
            **stats,
            "migrations": migrations,
            "router_counters": router_counters,
            "stall_seconds": {
                "p50": round(_percentile(stalls, 0.5), 3),
                "max": round(max(stalls), 3) if stalls else 0.0,
            },
            # The acceptance contract: no admitted stream lost or
            # corrupted, and the worst client-visible stall (which
            # includes the migration) stays bounded.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and (not stalls or max(stalls) <= stall_bound_s)
            ),
        }
        return report
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------
# Disaggregation chaos (ISSUE 15): a prefill-role + decode-role mock
# pool behind the router, SIGKILLing the prefill replica mid-hand-off
# and mid-export — the router must fall back to recompute-resume on the
# decode pool with zero lost admitted work, bit-identical greedy
# output, and no leaked pages on either surviving replica.
# ---------------------------------------------------------------------
def run_disagg_soak(
    cycles: int = 4,
    *,
    max_tokens: int = 10,
    prompt_pages: int = 3,
    stall_bound_s: float = 20.0,
) -> dict:
    """Each cycle streams one long (page-aligned) prompt through a
    disaggregated 2-replica pool.  Cycle 0 is the happy path (planned
    hand-off, KV pages adopted decode-side); odd cycles kill the
    prefill replica BEFORE the transfer starts (mid-hand-off), even
    cycles > 0 kill it after the first export chunk (mid-export) — via
    the deterministic disagg test seams.  Every stream must finish with
    the exact position-token sequence, and after each cycle the decode
    replica's allocator must account for every page (imports/holds
    empty, free count restored modulo cached-free chains).

    Mutates (and restores) os.environ; call from a dedicated process or
    a test that tolerates env churn."""
    import asyncio

    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )
    from vllm_distributed_tpu.router import disagg
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    page_size = 16
    prompt = [(i % 900) + 1 for i in range(prompt_pages * page_size)]
    env = {
        **ROUTER_AGENT_ENV,
        # Position-token mode + a low crossover so every cycle's prompt
        # plans a hand-off; small pools keep accounting checks tight.
        "VDT_DISAGG_MIN_PROMPT_TOKENS": str(len(prompt) - 1),
        "VDT_DISAGG_EXPORT_TTL_SECONDS": "10",
        # One layer per chunk: the mock's 2 synthetic layers then need
        # 2 round trips, so the mid-export kill really lands between
        # chunks of one transfer.
        "VDT_DISAGG_CHUNK_LAYERS": "1",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    tmpdir = tempfile.mkdtemp(prefix="vdt_disagg_soak_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    expected = list(range(len(prompt), len(prompt) + max_tokens))

    def mk_engine() -> AsyncLLM:
        return AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=96,
                page_size=page_size,
                max_model_len=2 * len(prompt),
                num_decode_steps=1,
                enable_prefix_caching=True,
                distributed_executor_backend=MockUniProcExecutor,
            )
        )

    stats = {
        "admitted": 0,
        "completed": 0,
        "mismatches": 0,
        "lost": 0,
        "leaks": 0,
    }
    stalls: list[float] = []

    async def go() -> dict:
        import aiohttp

        roles = ["prefill", "decode"]
        engines: list = [mk_engine() for _ in roles]
        ports = [get_open_port() for _ in roles]
        runners: list = [None] * len(roles)

        async def start_replica(i: int) -> None:
            state = init_app_state(
                engines[i],
                served_model_name="disagg-soak",
                replica_id=f"replica-{roles[i]}",
                role=roles[i],
            )
            for _ in range(50):
                try:
                    runners[i] = await serve_http(
                        build_app(state),
                        host="127.0.0.1",
                        port=ports[i],
                        shutdown_timeout=0.05,
                    )
                    return
                except OSError:
                    await asyncio.sleep(0.1)
            raise RuntimeError(f"could not rebind replica {i}")

        for i in range(len(roles)):
            await start_replica(i)
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        router_state = RouterState(
            urls,
            policy="least_loaded",
            health_interval=0.3,
            connect_timeout=2,
            read_timeout=30,
        )
        router_port = get_open_port()
        router_runner = await serve_http(
            build_router_app(router_state),
            host="127.0.0.1",
            port=router_port,
        )
        router_url = f"http://127.0.0.1:{router_port}"
        timeout = aiohttp.ClientTimeout(total=None, sock_read=60)

        async def kill_prefill() -> None:
            runner, runners[0] = runners[0], None
            if runner is not None:
                await runner.cleanup()
            engines[0].shutdown()

        async def revive_prefill() -> None:
            try:
                engines[0].shutdown()
            except Exception:  # noqa: BLE001 — already dead
                pass
            engines[0] = mk_engine()
            await start_replica(0)
            # Let the health poll re-learn the replica and its role.
            await asyncio.sleep(0.6)

        async def one_stream(session, tag: str) -> None:
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status != 200:
                        stats["lost"] += 1
                        return
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    last = time.monotonic()
                    worst_gap = 0.0
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            break
                        now = time.monotonic()
                        worst_gap = max(worst_gap, now - last)
                        last = now
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                    stalls.append(worst_gap)
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                        print(
                            f"{tag}: TOKEN MISMATCH {toks} != {expected}",
                            file=sys.stderr,
                        )
                    else:
                        stats["completed"] += 1
            except Exception as e:  # noqa: BLE001 — an admitted stream erroring out IS lost work
                stats["lost"] += 1
                print(f"{tag}: stream error {e}", file=sys.stderr)

        def check_decode_accounting(tag: str) -> None:
            """No leaked pages on the surviving decode replica: every
            transfer settled (imports/holds empty) and every page either
            free or cached-free (live requests all finished)."""
            engine = engines[1].engine
            kvt = engine.kv_transfer
            allocator = engine.scheduler.allocator
            usable = allocator.num_pages - 1
            ok = (
                not kvt.imports
                and not kvt.holds
                and allocator.num_free_pages == usable
            )
            if not ok:
                stats["leaks"] += 1
                print(
                    f"{tag}: PAGE LEAK imports={len(kvt.imports)} "
                    f"holds={len(kvt.holds)} "
                    f"free={allocator.num_free_pages}/{usable}",
                    file=sys.stderr,
                )

        async def cycle(session, n: int) -> None:
            mode = (
                "planned"
                if n == 0
                else ("mid_handoff" if n % 2 else "mid_export")
            )
            fired = asyncio.Event()

            async def seam_kill() -> None:
                if fired.is_set():
                    return
                fired.set()
                await kill_prefill()

            disagg._test_before_transfer = (
                seam_kill if mode == "mid_handoff" else None
            )

            async def after_chunk(idx: int) -> None:
                if idx == 1:
                    await seam_kill()

            disagg._test_after_chunk = (
                after_chunk if mode == "mid_export" else None
            )
            try:
                await asyncio.wait_for(
                    one_stream(session, f"cycle{n}-{mode}"), timeout=90
                )
            finally:
                disagg._test_before_transfer = None
                disagg._test_after_chunk = None
            # Let aborts/releases settle, then audit the decode pool.
            await asyncio.sleep(0.3)
            check_decode_accounting(f"cycle{n}-{mode}")
            if mode != "planned":
                await revive_prefill()

        async with aiohttp.ClientSession() as session:
            for n in range(cycles):
                await cycle(session, n)
            async with session.get(
                f"{router_url}/router/state",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                router_counters = (await resp.json())["counters"]
        await router_runner.cleanup()
        for runner in runners:
            if runner is not None:
                await runner.cleanup()
        for engine in engines:
            try:
                engine.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        return router_counters

    try:
        router_counters = (
            asyncio.new_event_loop().run_until_complete(go())
        )
        handoffs = {
            k: v
            for k, v in router_counters.items()
            if k.startswith("handoffs.")
        }
        migrations = sum(
            v
            for k, v in router_counters.items()
            if k.startswith("migrations.")
        )
        report = {
            "mode": "disagg",
            "cycles": cycles,
            **stats,
            "handoffs": handoffs,
            "migrations": migrations,
            "router_counters": router_counters,
            "stall_seconds": {
                "p50": round(_percentile(stalls, 0.5), 3),
                "max": round(max(stalls), 3) if stalls else 0.0,
            },
            # The acceptance contract: zero lost admitted work, greedy
            # bit-identity across every fallback, a real planned
            # hand-off observed, fallbacks engaged on the kills, no
            # leaked pages, and the happy path never burned migration
            # budget.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and stats["leaks"] == 0
                and handoffs.get("handoffs.planned", 0) >= 1
                and handoffs.get("handoffs.fallback", 0)
                >= max(cycles - 1, 0)
                and (not stalls or max(stalls) <= stall_bound_s)
            ),
        }
        return report
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------
# Partition chaos (ISSUE 19): the full disaggregated pool behind
# per-replica fault-injection TCP proxies (tools/net_chaos.py), with
# the resilience stack armed — breakers, retry budget, adaptive
# deadlines, hedging, resumable KV transfer.  Asserts zero lost
# admitted work and bit-identical streams under 5% chunk drop + 200ms
# jitter, a healed full partition of one replica mid-stream (breaker
# walks open -> half-open -> closed) and mid-hand-off (>=1 KV transfer
# completed via chunk resume, not recompute fallback), with retry
# amplification staying inside the configured budget ratio.
# ---------------------------------------------------------------------
def run_partition_soak(
    cycles: int = 4,
    *,
    max_tokens: int = 8,
    prompt_pages: int = 3,
    stall_bound_s: float = 30.0,
) -> dict:
    """Alternating cycles over a prefill + 2x decode mock pool, every
    router<->replica link shaped by a seeded ChaosProxy.  Even cycles
    ("handoff_resume") stream one long prompt and partition the decode
    links for ~0.5s right after the first KV chunk lands — the transfer
    must finish via the resume_from protocol.  Odd cycles ("partition")
    stream a short prompt under 5% drop + 200ms jitter and fully
    partition the replica serving it mid-stream for ~4s — the stream
    must migrate and finish bit-identically, and the victim's breaker
    must walk open -> half-open -> closed after the heal.

    Mutates (and restores) os.environ; call from a dedicated process or
    a test that tolerates env churn."""
    import asyncio

    from tests.mock_worker import MockUniProcExecutor
    from tools.net_chaos import ChaosProxy
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )
    from vllm_distributed_tpu.router import disagg
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    page_size = 16
    long_len = prompt_pages * page_size

    def long_prompt_for(idx: int) -> list[int]:
        # Content-unique per stream (length fixed): a repeated prompt
        # would be fully prefix-cached decode-side after the first
        # hand-off, so every later transfer would adopt zero pages and
        # count as a fallback even when the resumed chunk stream itself
        # succeeded.  Output tokens are position-indexed
        # (VDT_MOCK_TOKEN_SEQ), so the expected sequence depends only
        # on the length.
        return [(idx * 37 + i) % 900 + 1 for i in range(long_len)]

    short_prompt = [1, 2, 3]
    env = {
        **ROUTER_AGENT_ENV,
        "VDT_DISAGG_MIN_PROMPT_TOKENS": str(long_len - 1),
        "VDT_DISAGG_EXPORT_TTL_SECONDS": "15",
        "VDT_DISAGG_CHUNK_LAYERS": "1",
        # The resilience stack under test (ISSUE 19).
        "VDT_ROUTER_BREAKER_FAILURES": "3",
        "VDT_ROUTER_BREAKER_COOLDOWN_SECONDS": "1",
        "VDT_ROUTER_RETRY_BUDGET_RATIO": "0.5",
        "VDT_ROUTER_RETRY_BUDGET_MIN": "10",
        "VDT_ROUTER_ADAPTIVE_DEADLINE": "1",
        "VDT_ROUTER_DEADLINE_FLOOR_SECONDS": "2",
        "VDT_ROUTER_HEDGE": "1",
        "VDT_ROUTER_HEDGE_MIN_DELAY_MS": "100",
        # Generous cap: breaker-cooldown rejections during the healed
        # partition count as chunk failures too, and the resume loop
        # must outlast the ~1s cooldown on its linear backoff.
        "VDT_ROUTER_KV_CHUNK_RETRIES": "8",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    tmpdir = tempfile.mkdtemp(prefix="vdt_partition_soak_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    long_expected = list(range(long_len, long_len + max_tokens))
    short_expected = list(
        range(len(short_prompt), len(short_prompt) + max_tokens)
    )

    def mk_engine() -> AsyncLLM:
        return AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=96,
                page_size=page_size,
                max_model_len=2 * long_len,
                num_decode_steps=1,
                enable_prefix_caching=True,
                distributed_executor_backend=MockUniProcExecutor,
            )
        )

    stats = {
        "admitted": 0,
        "completed": 0,
        "mismatches": 0,
        "lost": 0,
        "rejected": 0,
        "resumed_transfers": 0,
        "breaker_walks": 0,
        "degraded_alerts": 0,
    }
    stalls: list[float] = []

    async def go() -> dict:
        import aiohttp

        roles = ["prefill", "decode", "decode"]
        engines: list = [mk_engine() for _ in roles]
        ports = [get_open_port() for _ in roles]
        runners: list = [None] * len(roles)
        proxies = [
            ChaosProxy("127.0.0.1", ports[i], seed=1000 + i)
            for i in range(len(roles))
        ]
        for proxy in proxies:
            await proxy.start()
        decode_idx = [i for i, r in enumerate(roles) if r == "decode"]

        async def start_replica(i: int) -> None:
            state = init_app_state(
                engines[i],
                served_model_name="partition-soak",
                replica_id=f"replica-{i}-{roles[i]}",
                role=roles[i],
            )
            for _ in range(50):
                try:
                    runners[i] = await serve_http(
                        build_app(state),
                        host="127.0.0.1",
                        port=ports[i],
                        shutdown_timeout=0.05,
                    )
                    return
                except OSError:
                    await asyncio.sleep(0.1)
            raise RuntimeError(f"could not rebind replica {i}")

        for i in range(len(roles)):
            await start_replica(i)
        # The router only ever sees the proxies.
        router_state = RouterState(
            [p.url for p in proxies],
            policy="least_loaded",
            health_interval=0.3,
            connect_timeout=2,
            read_timeout=30,
        )
        router_port = get_open_port()
        router_runner = await serve_http(
            build_router_app(router_state),
            host="127.0.0.1",
            port=router_port,
        )
        router_url = f"http://127.0.0.1:{router_port}"
        timeout = aiohttp.ClientTimeout(total=None, sock_read=90)

        def arm_baseline(drop: float) -> None:
            for proxy in proxies:
                proxy.arm(
                    latency_ms=0.0, jitter_ms=200.0, drop_prob=drop
                )

        async def router_snapshot(session) -> dict:
            async with session.get(
                f"{router_url}/router/state",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                return await resp.json()

        async def one_stream(
            session,
            tag: str,
            prompt,
            expected,
            on_tokens=None,
            served: dict | None = None,
        ) -> None:
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status == 429:
                        stats["rejected"] += 1
                        return
                    if resp.status != 200:
                        stats["lost"] += 1
                        print(
                            f"{tag}: HTTP {resp.status} "
                            f"{(await resp.text())[:200]}",
                            file=sys.stderr,
                        )
                        return
                    if served is not None:
                        served["id"] = resp.headers.get(
                            "X-VDT-Replica-Id", ""
                        )
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    last = time.monotonic()
                    worst_gap = 0.0
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            print(
                                f"{tag}: error frame {obj}",
                                file=sys.stderr,
                            )
                            break
                        now = time.monotonic()
                        worst_gap = max(worst_gap, now - last)
                        last = now
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                        if on_tokens is not None:
                            await on_tokens(len(toks))
                    stalls.append(worst_gap)
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                        print(
                            f"{tag}: TOKEN MISMATCH {toks} != {expected}",
                            file=sys.stderr,
                        )
                    else:
                        stats["completed"] += 1
            except Exception as e:  # noqa: BLE001 — an admitted stream erroring out IS lost work
                stats["lost"] += 1
                print(f"{tag}: stream error {e}", file=sys.stderr)

        heal_tasks: list = []

        async def handoff_resume_cycle(session, n: int) -> None:
            """Long prompt; partition the decode links for ~0.5s right
            after the first export->import chunk round trip.  The
            hand-off must complete via chunk resume, not fallback."""
            # Deterministic fault: only the seam partition, no random
            # drop, so exactly one resume cycle is forced.
            arm_baseline(0.0)
            before = await router_snapshot(session)
            fired = asyncio.Event()

            healed = asyncio.Event()

            def lift() -> None:
                if not healed.is_set():
                    healed.set()
                    for i in decode_idx:
                        proxies[i].arm(partitioned=False)

            async def after_chunk(idx: int) -> None:
                if idx != 1 or fired.is_set():
                    return
                fired.set()
                for i in decode_idx:
                    proxies[i].arm(partitioned=True)

                async def backstop() -> None:
                    # The failure seam below heals the instant the
                    # partition has bitten; this only guards a cycle
                    # where it somehow never does.
                    try:
                        await asyncio.wait_for(healed.wait(), timeout=5.0)
                    except asyncio.TimeoutError:
                        pass
                    lift()

                heal_tasks.append(asyncio.ensure_future(backstop()))

            async def after_chunk_failure(failure_count: int) -> None:
                # Event-driven heal: lift the partition the moment one
                # chunk round trip has actually been lost, so the
                # resume loop's first backoff (0.25s) always lands on a
                # healed link — a timed heal races event-loop
                # contention (the transfer can straddle or entirely
                # miss a fixed window) and flakes either way.
                lift()

            disagg._test_after_chunk = after_chunk
            disagg._test_after_chunk_failure = after_chunk_failure
            try:
                await asyncio.wait_for(
                    one_stream(
                        session,
                        f"cycle{n}-handoff_resume",
                        long_prompt_for(n + 1),
                        long_expected,
                    ),
                    timeout=120,
                )
            finally:
                disagg._test_after_chunk = None
                disagg._test_after_chunk_failure = None
            for task in heal_tasks:
                await task
            heal_tasks.clear()
            after = await router_snapshot(session)

            def ctr(snap: dict, key: str) -> float:
                return snap["counters"].get(key, 0)

            if ctr(after, "handoffs.planned") > ctr(
                before, "handoffs.planned"
            ) and ctr(after, "kv.transfer_resumes") > ctr(
                before, "kv.transfer_resumes"
            ):
                stats["resumed_transfers"] += 1
            else:
                print(
                    f"cycle{n}: hand-off did not complete via chunk "
                    "resume",
                    file=sys.stderr,
                )

        async def partition_cycle(session, n: int) -> None:
            """Short prompts under 5% drop + 200ms jitter; fully
            partition the replica serving the victim mid-stream, heal
            after ~1.2s, and require the breaker walk."""
            arm_baseline(0.05)
            fired = asyncio.Event()
            served: dict = {}
            victim: dict = {}

            async def trigger(count: int) -> None:
                if fired.is_set() or count < 2 or "id" not in served:
                    return
                fired.set()
                idx = int(served["id"].split("-")[1])
                victim["index"] = idx
                victim["rid"] = served["id"]
                proxies[idx].arm(partitioned=True)

                async def heal() -> None:
                    # Long enough for 3 consecutive probe failures to
                    # trip the breaker before the heal: probe rounds
                    # run well below the nominal 0.3s interval here —
                    # every link pays 200ms jitter each way, hedges
                    # add their own delay, and probe_all gathers the
                    # whole pool — so a round takes ~1s in practice.
                    await asyncio.sleep(4.0)
                    proxies[idx].arm(partitioned=False)

                heal_tasks.append(asyncio.ensure_future(heal()))

            loaders = [
                one_stream(
                    session,
                    f"cycle{n}-load{j}",
                    short_prompt,
                    short_expected,
                )
                for j in range(2)
            ]
            await asyncio.wait_for(
                asyncio.gather(
                    one_stream(
                        session,
                        f"cycle{n}-victim",
                        short_prompt,
                        short_expected,
                        trigger,
                        served,
                    ),
                    *loaders,
                ),
                timeout=120,
            )
            for task in heal_tasks:
                await task
            heal_tasks.clear()
            rid = victim.get("rid")
            if rid is None:
                print(
                    f"cycle{n}: partition never fired", file=sys.stderr
                )
                return
            # The breaker must walk open -> half-open -> closed once
            # the partition heals (the health probe IS the half-open
            # probe).
            deadline = time.monotonic() + 20
            walked = False
            while time.monotonic() < deadline:
                rz = (await router_snapshot(session)).get(
                    "resilience", {}
                )
                trans = rz.get("breaker_transitions", {})
                if (
                    trans.get(f"{rid}:open", 0) >= 1
                    and trans.get(f"{rid}:half_open", 0) >= 1
                    and rz.get("breakers", {}).get(rid) == "closed"
                ):
                    walked = True
                    break
                await asyncio.sleep(0.3)
            if walked:
                stats["breaker_walks"] += 1
            else:
                print(
                    f"cycle{n}: breaker never walked "
                    f"open->half_open->closed for {rid}",
                    file=sys.stderr,
                )
            # The sentinel (ISSUE 20) must have singled the victim
            # out while the partition held: a degraded/unreachable
            # alert NAMING rid on the bounded /router/alerts feed.
            try:
                async with session.get(
                    f"{router_url}/router/alerts",
                    timeout=aiohttp.ClientTimeout(total=10),
                ) as resp:
                    alerts = (await resp.json()).get("alerts", [])
            except Exception:  # noqa: BLE001 — judged via degraded_alerts below
                alerts = []
            named = [
                a
                for a in alerts
                if a.get("replica_id") == rid
                and a.get("kind")
                in ("replica_degraded", "replica_unreachable")
            ]
            if named:
                stats["degraded_alerts"] += 1
            else:
                print(
                    f"cycle{n}: no sentinel alert named {rid} "
                    f"(alerts={alerts})",
                    file=sys.stderr,
                )

        async with aiohttp.ClientSession() as session:
            # Clean-link warmup: the pool learns its replicas and the
            # latency trackers take their first samples.
            await asyncio.wait_for(
                one_stream(
                    session, "warmup-long", long_prompt_for(0), long_expected
                ),
                timeout=60,
            )
            await asyncio.wait_for(
                one_stream(
                    session, "warmup-short", short_prompt, short_expected
                ),
                timeout=60,
            )
            for n in range(cycles):
                if n % 2 == 0:
                    await handoff_resume_cycle(session, n)
                else:
                    await partition_cycle(session, n)
            final = await router_snapshot(session)
        await router_runner.cleanup()
        for runner in runners:
            if runner is not None:
                await runner.cleanup()
        for engine in engines:
            try:
                engine.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for proxy in proxies:
            await proxy.stop()
        return final

    try:
        final = asyncio.new_event_loop().run_until_complete(go())
        counters = final["counters"]
        rz = final.get("resilience", {})
        budget = rz.get("budget", {})
        granted = budget.get("retries_granted", 0)
        allowance = budget.get("min", 0) + budget.get(
            "ratio", 0
        ) * budget.get("first_attempts", 0)
        n_partition = cycles // 2
        report = {
            "mode": "partition",
            "cycles": cycles,
            **stats,
            "handoffs": {
                k: v
                for k, v in counters.items()
                if k.startswith("handoffs.")
            },
            "kv_transfer_resumes": counters.get(
                "kv.transfer_resumes", 0
            ),
            "budget": budget,
            "breaker_transitions": rz.get("breaker_transitions", {}),
            "router_counters": counters,
            "stall_seconds": {
                "p50": round(_percentile(stalls, 0.5), 3),
                "max": round(max(stalls), 3) if stalls else 0.0,
            },
            # The acceptance contract (ISSUE 19): zero lost admitted
            # work, bit-identical streams through drop + jitter +
            # partitions, at least one hand-off completed via chunk
            # resume (not fallback), at least one breaker walked
            # open -> half-open -> closed, and total retries (including
            # hedges) inside the configured budget.  Per-cycle misses
            # print diagnostics but don't fail the gate: under real
            # partition timing a transfer can legitimately heal through
            # fallback-then-clean-retry instead, which is the stack
            # working, not the contract breaking.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and stats["resumed_transfers"] >= 1
                and stats["breaker_walks"] >= min(n_partition, 1)
                and stats["degraded_alerts"] >= min(n_partition, 1)
                and counters.get("kv.transfer_resumes", 0) >= 1
                and granted <= allowance
                and (not stalls or max(stalls) <= stall_bound_s)
            ),
        }
        return report
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------
# Resize-chaos ramp (ISSUE 13): an autoscaled fleet of managed mock
# replicas under a Poisson rate sweep, with a SIGKILL mid-resize —
# asserting zero lost admitted work, zero token mismatches, every
# scale-down preceded by a drain, and the replica count following the
# ramp up AND down within bounds.
# ---------------------------------------------------------------------
def run_fleet_ramp(
    *,
    max_replicas: int = 3,
    ramp: str = "5:6,14:12,2:6,0:10",
    max_tokens: int = 12,
    kill_mid_resize: bool = True,
    stall_bound_s: float = 45.0,
    autoscale_interval: float = 0.75,
    up_cooldown: float = 1.5,
    down_cooldown: float = 2.5,
) -> dict:
    """Run the resize-chaos ramp; returns the report dict.  Mutates
    (and restores) os.environ — call from a dedicated process or a test
    that tolerates env churn.

    The fleet starts at 1 managed mock-uniproc replica (capacity
    deliberately tiny, max_num_seqs=2, so the sweep genuinely
    overloads one replica); the autoscaler follows the waiting-depth
    signal up to ``max_replicas`` and back down over the idle tail.
    ``kill_mid_resize`` SIGKILLs a serving replica while a scale-up is
    still warming — the crash path, the warmup path, and the migration
    path all land in the same instant, which is exactly the window a
    real resize is most fragile in."""
    import asyncio
    import random

    from tests.mock_replica import MockReplicaLauncher
    from vllm_distributed_tpu.entrypoints.cli import parse_ramp
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.router.fleet import (
        Autoscaler,
        AutoscalerConfig,
        ReplicaManager,
    )
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        serve_http,
    )
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    segments = parse_ramp(ramp)
    saved = {k: os.environ.get(k) for k in ROUTER_AGENT_ENV}
    os.environ.update(ROUTER_AGENT_ENV)
    tmpdir = tempfile.mkdtemp(prefix="vdt_fleet_ramp_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    prompt = [1, 2, 3]
    expected = list(range(len(prompt), len(prompt) + max_tokens))

    stats = {
        "offered": 0,
        "admitted": 0,
        "completed": 0,
        "mismatches": 0,
        "lost": 0,
        "rejected": 0,
    }
    stalls: list[float] = []
    ttfts: list[float] = []
    timeline: list[dict] = []
    kill_info: dict = {}

    async def go() -> dict:
        import aiohttp

        launcher = MockReplicaLauncher(
            model_dir, extra_env=dict(ROUTER_AGENT_ENV)
        )
        state = RouterState(
            [],
            policy="least_loaded",
            health_interval=0.25,
            connect_timeout=2,
            # Generous per-read deadline: at peak the sweep deliberately
            # overloads the fleet, so a (re)queued request can sit well
            # over 30s before its first token — that silence is the
            # scale-up SIGNAL, not a dead replica.
            read_timeout=60,
            allow_empty_pool=True,
        )
        manager = ReplicaManager(
            state.pool,
            state.metrics,
            launcher,
            target=1,
            warmup_timeout=60,
            drain_timeout=10,
            check_interval=0.2,
            max_restarts=10,
            restart_window=3600,
            backoff_base=0.2,
            backoff_cap=1.0,
        )
        autoscaler = Autoscaler(
            manager,
            state.pool,
            state.metrics,
            AutoscalerConfig(
                min_replicas=1,
                max_replicas=max_replicas,
                interval=autoscale_interval,
                up_waiting=2.0,
                down_waiting=0.5,
                up_cooldown=up_cooldown,
                down_cooldown=down_cooldown,
            ),
        )
        state.attach_fleet(manager, autoscaler)
        router_port = get_open_port()
        router_runner = await serve_http(
            build_router_app(state), host="127.0.0.1", port=router_port
        )
        router_url = f"http://127.0.0.1:{router_port}"
        # The client outlasts worst-case queue wait + migrations: a
        # stream the fleet admitted must be given time to finish, or
        # the harness manufactures its own "lost work".
        timeout = aiohttp.ClientTimeout(total=None, sock_read=150)

        async def one_stream(session, tag: str) -> None:
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status == 429:
                        stats["rejected"] += 1
                        return
                    if resp.status != 200:
                        stats["lost"] += 1
                        return
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    req_t0 = time.monotonic()
                    last = None
                    worst_gap = 0.0
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            break  # router gave up: lost work
                        now = time.monotonic()
                        if last is None:
                            # Queue wait under deliberate overload is
                            # the scale-up signal, reported as TTFT;
                            # the STALL bound judges mid-stream
                            # blackouts (kills, drains, migrations).
                            ttfts.append(now - req_t0)
                        else:
                            worst_gap = max(worst_gap, now - last)
                        last = now
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                    stalls.append(worst_gap)
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                        print(
                            f"{tag}: TOKEN MISMATCH {toks} != {expected}",
                            file=sys.stderr,
                        )
                    else:
                        stats["completed"] += 1
            except Exception as e:  # noqa: BLE001 — an admitted stream erroring out IS lost work
                stats["lost"] += 1
                print(f"{tag}: stream error {e}", file=sys.stderr)

        async def sampler(stop: "asyncio.Event") -> None:
            while not stop.is_set():
                timeline.append(
                    {
                        "mono": round(time.monotonic(), 2),
                        "target": manager.target,
                        "ready": manager.ready_count(),
                    }
                )
                await asyncio.sleep(0.2)

        async def chaos(stop: "asyncio.Event") -> None:
            """SIGKILL a serving replica while a scale-up is still
            warming (fallback: once a survivor exists), exactly once."""
            while not stop.is_set():
                ready = manager.ready_count()
                starting = any(
                    r.state == "starting" for r in manager.replicas
                )
                if ready >= 2 and (starting or manager.target >= 3):
                    victims = [
                        r for r in manager.replicas if r.state == "ready"
                    ]
                    victim = victims[0]
                    kill_info.update(
                        {
                            "replica_id": victim.replica_id,
                            "mono": round(time.monotonic(), 2),
                            "during_scale_event": starting,
                            "fleet_ready_at_kill": ready,
                        }
                    )
                    victim.handle.kill()
                    return
                await asyncio.sleep(0.1)

        async with aiohttp.ClientSession() as session:
            # Wait out the first warmup: the ramp measures resize
            # behavior, not cold boot.
            deadline = time.monotonic() + 90
            while manager.ready_count() < 1:
                if time.monotonic() > deadline:
                    raise RuntimeError("first replica never became ready")
                await asyncio.sleep(0.1)
            stop = asyncio.Event()
            aux = [
                asyncio.ensure_future(sampler(stop)),
            ]
            if kill_mid_resize:
                aux.append(asyncio.ensure_future(chaos(stop)))
            rng = random.Random(1234)
            tasks: list = []
            idx = 0
            try:
                for rate, dur in segments:
                    seg_t0 = time.monotonic()
                    while True:
                        remaining = dur - (time.monotonic() - seg_t0)
                        if remaining <= 0:
                            break
                        if rate <= 0:
                            await asyncio.sleep(remaining)
                            break
                        stats["offered"] += 1
                        tasks.append(
                            asyncio.ensure_future(
                                one_stream(session, f"ramp-{idx}")
                            )
                        )
                        idx += 1
                        await asyncio.sleep(
                            min(rng.expovariate(rate), remaining)
                        )
                if tasks:
                    await asyncio.wait_for(
                        asyncio.gather(*tasks), timeout=240
                    )
                # Let the autoscaler walk the fleet back to min over
                # the idle tail (bounded).
                settle_deadline = time.monotonic() + (
                    3 * down_cooldown + 10
                )
                while (
                    manager.target > 1
                    or manager.ready_count() > 1
                    or len(manager.active()) > 1
                ):
                    if time.monotonic() > settle_deadline:
                        break
                    await asyncio.sleep(0.2)
                timeline.append(
                    {
                        "mono": round(time.monotonic(), 2),
                        "target": manager.target,
                        "ready": manager.ready_count(),
                    }
                )
            finally:
                stop.set()
                for t in aux:
                    t.cancel()
            events = list(manager.events)
            decisions = list(autoscaler.decisions)
            final = {
                "target": manager.target,
                "ready": manager.ready_count(),
            }
        await router_runner.cleanup()  # drains + reaps the fleet
        return {
            "events": events,
            "decisions": decisions,
            "final": final,
            "leaked": launcher.leaked(),
        }

    try:
        out = asyncio.new_event_loop().run_until_complete(go())
        events = out["events"]
        # Drain-before-stop ordering: every replica that ever served
        # (has a "ready" event) and was stopped by the manager must
        # show a "drain" event before its "stopped" event.  Crashed
        # replicas (the SIGKILL chaos) never get a "stopped" event —
        # they get "crash" — so they don't relax the invariant.
        ready_ids = {
            e["replica_id"] for e in events if e["kind"] == "ready"
        }
        drained_before_stop = True
        drained_ids = set()
        for e in events:
            if e["kind"] == "drain":
                drained_ids.add(e["replica_id"])
            elif e["kind"] == "stopped" and e["replica_id"] in ready_ids:
                if e["replica_id"] not in drained_ids:
                    drained_before_stop = False
        max_ready = max((s["ready"] for s in timeline), default=0)
        scaled_up = any(
            e["kind"] == "scale" and e["to"] > e["from_target"]
            for e in events
        )
        scaled_down = any(
            e["kind"] == "scale" and e["to"] < e["from_target"]
            for e in events
        )
        report = {
            "mode": "fleet_ramp",
            "ramp": ramp,
            "max_replicas": max_replicas,
            **stats,
            "kill": kill_info or None,
            "max_ready_observed": max_ready,
            "final": out["final"],
            "scaled_up": scaled_up,
            "scaled_down": scaled_down,
            "drained_before_stop": drained_before_stop,
            "restarts_total": len(
                [e for e in events if e["kind"] == "crash"]
            ),
            "decisions": out["decisions"],
            "leaked_children": out["leaked"],
            "stall_seconds": {
                "p50": round(_percentile(stalls, 0.5), 3),
                "max": round(max(stalls), 3) if stalls else 0.0,
            },
            "ttft_seconds": {
                "p50": round(_percentile(ttfts, 0.5), 3),
                "p99": round(_percentile(ttfts, 0.99), 3),
                "max": round(max(ttfts), 3) if ttfts else 0.0,
            },
            # The acceptance contract (ISSUE 13): no admitted stream
            # lost or corrupted through any resize or the mid-resize
            # kill; the fleet followed the ramp up AND down within
            # bounds; every scale-down drained first; no child leaked.
            # When the kill is armed it must have actually FIRED — a
            # sweep that never reached the chaos window proved nothing
            # about the resize-kill collision and must not pass.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and scaled_up
                and scaled_down
                and max_ready <= max_replicas
                and drained_before_stop
                and not out["leaked"]
                and (not kill_mid_resize or bool(kill_info))
                and (not stalls or max(stalls) <= stall_bound_s)
            ),
        }
        return report
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------
# Router-kill chaos (ISSUE 17): SIGKILL the ROUTER ITSELF — mid-stream
# and mid-scale-up, with durable state on — then restart it against the
# same state dir and assert the restarted incarnation re-adopts every
# recorded child (zero leaked, zero double-spawned processes) and
# finishes every admitted in-flight stream bit-identically through the
# X-VDT-Resume-Id / X-VDT-Resume-Tokens reconnect protocol.
# ---------------------------------------------------------------------
def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


async def _stall_one_child(session, router_url: str, snap: dict) -> bool:
    """SIGSTOP one live replica child, require a sentinel alert naming
    it on /router/alerts, SIGCONT, and wait for it to probe healthy
    again.  Returns whether the named alert fired."""
    import asyncio
    import signal

    import aiohttp

    victims = [
        x
        for x in (snap.get("replicas") or [])
        if x.get("pid") and _pid_alive(int(x["pid"]))
    ]
    if not victims:
        print("sentinel check: no live child to stall", file=sys.stderr)
        return False
    victim = victims[0]
    rid, pid = victim["replica_id"], int(victim["pid"])
    os.kill(pid, signal.SIGSTOP)
    named = False
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not named:
            try:
                async with session.get(
                    f"{router_url}/router/alerts",
                    timeout=aiohttp.ClientTimeout(total=5),
                ) as resp:
                    alerts = (await resp.json()).get("alerts", [])
            except Exception:  # noqa: BLE001 — poll until the deadline judges it
                alerts = []
            named = any(
                a.get("replica_id") == rid
                and a.get("kind")
                in ("replica_degraded", "replica_unreachable")
                for a in alerts
            )
            if not named:
                await asyncio.sleep(0.25)
    finally:
        os.kill(pid, signal.SIGCONT)
    if not named:
        print(
            f"sentinel check: no alert named {rid} while stalled",
            file=sys.stderr,
        )
    # Thaw back to healthy so teardown drains cleanly.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            async with session.get(
                f"{router_url}/router/state",
                timeout=aiohttp.ClientTimeout(total=5),
            ) as resp:
                pool = (await resp.json()).get("replicas", [])
            if any(
                r.get("replica_id") == rid and r.get("state") == "healthy"
                for r in pool
            ):
                break
        except Exception:  # noqa: BLE001 — router busy; keep polling
            pass
        await asyncio.sleep(0.25)
    return named


def run_router_kill(
    *,
    cycles: int = 1,
    fleet_size: int = 2,
    scale_to: int = 3,
    streams: int = 3,
    max_tokens: int = 48,
    kill_after_tokens: int = 4,
    token_sleep_s: float = 0.2,
) -> dict:
    """Run the router-kill chaos cycle(s); returns the report dict.

    Unlike the other phases the router here is a REAL subprocess
    (``python -m vllm_distributed_tpu.entrypoints.cli router``) so it
    can be SIGKILLed like a crashed process, with ``--state-dir``
    pointed at a WAL this harness also reads back directly
    (``router.persist.load_state``) to check what the dead incarnation
    managed to record.  Children are ``tests.mock_replica`` processes
    spawned BY the router through ``--fleet-cmd``; they live in their
    own sessions, so the router SIGKILL orphans them — exactly the
    re-adoption scenario."""
    import asyncio
    import signal
    import subprocess

    from vllm_distributed_tpu.router.persist import load_state
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    tmpdir = tempfile.mkdtemp(prefix="vdt_router_kill_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    state_dir = os.path.join(tmpdir, "router-state")
    prompt = [1, 2, 3]
    expected = list(range(len(prompt), len(prompt) + max_tokens))
    router_port = get_open_port()
    router_url = f"http://127.0.0.1:{router_port}"

    env = {
        **os.environ,
        **ROUTER_AGENT_ENV,
        # Slow token cadence: streams must still be mid-flight after
        # the scale POST when the SIGKILL lands.
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": str(token_sleep_s),
        # Near-line-rate WAL freshness, and a verify window generous
        # enough for a child that was still BOOTING when the router
        # died (the mid-scale-up spawn) to come up and answer.
        "VDT_ROUTER_STATE_CKPT_INTERVAL_SECONDS": "0.05",
        "VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS": "0.05",
        "VDT_ROUTER_STATE_VERIFY_WINDOW_SECONDS": "60",
        # Decouple the journal TTL from the adoption poll bound: a slow
        # adoption must surface as adoption_complete=false, not cascade
        # into expired-journal replay refusals (lost work).
        "VDT_ROUTER_STATE_RECOVERY_TTL_SECONDS": "600",
        "PYTHONPATH": _REPO_ROOT
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }
    argv = [
        sys.executable,
        "-m",
        "vllm_distributed_tpu.entrypoints.cli",
        "router",
        "--host",
        "127.0.0.1",
        "--port",
        str(router_port),
        "--fleet-size",
        str(fleet_size),
        "--fleet-cmd",
        f"{sys.executable} -m tests.mock_replica --port {{port}} "
        f"--model-dir {model_dir}",
        "--state-dir",
        state_dir,
        "--health-interval",
        "0.25",
    ]

    stats = {
        "offered": 0,
        "admitted": 0,
        "completed": 0,
        "resumed": 0,
        "interrupted": 0,
        "mismatches": 0,
        "lost": 0,
    }

    def spawn_router() -> "subprocess.Popen":
        return subprocess.Popen(argv, env=env, cwd=_REPO_ROOT)  # vdt-lint: disable=thread-leak — waited on every kill/teardown path below

    async def go() -> dict:
        import aiohttp

        async def fleet_snap(session) -> dict | None:
            try:
                async with session.get(
                    f"{router_url}/router/fleet",
                    timeout=aiohttp.ClientTimeout(total=5),
                ) as r:
                    if r.status != 200:
                        return None
                    return await r.json()
            except Exception:  # noqa: BLE001 — router (re)booting
                return None

        async def wait_ready(session, want: int, bound_s: float) -> dict:
            deadline = time.monotonic() + bound_s
            while time.monotonic() < deadline:
                snap = await fleet_snap(session)
                if snap is not None and snap["ready"] >= want:
                    return snap
                await asyncio.sleep(0.2)
            raise RuntimeError(
                f"fleet never reached {want} ready replica(s)"
            )

        async def one_stream(session, rec: dict) -> None:
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            headers = {"X-VDT-Router": "1"}
            if rec.get("rid"):
                # Reconnect after the router kill: echo the request id
                # back and declare exactly what we already hold so the
                # journal rewinds/fast-forwards to OUR position.
                headers["X-VDT-Resume-Id"] = rec["rid"]
                headers["X-VDT-Resume-Tokens"] = (
                    f"{len(rec['toks'])}:{len(rec['text'])}"
                )
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=None, sock_read=120
                    ),
                ) as resp:
                    if resp.status != 200:
                        rec["status"] = resp.status
                        return
                    rid = resp.headers.get("X-VDT-Request-Id")
                    if rid:
                        rec["rid"] = rid
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            rec["done"] = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            rec["router_error"] = obj["error"]
                            break
                        for ch in obj.get("choices") or ():
                            rec["toks"] += ch.get("vdt_token_ids") or []
                            rec["text"] += ch.get("text") or ""
            except Exception as e:  # noqa: BLE001 — the router SIGKILL severs streams by design
                rec["conn_error"] = str(e)

        per_cycle: list[dict] = []
        all_pids: set[int] = set()
        proc = spawn_router()
        try:
            async with aiohttp.ClientSession() as session:
                await wait_ready(session, fleet_size, 180)
                for cyc in range(cycles):
                    crep: dict = {"cycle": cyc}
                    recs = [
                        {"toks": [], "text": "", "done": False}
                        for _ in range(streams)
                    ]
                    stats["offered"] += streams
                    tasks = [
                        asyncio.ensure_future(one_stream(session, r))
                        for r in recs
                    ]
                    # Let every stream get admitted and mid-flight.
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline:
                        if all(
                            r.get("rid")
                            and len(r["toks"]) >= kill_after_tokens
                            for r in recs
                        ) or all(t.done() for t in tasks):
                            break
                        await asyncio.sleep(0.05)
                    # Kick off a scale-up and catch it mid-warmup.
                    try:
                        async with session.post(
                            f"{router_url}/router/scale",
                            json={"replicas": scale_to},
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as r:
                            crep["scale_ack"] = r.status == 200
                    except Exception:  # noqa: BLE001 — judged via killed_mid_scale_up below
                        crep["scale_ack"] = False
                    mid_scale = False
                    sdl = time.monotonic() + 5
                    while time.monotonic() < sdl:
                        snap = await fleet_snap(session)
                        if snap is None:
                            break
                        states = [
                            x["state"] for x in snap["replicas"]
                        ]
                        if (
                            len(snap["replicas"]) > fleet_size
                            or "starting" in states
                        ):
                            mid_scale = True
                            break
                        await asyncio.sleep(0.05)
                    crep["killed_mid_scale_up"] = mid_scale
                    # The crash: SIGKILL, no goodbyes.
                    proc.kill()
                    proc.wait()
                    await asyncio.gather(*tasks, return_exceptions=True)
                    interrupted = [
                        r for r in recs if r.get("rid") and not r["done"]
                    ]
                    stats["interrupted"] += len(interrupted)
                    # Read the dead incarnation's WAL directly: the
                    # children must be recorded AND still alive, and
                    # every severed stream's journal must be there.
                    recovered = load_state(state_dir)
                    wal_pids = {
                        int(v["pid"])
                        for v in recovered.replicas.values()
                        if v.get("pid")
                    }
                    all_pids |= wal_pids
                    crep["wal_replicas"] = len(recovered.replicas)
                    crep["children_survived_kill"] = bool(
                        wal_pids
                    ) and all(_pid_alive(p) for p in wal_pids)
                    crep["journaled_inflight"] = all(
                        r["rid"] in recovered.journals
                        for r in interrupted
                    )
                    # Restart against the same state dir.
                    proc = spawn_router()
                    await wait_ready(session, 1, 180)
                    # Adoption must complete: every adopt must verify
                    # (fresh spawns only ever cover dead-pid shortfall).
                    adopted: set[str] = set()
                    verified: set[str] = set()
                    snap: dict = {}
                    vdl = time.monotonic() + 120
                    while time.monotonic() < vdl:
                        snap = await fleet_snap(session) or {}
                        events = snap.get("events") or []
                        adopted = {
                            e["replica_id"]
                            for e in events
                            if e["kind"] == "adopt"
                        }
                        verified = {
                            e["replica_id"]
                            for e in events
                            if e["kind"] == "adopt_verified"
                        }
                        if adopted and adopted <= verified:
                            break
                        await asyncio.sleep(0.2)
                    crep["adopted"] = sorted(adopted)
                    crep["adoption_complete"] = bool(
                        adopted
                    ) and adopted <= verified
                    crep["double_spawns"] = len(
                        [
                            e
                            for e in (snap.get("events") or [])
                            if e["kind"] == "spawn"
                            and e["replica_id"] in adopted
                        ]
                    )
                    snap_pids = {
                        int(x["pid"])
                        for x in (snap.get("replicas") or [])
                        if x.get("pid")
                    }
                    all_pids |= snap_pids
                    crep["pids_preserved"] = {
                        int(x["pid"])
                        for x in (snap.get("replicas") or [])
                        if x.get("pid") and x["replica_id"] in adopted
                    } <= wal_pids
                    # Replay every severed stream through the reconnect
                    # protocol; tokens must concatenate bit-identically.
                    rtasks = [
                        asyncio.ensure_future(one_stream(session, r))
                        for r in interrupted
                    ]
                    if rtasks:
                        await asyncio.wait_for(
                            asyncio.gather(
                                *rtasks, return_exceptions=True
                            ),
                            timeout=180,
                        )
                    for r in recs:
                        if r.get("rid"):
                            stats["admitted"] += 1
                        if not r.get("rid") or not r["done"]:
                            stats["lost"] += 1
                        elif r["toks"] != expected:
                            stats["mismatches"] += 1
                            print(
                                f"cycle {cyc}: TOKEN MISMATCH "
                                f"{r['toks']} != {expected}",
                                file=sys.stderr,
                            )
                        else:
                            stats["completed"] += 1
                            if r in interrupted:
                                stats["resumed"] += 1
                    # Sentinel check (ISSUE 20): freeze one adopted
                    # child (SIGSTOP — alive but silent, the degraded-
                    # replica shape) and require the restarted router's
                    # sentinel to raise an alert NAMING it, then thaw
                    # and wait for it to probe healthy again.
                    crep["degraded_alert"] = await _stall_one_child(
                        session, router_url, snap
                    )
                    per_cycle.append(crep)
                # Graceful goodbye: SIGTERM drains and reaps the fleet.
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=90)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        # Zero-leak scan over every child pid we ever saw (WAL records
        # + fleet snapshots), with a short grace for teardown.
        leaked: list[int] = []
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            leaked = [p for p in sorted(all_pids) if _pid_alive(p)]
            if not leaked:
                break
            await asyncio.sleep(0.25)
        for pid in leaked:  # clean up, but still report the failure
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        return {"cycles": per_cycle, "leaked": leaked}

    try:
        out = asyncio.new_event_loop().run_until_complete(go())
        per = out["cycles"]
        report = {
            "mode": "router_kill",
            "fleet_size": fleet_size,
            "scale_to": scale_to,
            **stats,
            "cycles_detail": per,
            "leaked_children": out["leaked"],
            # The acceptance contract (ISSUE 17): the kill really
            # landed mid-stream AND mid-scale-up; every recorded child
            # survived the router death and was re-adopted (no leak, no
            # double-spawn, pids preserved); every admitted in-flight
            # stream was journaled, replayed, and finished with the
            # exact greedy tokens an unkilled run produces.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and stats["interrupted"] >= 1
                and stats["resumed"] == stats["interrupted"]
                and bool(per)
                and all(c["children_survived_kill"] for c in per)
                and all(c["journaled_inflight"] for c in per)
                and all(c["adoption_complete"] for c in per)
                and all(c["double_spawns"] == 0 for c in per)
                and all(c["pids_preserved"] for c in per)
                and all(c["killed_mid_scale_up"] for c in per)
                and all(c.get("degraded_alert") for c in per)
                and not out["leaked"]
            ),
        }
        return report
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------
# Disagg per-role autoscale ramp (ISSUE 16): a mixed decode-capable
# replica plus an AUTOSCALED prefill pool, under a rising-then-falling
# long-prompt Poisson sweep with a steady short-prompt floor — the
# prefill pool must grow from the long-prompt demand EWMA and shrink
# back after the ramp with NO manual resize, zero lost admitted work
# through every per-role resize, and at least one planned KV hand-off
# landing on an autoscaler-spawned prefill replica.
# ---------------------------------------------------------------------
def run_disagg_autoscale_ramp(
    *,
    ramp: str = "1:3,6:10,0.5:4,0:12",
    short_rps: float = 1.5,
    max_tokens: int = 8,
    prefill_min: int = 1,
    prefill_max: int = 3,
    prefill_rps: float = 2.0,
    ewma_seconds: float = 2.0,
    autoscale_interval: float = 0.5,
    stall_bound_s: float = 45.0,
    settle_bound_s: float = 30.0,
) -> dict:
    """Run the per-role autoscale ramp; returns the report dict.
    Mutates (and restores) os.environ — call from a dedicated process
    or a test that tolerates env churn.

    ``ramp`` is the piecewise LONG-prompt arrival sweep (rate:seconds
    segments); a constant ``short_rps`` Poisson floor of short prompts
    rides underneath for the whole window, so the serve path and the
    hand-off path contend the way a mixed tenant load does.  The mixed
    target is pinned (min=max=1): the ONLY scaling in the run is the
    autoscaler's prefill-demand loop sizing the prefill role, which is
    exactly what the acceptance judges."""
    import asyncio
    import random

    from tests.mock_replica import MockReplicaLauncher
    from vllm_distributed_tpu.entrypoints.cli import parse_ramp
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.router.fleet import (
        Autoscaler,
        AutoscalerConfig,
        ReplicaManager,
    )
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        serve_http,
    )
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    segments = parse_ramp(ramp)
    total_seconds = sum(dur for _, dur in segments)
    page_size = 16
    long_len = 3 * page_size

    def long_prompt_for(idx: int) -> list[int]:
        # Content-unique per request (length fixed): a repeated prompt
        # would be fully prefix-cached decode-side after the first
        # hand-off, so every later transfer would decline adoption and
        # count as a fallback — unique prefixes keep the KV stream
        # genuinely exercised for the whole ramp.  Output tokens are
        # position-indexed (VDT_MOCK_TOKEN_SEQ), so the expected
        # sequence depends only on the length.
        return [(idx * 37 + i) % 900 + 1 for i in range(long_len)]

    short_prompt = [1, 2, 3]
    env = {
        **ROUTER_AGENT_ENV,
        # Every long prompt crosses the hand-off threshold AND feeds
        # the prefill-demand EWMA; short prompts do neither.
        "VDT_DISAGG_MIN_PROMPT_TOKENS": str(long_len - 1),
        "VDT_AUTOSCALE_PREFILL_EWMA_SECONDS": str(ewma_seconds),
        "VDT_DISAGG_EXPORT_TTL_SECONDS": "10",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    tmpdir = tempfile.mkdtemp(prefix="vdt_disagg_autoscale_")
    model_dir = write_llama_config(os.path.join(tmpdir, "m"))
    expected_long = list(range(long_len, long_len + max_tokens))
    expected_short = list(
        range(len(short_prompt), len(short_prompt) + max_tokens)
    )

    stats = {
        "offered_long": 0,
        "offered_short": 0,
        "admitted": 0,
        "completed": 0,
        "mismatches": 0,
        "lost": 0,
        "rejected": 0,
    }
    stalls: list[float] = []
    ttfts: list[float] = []
    timeline: list[dict] = []

    async def go() -> dict:
        import aiohttp

        launcher = MockReplicaLauncher(
            model_dir,
            extra_env=dict(env),
            max_num_seqs=4,
            # The decode-capable side of a KV hand-off adopts imported
            # pages through the radix index; without it every hand-off
            # degrades to the recompute fallback.
            enable_prefix_caching=True,
        )
        state = RouterState(
            [],
            policy="least_loaded",
            health_interval=0.25,
            connect_timeout=2,
            read_timeout=60,
            allow_empty_pool=True,
        )
        manager = ReplicaManager(
            state.pool,
            state.metrics,
            launcher,
            # One pinned mixed (decode-capable) replica; the prefill
            # pool starts at its floor and is resized ONLY by the
            # autoscaler's demand loop from here on.
            target=1,
            role_targets={"prefill": prefill_min},
            warmup_timeout=60,
            drain_timeout=10,
            check_interval=0.2,
            max_restarts=10,
            restart_window=3600,
            backoff_base=0.2,
            backoff_cap=1.0,
        )
        autoscaler = Autoscaler(
            manager,
            state.pool,
            state.metrics,
            AutoscalerConfig(
                # Pin the mixed target: min == max == current, so the
                # queue-depth loop can never act and every scale event
                # in the run is attributable to prefill demand.
                min_replicas=1,
                max_replicas=1,
                interval=autoscale_interval,
                up_waiting=1e9,
                down_waiting=0.0,
                prefill_rps=prefill_rps,
                prefill_min=prefill_min,
                prefill_max=prefill_max,
            ),
            prefill_demand=state.prefill_demand,
        )
        state.attach_fleet(manager, autoscaler)
        router_port = get_open_port()
        router_runner = await serve_http(
            build_router_app(state), host="127.0.0.1", port=router_port
        )
        router_url = f"http://127.0.0.1:{router_port}"
        timeout = aiohttp.ClientTimeout(total=None, sock_read=150)

        def prefill_ready() -> int:
            return sum(
                1
                for r in manager.replicas
                if r.role == "prefill" and r.state == "ready"
            )

        async def one_stream(
            session, tag: str, prompt: list[int], expected: list[int]
        ) -> None:
            body = {
                "prompt": list(prompt),
                "max_tokens": max_tokens,
                "temperature": 0.0,
                "ignore_eos": True,
                "stream": True,
            }
            try:
                async with session.post(
                    f"{router_url}/v1/completions",
                    json=body,
                    headers={"X-VDT-Router": "1"},
                    timeout=timeout,
                ) as resp:
                    if resp.status == 429:
                        stats["rejected"] += 1
                        return
                    if resp.status != 200:
                        stats["lost"] += 1
                        return
                    stats["admitted"] += 1
                    toks: list[int] = []
                    finished = False
                    req_t0 = time.monotonic()
                    last = None
                    worst_gap = 0.0
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            finished = True
                            break
                        obj = json.loads(payload)
                        if "error" in obj and not obj.get("choices"):
                            break  # router gave up: lost work
                        now = time.monotonic()
                        if last is None:
                            ttfts.append(now - req_t0)
                        else:
                            worst_gap = max(worst_gap, now - last)
                        last = now
                        for ch in obj.get("choices") or ():
                            toks += ch.get("vdt_token_ids") or []
                    stalls.append(worst_gap)
                    if not finished:
                        stats["lost"] += 1
                    elif toks != expected:
                        stats["mismatches"] += 1
                        print(
                            f"{tag}: TOKEN MISMATCH {toks} != {expected}",
                            file=sys.stderr,
                        )
                    else:
                        stats["completed"] += 1
            except Exception as e:  # noqa: BLE001 — an admitted stream erroring out IS lost work
                stats["lost"] += 1
                print(f"{tag}: stream error {e}", file=sys.stderr)

        async def sampler(stop: "asyncio.Event") -> None:
            while not stop.is_set():
                timeline.append(
                    {
                        "mono": round(time.monotonic(), 2),
                        "prefill_target": manager.role_targets.get(
                            "prefill", 0
                        ),
                        "prefill_ready": prefill_ready(),
                        "prefill_rate": round(
                            state.prefill_demand.rate, 3
                        ),
                    }
                )
                await asyncio.sleep(0.2)

        async def offer_long(session, tasks: list) -> None:
            rng = random.Random(20816)
            idx = 0
            for rate, dur in segments:
                seg_t0 = time.monotonic()
                while True:
                    remaining = dur - (time.monotonic() - seg_t0)
                    if remaining <= 0:
                        break
                    if rate <= 0:
                        await asyncio.sleep(remaining)
                        break
                    stats["offered_long"] += 1
                    tasks.append(
                        asyncio.ensure_future(
                            one_stream(
                                session,
                                f"long-{idx}",
                                long_prompt_for(idx),
                                expected_long,
                            )
                        )
                    )
                    idx += 1
                    await asyncio.sleep(
                        min(rng.expovariate(rate), remaining)
                    )

        async def offer_short(session, tasks: list) -> None:
            if short_rps <= 0:
                return
            rng = random.Random(40816)
            deadline = time.monotonic() + total_seconds
            idx = 0
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                stats["offered_short"] += 1
                tasks.append(
                    asyncio.ensure_future(
                        one_stream(
                            session,
                            f"short-{idx}",
                            short_prompt,
                            expected_short,
                        )
                    )
                )
                idx += 1
                await asyncio.sleep(
                    min(rng.expovariate(short_rps), remaining)
                )

        async with aiohttp.ClientSession() as session:
            # Wait out the boot: the ramp judges demand-driven resize
            # behavior, not cold start.  Both the mixed replica and the
            # prefill floor must be serving before load is offered.
            deadline = time.monotonic() + 90
            while (
                manager.ready_count() < 1 + prefill_min
                or prefill_ready() < prefill_min
            ):
                if time.monotonic() > deadline:
                    raise RuntimeError("initial fleet never became ready")
                await asyncio.sleep(0.1)
            stop = asyncio.Event()
            aux = [asyncio.ensure_future(sampler(stop))]
            tasks: list = []
            try:
                await asyncio.gather(
                    offer_long(session, tasks),
                    offer_short(session, tasks),
                )
                if tasks:
                    await asyncio.wait_for(
                        asyncio.gather(*tasks), timeout=240
                    )
                # Let the demand EWMA decay and the autoscaler walk the
                # prefill pool back to its floor (bounded).
                settle_deadline = time.monotonic() + settle_bound_s
                while (
                    manager.role_targets.get("prefill", 0) > prefill_min
                    or len(manager.active("prefill")) > prefill_min
                ):
                    if time.monotonic() > settle_deadline:
                        break
                    await asyncio.sleep(0.2)
                timeline.append(
                    {
                        "mono": round(time.monotonic(), 2),
                        "prefill_target": manager.role_targets.get(
                            "prefill", 0
                        ),
                        "prefill_ready": prefill_ready(),
                        "prefill_rate": round(
                            state.prefill_demand.rate, 3
                        ),
                    }
                )
            finally:
                stop.set()
                for t in aux:
                    t.cancel()
            async with session.get(
                f"{router_url}/router/state",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                router_counters = (await resp.json())["counters"]
            events = list(manager.events)
            decisions = list(autoscaler.decisions)
            final = {
                "prefill_target": manager.role_targets.get("prefill", 0),
                "prefill_active": len(manager.active("prefill")),
                "mixed_target": manager.target,
            }
        await router_runner.cleanup()  # drains + reaps the fleet
        return {
            "events": events,
            "decisions": decisions,
            "final": final,
            "counters": router_counters,
            "leaked": launcher.leaked(),
        }

    try:
        out = asyncio.new_event_loop().run_until_complete(go())
        events = out["events"]
        # Drain-before-stop (same invariant as the mixed-fleet ramp):
        # every replica that ever served and was stopped by the manager
        # drained first — per-role retires included.
        ready_ids = {
            e["replica_id"] for e in events if e["kind"] == "ready"
        }
        drained_before_stop = True
        drained_ids = set()
        for e in events:
            if e["kind"] == "drain":
                drained_ids.add(e["replica_id"])
            elif e["kind"] == "stopped" and e["replica_id"] in ready_ids:
                if e["replica_id"] not in drained_ids:
                    drained_before_stop = False
        role_scales = [
            e
            for e in events
            if e["kind"] == "scale_role" and e["role"] == "prefill"
        ]
        demand_ups = [
            e
            for e in role_scales
            if e["to"] > e["from_target"]
            and e["reason"] == "autoscale:prefill_demand"
        ]
        demand_downs = [
            e
            for e in role_scales
            if e["to"] < e["from_target"]
            and e["reason"] == "autoscale:prefill_demand"
        ]
        # "Without manual resize": every scale event in the run — role
        # or mixed — must be the autoscaler's.
        manual_resizes = [
            e
            for e in events
            if e["kind"] in ("scale", "scale_role")
            and not str(e.get("reason", "")).startswith("autoscale:")
        ]
        max_prefill_target = max(
            (s["prefill_target"] for s in timeline), default=0
        )
        max_prefill_ready = max(
            (s["prefill_ready"] for s in timeline), default=0
        )
        handoffs = {
            k: v
            for k, v in out["counters"].items()
            if k.startswith("handoffs.")
        }
        report = {
            "mode": "disagg_autoscale_ramp",
            "ramp": ramp,
            "short_rps": short_rps,
            "prefill_min": prefill_min,
            "prefill_max": prefill_max,
            "prefill_rps": prefill_rps,
            **stats,
            "handoffs": handoffs,
            "max_prefill_target": max_prefill_target,
            "max_prefill_ready": max_prefill_ready,
            "final": out["final"],
            "demand_ups": len(demand_ups),
            "demand_downs": len(demand_downs),
            "manual_resizes": len(manual_resizes),
            "drained_before_stop": drained_before_stop,
            "decisions": out["decisions"],
            "leaked_children": out["leaked"],
            "stall_seconds": {
                "p50": round(_percentile(stalls, 0.5), 3),
                "max": round(max(stalls), 3) if stalls else 0.0,
            },
            "ttft_seconds": {
                "p50": round(_percentile(ttfts, 0.5), 3),
                "p99": round(_percentile(ttfts, 0.99), 3),
                "max": round(max(ttfts), 3) if ttfts else 0.0,
            },
            # The acceptance contract (ISSUE 16): the long-prompt sweep
            # GREW the prefill pool (target AND serving replicas) and
            # shrank it back to the floor after the ramp, every resize
            # was the autoscaler's (no manual scale anywhere), no
            # admitted stream was lost or corrupted through any per-role
            # resize, every retire drained first, at least one planned
            # KV hand-off landed (the grown pool did real disagg work),
            # the pool never exceeded its ceiling, and no child leaked.
            "bounded": (
                stats["lost"] == 0
                and stats["mismatches"] == 0
                and len(demand_ups) >= 1
                and len(demand_downs) >= 1
                and max_prefill_target > prefill_min
                and max_prefill_ready > prefill_min
                and max_prefill_target <= prefill_max
                and out["final"]["prefill_target"] == prefill_min
                and out["final"]["prefill_active"] == prefill_min
                and out["final"]["mixed_target"] == 1
                and not manual_resizes
                and handoffs.get("handoffs.planned", 0) >= 1
                and drained_before_stop
                and not out["leaked"]
                and (not stalls or max(stalls) <= stall_bound_s)
            ),
        }
        return report
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmpdir, ignore_errors=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--cycles", type=int, default=5)
    parser.add_argument("--max-tokens", type=int, default=14)
    parser.add_argument("--kill-after-tokens", type=int, default=3)
    parser.add_argument("--backoff", type=float, default=0.2)
    parser.add_argument(
        "--overload-rps",
        type=float,
        default=0.0,
        help="arm the overload phase: open-loop Poisson offered load "
        "at this rate rides across the kill-recover cycles "
        "(admission caps on; 0 = off)",
    )
    parser.add_argument(
        "--overload-cap",
        type=int,
        default=8,
        help="VDT_MAX_WAITING_REQUESTS for the overload phase",
    )
    parser.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="ISSUE 10 router mode: run this many mock replicas behind "
        "the router and kill/drain them under load, asserting zero "
        "lost admitted work and bounded client stall (1 = classic "
        "single-engine kill-recover soak)",
    )
    parser.add_argument(
        "--router-policy",
        type=str,
        default="least_loaded",
        choices=["affinity", "least_loaded", "round_robin"],
        help="router placement policy for --replicas mode",
    )
    parser.add_argument(
        "--ramp",
        type=str,
        nargs="?",
        const="5:6,14:12,2:6,0:10",
        default=None,
        metavar="R1:S1,R2:S2,...",
        help="ISSUE 13 resize-chaos ramp mode: an AUTOSCALED fleet of "
        "managed mock replicas under this piecewise Poisson rate "
        "sweep, with a SIGKILL mid-resize — asserts zero lost "
        "admitted work, zero token mismatches, drain-before-stop on "
        "every scale-down, and the replica count following the ramp "
        "up and down (default sweep when the flag is bare)",
    )
    parser.add_argument(
        "--ramp-max-replicas",
        type=int,
        default=3,
        help="autoscaler ceiling for --ramp mode",
    )
    parser.add_argument(
        "--no-kill",
        action="store_true",
        help="--ramp mode: skip the mid-resize SIGKILL (pure "
        "autoscale acceptance run)",
    )
    parser.add_argument(
        "--disagg-autoscale",
        type=str,
        nargs="?",
        const="1:3,6:10,0.5:4,0:12",
        default=None,
        metavar="R1:S1,R2:S2,...",
        help="ISSUE 16 per-role autoscale ramp: a pinned mixed replica "
        "plus an autoscaled prefill pool under this long-prompt "
        "Poisson sweep (with a steady short-prompt floor) — asserts "
        "the prefill pool grows from the demand EWMA and shrinks "
        "back after the ramp with no manual resize, zero lost "
        "admitted work through every per-role resize, and at least "
        "one planned KV hand-off (default sweep when the flag is "
        "bare)",
    )
    parser.add_argument(
        "--prefill-max",
        type=int,
        default=3,
        help="prefill-pool ceiling for --disagg-autoscale mode",
    )
    parser.add_argument(
        "--disagg",
        action="store_true",
        help="ISSUE 15 disaggregation phase: a prefill-role + "
        "decode-role mock pool behind the router, SIGKILLing the "
        "prefill replica mid-hand-off and mid-export — asserts "
        "recompute fallback engages with zero lost admitted work, "
        "bit-identical greedy output, at least one planned hand-off, "
        "no leaked pages, and no migration budget burned by the "
        "happy path",
    )
    parser.add_argument(
        "--partition",
        action="store_true",
        help="ISSUE 19 resilient-data-plane phase: the disaggregated "
        "pool behind per-replica fault-injection TCP proxies "
        "(tools/net_chaos.py) with breakers, retry budget, adaptive "
        "deadlines, hedging, and resumable KV transfer armed — "
        "asserts zero lost admitted work and bit-identical streams "
        "under 5%% drop + 200ms jitter, >=1 KV transfer completed "
        "via chunk resume across a healed mid-hand-off partition, "
        "the breaker walking open->half-open->closed across a healed "
        "mid-stream partition, and retry amplification inside the "
        "configured budget ratio",
    )
    parser.add_argument(
        "--router-kill",
        action="store_true",
        help="ISSUE 17 crash-safe router phase: run a managed fleet "
        "with durable state (--state-dir WAL), SIGKILL the ROUTER "
        "ITSELF mid-stream and mid-scale-up, restart it against the "
        "same state dir — asserts every recorded child survives and "
        "is re-adopted (zero leaked, zero double-spawned processes) "
        "and every admitted in-flight stream finishes bit-identically "
        "through the X-VDT-Resume-Id reconnect protocol",
    )
    parser.add_argument(
        "--router-kill-cycles",
        type=int,
        default=1,
        help="kill→restart cycles for --router-kill mode",
    )
    parser.add_argument(
        "--kv-spill",
        action="store_true",
        help="ISSUE 14 spill phase: kill-recover cycles with an ACTIVE "
        "host-DRAM KV tier — asserts restored-page streams stay "
        "bit-identical, the host tier stays bounded across "
        "recoveries, and RSS plateaus (no host-memory leak)",
    )
    args = parser.parse_args()
    if args.partition:
        report = run_partition_soak(
            cycles=args.cycles, max_tokens=args.max_tokens
        )
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    if args.router_kill:
        report = run_router_kill(cycles=args.router_kill_cycles)
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    if args.disagg_autoscale is not None:
        report = run_disagg_autoscale_ramp(
            ramp=args.disagg_autoscale,
            max_tokens=args.max_tokens,
            prefill_max=args.prefill_max,
        )
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    if args.disagg:
        report = run_disagg_soak(
            cycles=args.cycles, max_tokens=args.max_tokens
        )
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    if args.kv_spill:
        report = run_kv_spill_soak(
            cycles=args.cycles, max_tokens=args.max_tokens
        )
        print(json.dumps(report))
        if not (report["bounded"] and report["active"]):
            sys.exit(1)
        return
    if args.ramp is not None:
        report = run_fleet_ramp(
            max_replicas=args.ramp_max_replicas,
            ramp=args.ramp,
            max_tokens=args.max_tokens,
            kill_mid_resize=not args.no_kill,
        )
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    if args.replicas > 1:
        report = run_router_soak(
            replicas=args.replicas,
            cycles=args.cycles,
            max_tokens=args.max_tokens,
            kill_after_tokens=args.kill_after_tokens,
            policy=args.router_policy,
        )
        print(json.dumps(report))
        if not report["bounded"]:
            sys.exit(1)
        return
    report = run_soak(
        cycles=args.cycles,
        max_tokens=args.max_tokens,
        kill_after_tokens=args.kill_after_tokens,
        backoff=args.backoff,
        overload_rps=args.overload_rps,
        overload_cap=args.overload_cap,
    )
    print(json.dumps(report))
    if report["replay_failures"]:
        sys.exit(1)
    overload = report.get("overload")
    if overload is not None and not overload["bounded"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
