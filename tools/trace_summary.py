"""Offline per-stage latency summary of a /debug/traces dump.

The command-line companion to Perfetto (ISSUE 5 satellite): point it at
a saved ``/debug/traces`` JSON dump — or straight at a live server's
endpoint URL — and it prints a per-stage p50/p90/p99 table, so "where
did the latency go" is answerable from a terminal without loading a
trace UI.

    python tools/trace_summary.py traces.json
    python tools/trace_summary.py http://localhost:8000/debug/traces

Pure stdlib; the input is the ``{"traces": [...]}`` shape served by the
API server (tracing.Tracer.snapshot).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

# Stages printed first, in pipeline order; any other span names found in
# the dump follow alphabetically.
_STAGE_ORDER = [
    "api.request",
    "engine.queue",
    "engine.prefill",
    "engine.decode",
    "scheduler.schedule",
    "executor.dispatch",
    "executor.gather",
    "worker.execute",
    "worker.serialize",
]


def load_traces(source: str) -> list[dict]:
    """Read a dump from a file path or an http(s) URL."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=30) as resp:
            payload = json.load(resp)
    elif source == "-":
        payload = json.load(sys.stdin)
    else:
        with open(source) as f:
            payload = json.load(f)
    if isinstance(payload, dict):
        return payload.get("traces", [])
    return payload


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_values:
        return 0.0
    idx = min(
        len(sorted_values) - 1, max(0, round(q * (len(sorted_values) - 1)))
    )
    return sorted_values[idx]


def summarize(traces: list[dict]) -> dict[str, dict[str, float]]:
    """Aggregate span durations by name: count/p50/p90/p99/max (s)."""
    by_name: dict[str, list[float]] = {}
    for trace in traces:
        for span in trace.get("spans", []):
            duration = span.get("duration")
            if duration is None:
                continue  # instant event (preemption/replay marker)
            by_name.setdefault(span["name"], []).append(float(duration))
    stats: dict[str, dict[str, float]] = {}
    for name, durations in by_name.items():
        durations.sort()
        stats[name] = {
            "count": len(durations),
            "p50": percentile(durations, 0.50),
            "p90": percentile(durations, 0.90),
            "p99": percentile(durations, 0.99),
            "max": durations[-1],
        }
    return stats


def format_table(stats: dict[str, dict[str, float]]) -> str:
    names = [n for n in _STAGE_ORDER if n in stats]
    names += sorted(set(stats) - set(_STAGE_ORDER))
    header = (
        f"{'stage':<22} {'count':>7} {'p50(ms)':>10} {'p90(ms)':>10} "
        f"{'p99(ms)':>10} {'max(ms)':>10}"
    )
    lines = [header, "-" * len(header)]
    for name in names:
        s = stats[name]
        lines.append(
            f"{name:<22} {int(s['count']):>7} {s['p50'] * 1e3:>10.2f} "
            f"{s['p90'] * 1e3:>10.2f} {s['p99'] * 1e3:>10.2f} "
            f"{s['max'] * 1e3:>10.2f}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="per-stage p50/p90/p99 from a /debug/traces dump"
    )
    parser.add_argument(
        "source",
        help="dump file, '-' for stdin, or a /debug/traces URL",
    )
    args = parser.parse_args(argv)
    traces = load_traces(args.source)
    if not traces:
        print("no traces in dump (is tracing enabled on the server?)")
        return 1
    stats = summarize(traces)
    print(f"{len(traces)} trace(s)")
    print(format_table(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
