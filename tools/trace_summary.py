"""Offline per-stage latency summary of a /debug/traces dump.

The command-line companion to Perfetto (ISSUE 5 satellite): point it at
a saved ``/debug/traces`` JSON dump — or straight at a live server's
endpoint URL — and it prints a per-stage p50/p90/p99 table, so "where
did the latency go" is answerable from a terminal without loading a
trace UI.

    python tools/trace_summary.py traces.json
    python tools/trace_summary.py http://localhost:8000/debug/traces

Pure stdlib; the input is the ``{"traces": [...]}`` shape served by the
API server (tracing.Tracer.snapshot).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

# Stages printed first, in pipeline order; any other span names found in
# the dump follow alphabetically.  The router hop (ISSUE 10) sits above
# the replica's api.request: the router forwards its trace context in
# X-VDT-Trace-Id, so a dump merged from the router's and the replica's
# /debug/traces shows the whole path under one trace id.
_STAGE_ORDER = [
    "router.request",
    "router.handoff",
    "api.request",
    "engine.queue",
    "engine.kv_restore",
    "engine.kv_handoff",
    "engine.prefill",
    "engine.decode",
    "scheduler.schedule",
    "executor.dispatch",
    "executor.gather",
    "worker.execute",
    "worker.serialize",
]


def load_traces(source: str) -> list[dict]:
    """Read a dump from a file path or an http(s) URL."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=30) as resp:
            payload = json.load(resp)
    elif source == "-":
        payload = json.load(sys.stdin)
    else:
        with open(source) as f:
            payload = json.load(f)
    if isinstance(payload, dict):
        return payload.get("traces", [])
    return payload


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_values:
        return 0.0
    idx = min(
        len(sorted_values) - 1, max(0, round(q * (len(sorted_values) - 1)))
    )
    return sorted_values[idx]


def summarize(traces: list[dict]) -> dict[str, dict[str, float]]:
    """Aggregate span durations by name: count/p50/p90/p99/max (s)."""
    by_name: dict[str, list[float]] = {}
    marker_counts: dict[str, int] = {}
    for trace in traces:
        for span in trace.get("spans", []):
            name = span["name"]
            duration = span.get("duration")
            if duration is None:
                # Instant event.  Pipeline stages recorded as markers
                # (router.handoff is an event on the router's request
                # span, not a timed child) still get a count-only row —
                # a stage listed in _STAGE_ORDER must never silently
                # vanish from the table.
                if name in _STAGE_ORDER:
                    marker_counts[name] = marker_counts.get(name, 0) + 1
                continue
            by_name.setdefault(name, []).append(float(duration))
    stats: dict[str, dict[str, float]] = {}
    for name, durations in by_name.items():
        durations.sort()
        stats[name] = {
            "count": len(durations),
            "p50": percentile(durations, 0.50),
            "p90": percentile(durations, 0.90),
            "p99": percentile(durations, 0.99),
            "max": durations[-1],
        }
    for name, count in marker_counts.items():
        if name not in stats:
            stats[name] = {
                "count": count,
                "p50": None,
                "p90": None,
                "p99": None,
                "max": None,
            }
    return stats


def overlap_summary(traces: list[dict]) -> dict | None:
    """Dispatch-pipeline overlap view (ISSUE 7): for each pair of
    consecutively dispatched steps on the same host, the idle gap
    between the end of ``executor.gather`` N and the start of
    ``executor.dispatch`` N+1.  A positive gap is a **stall window** —
    the driver sat waiting for results before it had the next step on
    the wire; the overlapped scheduler exists to make every gap
    negative (dispatch N+1 in flight before gather N lands).

    Returns ``{"steps", "stall_windows", "gap_p50", "gap_p90",
    "gap_max"}`` (seconds; gaps can be negative), or None when the dump
    has no step-stamped dispatch/gather spans (tracing predates the
    overlap protocol, or no steps ran).
    """
    # host -> step_id -> {"dispatch": start, "gather_end": end}
    by_host: dict[str, dict[int, dict[str, float]]] = {}
    for trace in traces:
        for span in trace.get("spans", []):
            name = span.get("name")
            if name not in ("executor.dispatch", "executor.gather"):
                continue
            attrs = span.get("attributes") or {}
            step_id = attrs.get("step_id")
            host = attrs.get("target_host")
            if step_id is None or host is None:
                continue
            steps = by_host.setdefault(host, {})
            entry = steps.setdefault(int(step_id), {})
            entry["trace_id"] = trace.get("trace_id")
            start = float(span.get("start") or 0.0)
            duration = float(span.get("duration") or 0.0)
            if name == "executor.dispatch":
                # First dispatch span wins (a step is dispatched once
                # per host; retries would only widen the gap).
                entry.setdefault("dispatch", start)
            else:
                entry["gather_end"] = max(
                    entry.get("gather_end", 0.0), start + duration
                )
    gaps: list[float] = []
    stall_windows = 0
    pairs = 0
    for steps in by_host.values():
        ordered = sorted(steps)
        for prev, nxt in zip(ordered, ordered[1:]):
            if steps[prev].get("trace_id") != steps[nxt].get("trace_id"):
                # Steps from different traces: idle time between
                # unrelated requests (or a ring-evicted trace), not a
                # pipeline gap.  Within one trace, non-adjacent ids are
                # still a real pair — empty schedules consume a step id
                # without dispatching, exactly the stall-prone window.
                continue
            gather_end = steps[prev].get("gather_end")
            dispatch = steps[nxt].get("dispatch")
            if gather_end is None or dispatch is None:
                continue
            pairs += 1
            gap = dispatch - gather_end
            gaps.append(gap)
            if gap > 0:
                stall_windows += 1
    if not pairs:
        return None
    gaps.sort()
    return {
        "steps": pairs,
        "stall_windows": stall_windows,
        "gap_p50": percentile(gaps, 0.50),
        "gap_p90": percentile(gaps, 0.90),
        "gap_max": gaps[-1],
    }


def spec_summary(traces: list[dict]) -> dict | None:
    """Speculative-decoding acceptance view (ISSUE 11): aggregate the
    ``engine.spec_decode`` instant events the engine emits per verify
    step (attributes: drafted, accepted).  Returns ``{"verify_steps",
    "drafted", "accepted", "acceptance_rate"}`` or None when the dump
    has no spec events (spec decode off, or tracing predates it)."""
    steps = drafted = accepted = 0
    for trace in traces:
        for span in trace.get("spans", []):
            if span.get("name") != "engine.spec_decode":
                continue
            attrs = span.get("attributes") or {}
            if "drafted" not in attrs:
                continue
            steps += 1
            drafted += int(attrs.get("drafted", 0))
            accepted += int(attrs.get("accepted", 0))
    if not steps:
        return None
    return {
        "verify_steps": steps,
        "drafted": drafted,
        "accepted": accepted,
        "acceptance_rate": (accepted / drafted) if drafted else 0.0,
    }


def format_spec(spec: dict) -> str:
    return "\n".join(
        [
            "speculative decoding (greedy n-gram verify)",
            f"  verify steps   : {spec['verify_steps']}",
            f"  drafted tokens : {spec['drafted']}",
            f"  accepted tokens: {spec['accepted']}",
            f"  acceptance rate: {spec['acceptance_rate']:.3f}",
        ]
    )


def format_overlap(overlap: dict) -> str:
    lines = [
        "dispatch overlap (gap = dispatch N+1 start - gather N end; "
        "negative = overlapped)",
        f"  step pairs     : {overlap['steps']}",
        f"  stall_windows  : {overlap['stall_windows']}",
        f"  gap p50 (ms)   : {overlap['gap_p50'] * 1e3:+.2f}",
        f"  gap p90 (ms)   : {overlap['gap_p90'] * 1e3:+.2f}",
        f"  gap max (ms)   : {overlap['gap_max'] * 1e3:+.2f}",
    ]
    return "\n".join(lines)


def format_table(stats: dict[str, dict[str, float]]) -> str:
    names = [n for n in _STAGE_ORDER if n in stats]
    names += sorted(set(stats) - set(_STAGE_ORDER))
    header = (
        f"{'stage':<22} {'count':>7} {'p50(ms)':>10} {'p90(ms)':>10} "
        f"{'p99(ms)':>10} {'max(ms)':>10}"
    )
    lines = [header, "-" * len(header)]
    for name in names:
        s = stats[name]
        if s["p50"] is None:  # count-only marker stage
            dash = f"{'-':>10}"
            lines.append(
                f"{name:<22} {int(s['count']):>7} {dash} {dash} "
                f"{dash} {dash}"
            )
            continue
        lines.append(
            f"{name:<22} {int(s['count']):>7} {s['p50'] * 1e3:>10.2f} "
            f"{s['p90'] * 1e3:>10.2f} {s['p99'] * 1e3:>10.2f} "
            f"{s['max'] * 1e3:>10.2f}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="per-stage p50/p90/p99 from a /debug/traces dump"
    )
    parser.add_argument(
        "source",
        help="dump file, '-' for stdin, or a /debug/traces URL",
    )
    args = parser.parse_args(argv)
    traces = load_traces(args.source)
    if not traces:
        print("no traces in dump (is tracing enabled on the server?)")
        return 1
    stats = summarize(traces)
    print(f"{len(traces)} trace(s)")
    print(format_table(stats))
    overlap = overlap_summary(traces)
    if overlap is not None:
        print()
        print(format_overlap(overlap))
    spec = spec_summary(traces)
    if spec is not None:
        print()
        print(format_spec(spec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
