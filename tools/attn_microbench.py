"""Standalone paged-attention kernel microbenchmark (real TPU).

Times the decode-shape kernel in isolation to attribute the ~180 GB/s
effective bandwidth PERF.md measured: per-page DMA descriptor issue
rate vs DMA size.  Sweeps page_size (descriptor count at constant
bytes) so the two explanations separate.

Measurement notes (tunneled chip): block_until_ready does NOT wait for
execution under the axon proxy, so each measurement runs the kernel n
times inside ONE jitted fori_loop with a data dependence (q perturbed
by the previous output) and syncs via device_get of a scalar; two loop
counts are differenced to cancel the dispatch overhead.

Usage: python tools/attn_microbench.py [--pages 16 32 64]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.ops.attention import AttentionMetadata
from vllm_distributed_tpu.ops.pallas.paged_attention import paged_attention


def _time_chained(fn, n_small=8, n_big=104):
    """Per-iteration seconds of fn-in-fori_loop, dispatch cost cancelled."""

    def run(n):
        r = fn(n)
        _ = jax.device_get(r)
        t0 = time.perf_counter()
        r = fn(n)
        _ = jax.device_get(r)
        return time.perf_counter() - t0

    run(n_small)  # compile both variants before timing
    run(n_big)
    ts = min(run(n_small) for _ in range(5))
    tb = min(run(n_big) for _ in range(5))
    return max(tb - ts, 1e-9) / (n_big - n_small)


def run(name, *, s, seq_len, hq, hkv, d_pool, d_q, page_size):
    rng = np.random.default_rng(0)
    pages_per_seq = -(-seq_len // page_size)
    p_total = s * pages_per_seq + 1
    q0 = jnp.asarray(rng.normal(size=(s, hq, d_q)), jnp.bfloat16)
    kv = jnp.asarray(
        rng.normal(size=(2, p_total, page_size, hkv * d_q)), jnp.bfloat16
    )
    bt = (
        rng.permutation(np.arange(1, p_total))
        .reshape(s, pages_per_seq)
        .astype(np.int32)
    )
    meta = AttentionMetadata(
        q_seq_ids=jnp.arange(s, dtype=jnp.int32),
        q_positions=jnp.full(s, seq_len - 1, jnp.int32),
        slot_mapping=jnp.zeros(s, jnp.int32),
        block_tables=jnp.asarray(bt),
        seq_lens=jnp.full(s, seq_len, jnp.int32),
        logits_indices=jnp.arange(s, dtype=jnp.int32),
        chunk_starts=jnp.full(s, seq_len - 1, jnp.int32),
    )

    @partial(jax.jit, static_argnames="n")
    def chained(q, kv, meta, n):
        def body(i, q):
            out = paged_attention(
                q, kv, meta, scale=0.125, num_kv_heads=hkv, max_q=1
            )
            return q + (out * 1e-30).astype(q.dtype)

        q = jax.lax.fori_loop(0, n, body, q)
        return jnp.sum(q, dtype=jnp.float32)

    dt = _time_chained(lambda n: chained(q0, kv, meta, n))
    kv_bytes = 2 * s * pages_per_seq * page_size * hkv * d_q * 2
    n_desc = s * pages_per_seq
    print(
        f"{name:20s} page={page_size:3d} {dt*1e6:8.1f} us/exec  "
        f"{kv_bytes/dt/1e9:7.1f} GB/s  {n_desc:6d} DMAs "
        f"({kv_bytes/n_desc/1024:.0f} KiB each, {n_desc/dt/1e6:5.1f} M desc/s)"
    )
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pages", type=int, nargs="+", default=[16, 32, 64])
    args = ap.parse_args()
    print(f"backend={jax.default_backend()} dev={jax.devices()[0].device_kind}")
    for ps in args.pages:
        # 1B decode shape: 32 seqs x 2048 ctx, hkv=8, head_dim 64 -> 128 pad
        run("1b_b32_ctx2048", s=32, seq_len=2048, hq=32, hkv=8,
            d_pool=128, d_q=64, page_size=ps)
    for ps in args.pages:
        # 7B decode shape: 32 seqs x 1024 ctx, MHA hkv=32, d=128
        run("7b_b32_ctx1024", s=32, seq_len=1024, hq=32, hkv=32,
            d_pool=128, d_q=128, page_size=ps)


if __name__ == "__main__":
    main()
