"""Fault-injection TCP proxy for network chaos tests (ISSUE 19).

A ``ChaosProxy`` sits between the router and one replica and shapes the
raw byte stream the way a congested or partitioned DCN link would:

- **latency/jitter**: every forwarded chunk waits a fixed base delay
  plus a uniform jitter draw (per direction — a request pays it on the
  way up AND the response pays it on the way down);
- **probabilistic drop**: each forwarded chunk has ``drop_prob`` odds
  of killing the whole connection mid-flight (an abortive close, the
  way a flapping link actually fails — not a polite FIN);
- **bandwidth cap**: chunk delays sized so sustained throughput never
  exceeds ``bandwidth_bps``;
- **full partition**: new connections are refused with an abortive
  close and every established one is torn down — armable and healable
  at runtime, so a soak can partition one replica mid-stream and then
  watch the breaker walk open → half-open → closed after the heal.

All randomness comes from a seeded ``random.Random`` so a chaos run is
reproducible.  The proxy is pure asyncio (no extra deps) and is used
in-process by ``tools/chaos_soak.py --partition``; the CLI main exists
for poking at a live replica by hand.
"""

from __future__ import annotations

import argparse
import asyncio
import random

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Forwarding unit: small enough that latency/bandwidth shaping has
# sub-chunk resolution, large enough not to dominate CPU.
_CHUNK = 16 * 1024


class ChaosProxy:
    """One shapeable TCP proxy in front of one ``host:port`` target."""

    def __init__(
        self,
        target_host: str,
        target_port: int,
        *,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        seed: int = 0,
    ) -> None:
        self.target_host = target_host
        self.target_port = int(target_port)
        self.listen_host = listen_host
        self.listen_port = int(listen_port)
        self.rng = random.Random(seed)
        # Fault knobs (all off = transparent forwarding).
        self.latency_ms = 0.0
        self.jitter_ms = 0.0
        self.drop_prob = 0.0
        self.bandwidth_bps = 0.0  # 0 = unlimited
        self.partitioned = False
        # Filled by start().
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        # Counters for soak assertions.
        self.connections_total = 0
        self.connections_refused = 0
        self.connections_dropped = 0
        self.bytes_forwarded = 0

    # ---- lifecycle ----
    async def start(self) -> "ChaosProxy":
        self._server = await asyncio.start_server(
            self._handle, self.listen_host, self.listen_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self._kill_established()

    @property
    def url(self) -> str:
        return f"http://{self.listen_host}:{self.port}"

    # ---- runtime fault control ----
    def arm(
        self,
        *,
        latency_ms: float | None = None,
        jitter_ms: float | None = None,
        drop_prob: float | None = None,
        bandwidth_bps: float | None = None,
        partitioned: bool | None = None,
    ) -> None:
        """Set fault knobs at runtime; ``None`` leaves a knob as-is.
        Arming a partition also tears down established connections —
        a partition that only blocks NEW flows is not a partition."""
        if latency_ms is not None:
            self.latency_ms = float(latency_ms)
        if jitter_ms is not None:
            self.jitter_ms = float(jitter_ms)
        if drop_prob is not None:
            self.drop_prob = float(drop_prob)
        if bandwidth_bps is not None:
            self.bandwidth_bps = float(bandwidth_bps)
        if partitioned is not None:
            self.partitioned = bool(partitioned)
            if self.partitioned:
                self._kill_established()

    def heal(self) -> None:
        """Back to transparent forwarding (partition lifted, all
        shaping off)."""
        self.arm(
            latency_ms=0.0,
            jitter_ms=0.0,
            drop_prob=0.0,
            bandwidth_bps=0.0,
            partitioned=False,
        )

    def _kill_established(self) -> None:
        for w in list(self._writers):
            try:
                w.transport.abort()
            except Exception:  # noqa: BLE001 — already-dead transports
                pass

    # ---- data path ----
    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.connections_total += 1
        if self.partitioned:
            self.connections_refused += 1
            writer.transport.abort()
            return
        try:
            up_reader, up_writer = await asyncio.open_connection(
                self.target_host, self.target_port
            )
        except OSError:
            writer.transport.abort()
            return
        self._writers.update((writer, up_writer))
        up = asyncio.ensure_future(self._pump(reader, up_writer))
        down = asyncio.ensure_future(self._pump(up_reader, writer))
        try:
            # Either direction ending (EOF, fault-drop, reset) tears
            # down the whole connection abortively: a chaos link never
            # lingers in half-closed politeness.
            await asyncio.wait(
                {up, down}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            up.cancel()
            down.cancel()
            for w in (writer, up_writer):
                self._writers.discard(w)
                try:
                    w.transport.abort()
                except Exception:  # noqa: BLE001
                    pass

    async def _pump(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                data = await reader.read(_CHUNK)
                if not data:
                    return  # EOF: _handle aborts the pair
                if self.partitioned:
                    self.connections_dropped += 1
                    return
                if self.drop_prob and self.rng.random() < self.drop_prob:
                    self.connections_dropped += 1
                    return
                delay = self.latency_ms / 1e3
                if self.jitter_ms:
                    delay += self.rng.random() * self.jitter_ms / 1e3
                if self.bandwidth_bps:
                    delay += len(data) / self.bandwidth_bps
                if delay > 0:
                    await asyncio.sleep(delay)
                writer.write(data)
                await writer.drain()
                self.bytes_forwarded += len(data)
        except asyncio.CancelledError:
            raise
        except (OSError, ConnectionResetError):
            return


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="fault-injection TCP proxy (chaos harness)"
    )
    ap.add_argument("--target", required=True, help="host:port to front")
    ap.add_argument("--listen-port", type=int, default=0)
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--latency-ms", type=float, default=0.0)
    ap.add_argument("--jitter-ms", type=float, default=0.0)
    ap.add_argument("--drop-prob", type=float, default=0.0)
    ap.add_argument("--bandwidth-bps", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    host, _, port = args.target.rpartition(":")

    async def run() -> None:
        proxy = ChaosProxy(
            host or "127.0.0.1",
            int(port),
            listen_host=args.listen_host,
            listen_port=args.listen_port,
            seed=args.seed,
        )
        proxy.arm(
            latency_ms=args.latency_ms,
            jitter_ms=args.jitter_ms,
            drop_prob=args.drop_prob,
            bandwidth_bps=args.bandwidth_bps,
        )
        await proxy.start()
        print(f"chaos proxy :{proxy.port} -> {args.target}", flush=True)
        await asyncio.Event().wait()  # Ctrl-C to stop

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
