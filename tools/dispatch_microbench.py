"""Dispatch-overlap microbench (ISSUE 7 gate).

Boots the SAME loopback 2-host mock deployment twice and drives a real
``Scheduler`` through an identical greedy workload over each dispatch
protocol:

- **blocking** (``VDT_STEP_STREAMS=0``, ``non_block=False``): one
  collective ``execute_model`` request/reply pair per step — the engine
  thread is occupied for serialize + RPC + device + gather every step
  (the "dispatch tax" BENCH r02-r05 measured at 110-210 ms p50), and
  the device idles one full driver round trip per step by construction.
- **overlapped** (``VDT_STEP_STREAMS=1``, ``non_block=True``): each
  step is delta-compressed, pushed as one one-way frame per host, and
  the driver schedules step N+1 while N executes (two in flight, the
  engine's ``max_concurrent_dispatches`` discipline).

Asserted (exit 1 on violation, ``--no-assert`` to just report):

1. greedy outputs are bit-identical across the two protocols
   (``VDT_MOCK_TOKEN_SEQ`` position tokens make any divergence loud);
2. per-step dispatch time (engine-thread occupancy of the
   ``execute_model`` call, what ``vllm:step_dispatch_time_seconds``
   observes) drops >= 5x at p50;
3. overlap: the overlapped run's steady-state wall is under the sum of
   the blocking path's per-step dispatch times (driver work hides
   entirely under device time);
4. measured steady-state ``stall_windows`` == 0: after the pipeline
   fills, the device-side run loops never wait for a frame with
   nothing in flight.

Run from the repo root:

    JAX_PLATFORMS=cpu python tools/dispatch_microbench.py

A small-workload smoke runs in tier-1
(tests/test_multihost.py::test_dispatch_microbench).
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import statistics
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _agent_main(port: int, env: dict[str, str]) -> None:
    for k, v in env.items():
        os.environ[k] = v
    from vllm_distributed_tpu.distributed.agent import remote_main

    remote_main("127.0.0.1", port)


def _spawn_agent(port: int, env: dict[str, str]):
    proc = multiprocessing.Process(
        target=_agent_main, args=(port, env), daemon=True
    )
    proc.start()
    return proc


def _make_scheduler(batch: int, prompt_len: int, max_tokens: int):
    from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
    from vllm_distributed_tpu.engine.request import Request
    from vllm_distributed_tpu.engine.scheduler import Scheduler
    from vllm_distributed_tpu.sampling_params import SamplingParams

    sched = Scheduler(
        SchedulerConfig(
            max_num_seqs=batch,
            max_num_batched_tokens=4096,
            enable_chunked_prefill=True,
            max_model_len=max(4 * (prompt_len + max_tokens), 64),
            # One token per decode dispatch: the microbench measures
            # PER-DISPATCH driver overhead, so fused windows would just
            # shrink the sample count (the engine-level fused path is
            # covered by test_pipelined_vs_blocking_engine_outputs_*).
            num_decode_steps=1,
        ),
        CacheConfig(page_size=4),
        num_pages=512,
    )
    for i in range(batch):
        sched.add_request(
            Request(
                request_id=f"r{i}",
                prompt_token_ids=[(7 * i + j) % 900 + 1
                                  for j in range(prompt_len)],
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=max_tokens, ignore_eos=True
                ),
                eos_token_id=None,
            )
        )
    return sched


# Simulated device time per step.  SHORT on purpose: the production
# regime this microbench reproduces is decode microsteps of 5-13 ms
# against a driver path that was costing 110-210 ms per step (BENCH
# r02-r05) — device time must NOT dwarf driver overhead or the blocking
# path's tax disappears into the sleeps.
DEVICE_SECONDS = 0.01


def run_phase(
    overlapped: bool,
    *,
    batch: int = 4,
    prompt_len: int = 8,
    max_tokens: int = 24,
    depth: int = 2,
) -> dict:
    """One full boot + workload over one protocol.  Returns per-step
    dispatch times (ms), steady-state wall (s), per-request tokens, and
    the stream runners' steady-state stall counts."""
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.executor.multihost import MultiHostExecutor
    from vllm_distributed_tpu.testing import write_llama_config
    from vllm_distributed_tpu.utils import get_open_port

    class MicrobenchExecutor(MultiHostExecutor):
        worker_cls = "tests.mock_worker.MockWorker"

    port = get_open_port()
    env = {
        "VDT_SERVER_PORT": str(port),
        "VDT_STEP_STREAMS": "1" if overlapped else "0",
        "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS": "60",
        "VDT_MOCK_TOKEN_SEQ": "1",
        # Same simulated device time on BOTH protocols: the blocking
        # verb and the two-phase fetch both sleep DEVICE_SECONDS.
        "VDT_MOCK_EXECUTE_SLEEP_SECONDS": str(DEVICE_SECONDS),
        "VDT_MOCK_STEP_SECONDS": str(DEVICE_SECONDS),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    agent_env = {
        **env,
        "VDT_ADVERTISE_NUM_CHIPS": "4",
        "VDT_ADVERTISE_PLATFORM": "cpu",
    }
    tmp = tempfile.mkdtemp(prefix="vdt_dispatch_mb_")
    agent = _spawn_agent(port, agent_env)
    executor = None
    try:
        config = EngineArgs(
            model=write_llama_config(os.path.join(tmp, "m")),
            skip_tokenizer_init=True,
            load_format="dummy",
            num_hosts=2,
        ).create_engine_config()
        executor = MicrobenchExecutor(config)
        sched = _make_scheduler(batch, prompt_len, max_tokens)
        tokens: dict[str, list[int]] = {}

        def settle(so, result):
            for req in sched.update_from_output(
                so, result.sampled_token_ids
            ):
                tokens[req.request_id] = list(req.output_token_ids)

        # Prime: prefill runs blocking on both protocols (the pipeline
        # only overlaps decode continuations, exactly like the engine).
        prefill = sched.schedule()
        settle(prefill, executor.execute_model(prefill))

        dispatch_ms: list[float] = []
        pending: list[tuple] = []
        stall_base: dict | None = None
        t_wall = time.perf_counter()
        while sched.has_unfinished_requests() or pending:
            so = sched.schedule()
            if not so.is_empty:
                t0 = time.perf_counter()
                if overlapped:
                    fut = executor.execute_model(so, non_block=True)
                    dispatch_ms.append((time.perf_counter() - t0) * 1e3)
                    pending.append((so, fut))
                else:
                    out = executor.execute_model(so)
                    dispatch_ms.append((time.perf_counter() - t0) * 1e3)
                    settle(so, out)
            elif not pending:
                break  # nothing in flight and nothing to schedule
            while pending and (
                len(pending) > depth - 1 or so.is_empty
            ):
                so0, fut0 = pending.pop(0)
                settle(so0, fut0.result(timeout=60))
                if so.is_empty:
                    break  # drain ONE per idle pass, like the engine
            if (
                overlapped
                and stall_base is None
                and len(dispatch_ms) >= depth
            ):
                # Pipeline is full: steady state starts here.  The
                # prefill->decode boundary may legitimately record one
                # stall window; everything after this snapshot may not.
                stall_base = executor.step_stream_stats()
        wall_s = time.perf_counter() - t_wall

        stalls_steady = None
        if overlapped:
            stall_end = executor.step_stream_stats()
            base = stall_base or {}
            stalls_steady = sum(
                host_stats["stalls"]
                - base.get(host, {}).get("stalls", 0)
                for host, host_stats in stall_end.items()
            )
        return {
            "protocol": "overlapped" if overlapped else "blocking",
            "steps": len(dispatch_ms),
            "dispatch_ms": [round(ms, 3) for ms in dispatch_ms],
            "dispatch_ms_p50": round(statistics.median(dispatch_ms), 3),
            "wall_s": round(wall_s, 3),
            "stall_windows_steady": stalls_steady,
            "tokens": tokens,
        }
    finally:
        if executor is not None:
            executor.shutdown()
        if agent.is_alive():
            agent.terminate()
        agent.join(timeout=5)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_microbench(
    *, batch: int = 4, prompt_len: int = 8, max_tokens: int = 24
) -> dict:
    blocking = run_phase(
        False, batch=batch, prompt_len=prompt_len, max_tokens=max_tokens
    )
    overlapped = run_phase(
        True, batch=batch, prompt_len=prompt_len, max_tokens=max_tokens
    )
    ratio = blocking["dispatch_ms_p50"] / max(
        overlapped["dispatch_ms_p50"], 1e-9
    )
    blocking_dispatch_sum_s = sum(blocking["dispatch_ms"]) / 1e3
    report = {
        "blocking": {k: v for k, v in blocking.items() if k != "tokens"},
        "overlapped": {
            k: v for k, v in overlapped.items() if k != "tokens"
        },
        "dispatch_p50_speedup": round(ratio, 1),
        "checks": {
            "outputs_bit_identical": blocking["tokens"]
            == overlapped["tokens"],
            "dispatch_p50_5x": ratio >= 5.0,
            "overlap_wall_lt_blocking_dispatch_sum": overlapped["wall_s"]
            < blocking_dispatch_sum_s,
            "stall_windows_zero": overlapped["stall_windows_steady"] == 0,
        },
    }
    report["ok"] = all(report["checks"].values())
    return report


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="blocking vs overlapped dispatch protocol microbench"
    )
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--prompt-len", type=int, default=8)
    parser.add_argument("--max-tokens", type=int, default=24)
    parser.add_argument(
        "--no-assert",
        action="store_true",
        help="report only; exit 0 even when a check fails",
    )
    args = parser.parse_args(argv)
    report = run_microbench(
        batch=args.batch,
        prompt_len=args.prompt_len,
        max_tokens=args.max_tokens,
    )
    print(json.dumps(report, indent=2))
    if not report["ok"] and not args.no_assert:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
