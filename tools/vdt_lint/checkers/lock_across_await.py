"""VDT002 lock-across-await: no sync lock held across an ``await``.

A ``threading.Lock`` held across a suspension point wedges every other
coroutine (and thread) contending for it until the awaited I/O returns
— with a slow peer, that is a cross-host priority inversion the
heartbeat watchdog then misattributes to the remote side.  Asyncio
locks must use ``async with``; threading locks must release before
awaiting (see ``FaultInjector.on_write``, which reads state under the
lock and sleeps outside it).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import callee_last, contains_await, dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_LOCKISH_SUBSTRINGS = ("lock", "mutex")
_LOCK_CONSTRUCTORS = {
    "Lock",
    "RLock",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
}


def _is_lockish(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        last = callee_last(expr)
        if last in _LOCK_CONSTRUCTORS:
            return True
        # lock.acquire()-style context factories: x.some_lock()
        expr = expr.func
    name = dotted_name(expr)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(s in terminal for s in _LOCKISH_SUBSTRINGS)


@register
class LockAcrossAwaitChecker(Checker):
    code = "VDT002"
    rule = "lock-across-await"
    description = "sync lock held across an await"
    rationale = (
        "a threading lock held across a suspension point wedges every "
        "contender until the awaited I/O returns"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            # Sync `with` only: `async with asyncio.Lock()` releasing at
            # suspension points is the designed usage.
            if not isinstance(node, ast.With):
                continue
            lock_items = [
                item for item in node.items if _is_lockish(item.context_expr)
            ]
            if not lock_items:
                continue
            if any(contains_await(stmt) for stmt in node.body):
                expr = lock_items[0].context_expr
                name = dotted_name(expr)
                if name is None and isinstance(expr, ast.Call):
                    name = f"{dotted_name(expr.func) or '...'}()"
                name = name or "a lock"
                yield ctx.finding(
                    self,
                    node,
                    f"`with {name}:` encloses an await — the lock is "
                    "held across the suspension; release it before "
                    "awaiting",
                )
