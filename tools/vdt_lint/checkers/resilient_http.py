"""VDT010 resilient-http: outbound HTTP from the router goes through
the resilience wrapper.

The ISSUE 19 failure class: a raw ``session.get(...)`` in ``router/``
bypasses the circuit breakers, retry budget, and adaptive deadlines —
one forgotten call site and a partitioned replica gets hammered with
un-budgeted retries on a fixed timeout while its breaker reads healthy.
Every aiohttp client-session verb call (``get``/``post``/``put``/
``delete``/``head``/``patch``/``options``/``request``/``ws_connect``)
whose receiver is a session attribute or variable must either be routed
through ``ResilienceManager.request`` / ``hedged`` or carry an inline
waiver naming why it cannot be (the wrapper's own passthrough line, a
bootstrap probe that predates the manager).

Receivers are matched by name: the final dotted component is
``session`` or ends with ``_session`` (``state.session``,
``self.session``, ``self._kv_session``).  Calling the wrapper itself
(``rz.request(state.session, ...)``) does not match — the session is an
argument there, not the receiver.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_HTTP_VERBS = {
    "get",
    "post",
    "put",
    "delete",
    "head",
    "patch",
    "options",
    "request",
    "ws_connect",
}


def _session_receiver(func: ast.expr) -> str | None:
    """Return the dotted receiver name when ``func`` is
    ``<receiver>.<verb>`` and the receiver looks like an aiohttp
    session; None otherwise."""
    if not isinstance(func, ast.Attribute) or func.attr not in _HTTP_VERBS:
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    leaf = receiver.rsplit(".", 1)[-1]
    if leaf == "session" or leaf.endswith("_session"):
        return receiver
    return None


@register
class ResilientHttpChecker(Checker):
    code = "VDT010"
    rule = "resilient-http"
    description = (
        "raw session HTTP call in router/ bypasses the resilience wrapper"
    )
    rationale = (
        "a direct session call skips circuit breakers, the retry "
        "budget, and adaptive deadlines — route it through "
        "ResilienceManager.request/hedged, or waive with why it "
        "cannot be"
    )
    scope = ("router/",)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            receiver = _session_receiver(node.func)
            if receiver is None:
                continue
            yield ctx.finding(
                self,
                node,
                f"{receiver}.{node.func.attr}() bypasses the "
                "resilience wrapper — use "
                "ResilienceManager.request/hedged, or waive with the "
                "reason it cannot apply",
            )
