"""VDT011 sentinel-emitter: timeline events go through the sentinel
emitter API with registered kinds.

The ISSUE 20 failure class: the unified event timeline is only useful
if it is *complete and well-typed* — one subsystem appending dicts to
its own ad-hoc ring (instead of ``SentinelLog.emit``) produces events
that never reach ``/debug/events`` or the fleet merge, and a free-form
``kind`` string silently fragments the vocabulary that alerting and
``fleet_doctor`` key on.  Two checks:

* **Ad-hoc ring appends** — ``<recv>.append(...)`` where the final
  dotted component of the receiver is ``events`` or ends with
  ``_events`` is an event-ring append bypassing the emitter.  Legacy
  rings that deliberately predate the timeline (the flight recorder's
  marker ring, the fleet event deque that is mirrored into the
  sentinel) carry inline waivers naming why.
* **Unregistered kinds** — ``<recv>.emit("literal", ...)`` on a
  sentinel-looking receiver (leaf ``sentinel``/``events``/``log``)
  where the literal kind is not in ``engine/sentinel.py``'s
  ``EVENT_KINDS``.  The vocabulary is parsed from that module by AST
  (never imported), so the linter works on an un-importable tree.

``engine/sentinel.py`` itself is exempt: it IS the emitter.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_SENTINEL_MODULE = "engine/sentinel.py"
_EMIT_RECEIVER_LEAVES = {"sentinel", "events", "log"}

_kinds_cache: frozenset[str] | None = None


def _registered_kinds() -> frozenset[str]:
    """Parse EVENT_KINDS out of engine/sentinel.py without importing
    it.  Missing module / unparseable set -> empty vocabulary, which
    disables the kind check rather than erroring the whole lint run."""
    global _kinds_cache
    if _kinds_cache is not None:
        return _kinds_cache
    repo_root = Path(__file__).resolve().parents[3]
    path = repo_root / "vllm_distributed_tpu" / _SENTINEL_MODULE
    kinds: set[str] = set()
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        _kinds_cache = frozenset()
        return _kinds_cache
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
        if "EVENT_KINDS" not in targets:
            continue
        for literal in ast.walk(node.value):
            if isinstance(literal, ast.Constant) and isinstance(
                literal.value, str
            ):
                kinds.add(literal.value)
    _kinds_cache = frozenset(kinds)
    return _kinds_cache


def _event_ring_receiver(func: ast.expr) -> str | None:
    if not isinstance(func, ast.Attribute) or func.attr != "append":
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    leaf = receiver.rsplit(".", 1)[-1]
    if leaf == "events" or leaf.endswith("_events"):
        return receiver
    return None


def _emit_kind_literal(node: ast.Call) -> str | None:
    """The literal kind of a sentinel-receiver ``.emit("...")`` call,
    or None when this is not one / the kind is dynamic."""
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr != "emit":
        return None
    receiver = dotted_name(func.value)
    if receiver is None:
        return None
    if receiver.rsplit(".", 1)[-1] not in _EMIT_RECEIVER_LEAVES:
        return None
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


@register
class SentinelEmitterChecker(Checker):
    code = "VDT011"
    rule = "sentinel-emitter"
    description = (
        "timeline events must go through the sentinel emitter API "
        "with registered kinds"
    )
    rationale = (
        "an ad-hoc event-ring append never reaches /debug/events or "
        "the fleet timeline merge, and an unregistered kind string "
        "fragments the vocabulary alerting keys on — emit via "
        "SentinelLog with a kind from EVENT_KINDS, or waive with why "
        "the ring is not a timeline"
    )
    scope = ("engine/", "router/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.scope_rel == _SENTINEL_MODULE:
            return  # the emitter's own internals
        kinds = _registered_kinds()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            receiver = _event_ring_receiver(node.func)
            if receiver is not None:
                yield ctx.finding(
                    self,
                    node,
                    f"{receiver}.append() bypasses the sentinel "
                    "emitter — events appended here never reach "
                    "/debug/events or the fleet timeline; use "
                    "SentinelLog.emit, or waive with why this ring "
                    "is not a timeline",
                )
                continue
            kind = _emit_kind_literal(node)
            if kind is not None and kinds and kind not in kinds:
                yield ctx.finding(
                    self,
                    node,
                    f"sentinel event kind {kind!r} is not registered "
                    "in engine/sentinel.py EVENT_KINDS — register it "
                    "so the timeline vocabulary stays typed",
                )
