"""VDT006 silent-except: no ``except Exception: pass``.

Migrated from tests/test_code_hygiene.py (ISSUE 2 satellite), widened
from ``distributed/`` to the whole package: the layers whose job is
failure DETECTION must not swallow exactly the signals the
fault-tolerance machinery exists to surface.  Teardown best-effort
blocks log at debug instead (see rpc_transport.close()); genuinely
expected errors carry an inline waiver saying why.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.core import Checker, FileContext, Finding, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return isinstance(stmt, ast.Expr) and isinstance(
        stmt.value, ast.Constant
    ) and stmt.value.value is ...


@register
class SilentExceptChecker(Checker):
    code = "VDT006"
    rule = "silent-except"
    description = "broad except block that swallows silently"
    rationale = (
        "a silent broad except hides exactly the failure signals the "
        "fault-tolerance layer exists to surface — log at debug at least"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.ExceptHandler)
                and _is_broad(node)
                and _is_silent(node)
            ):
                yield ctx.finding(
                    self,
                    node,
                    "silent broad except — log at debug instead of "
                    "swallowing (rpc_transport.close() is the pattern)",
                )
