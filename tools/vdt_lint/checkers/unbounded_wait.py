"""VDT003 unbounded-wait: control-plane waits must carry a deadline.

The PR 2 "no leaked futures" rule, generalized: in the control plane
(``distributed/``, ``executor/``, ``engine/supervisor.py``) any wait on
a peer — a bare future, an RPC param fetch, a queue/event/stream
primitive — must be bounded by ``asyncio.wait_for``/a ``timeout=``,
because a silent host is a ROUTINE failure over DCN and an unbounded
wait converts it into a wedged driver (SURVEY.md §5.3; Llumnix-style
migration is only safe on a deadline-disciplined control plane).

What counts as an unbounded leaf wait:

- ``await fut`` / ``await task`` — a bare Name/Attribute future;
- ``await x.<leaf>(...)`` with no ``timeout=`` for leaf primitives
  (``wait``, ``gather``, ``get``, ``join``, ``acquire``, ``drain``,
  ``read``/``readexactly``/``readuntil``/``readline``, ``recv``,
  ``communicate``, ``open_connection``, ``connect``,
  ``get_param``/``getParam``);
- sync ``<expr>.result()`` with neither a positional timeout nor
  ``timeout=`` (concurrent futures block forever);
- sync no-arg ``<expr>.get()`` / ``<expr>.wait()`` with no ``timeout=``
  — the step-queue wait pattern (ISSUE 7): the persistent run loops in
  ``worker/step_stream.py`` park loop threads on ``queue.Queue.get`` /
  ``threading.Event.wait``, and an unbounded one survives ``stop()``
  forever (a no-arg ``.get()`` cannot be a ``dict.get``, which needs a
  key, so this stays precise).

Awaiting an ordinary coroutine *call* is composition, not a leaf wait —
deadline ownership belongs inside the callee or at the orchestration
point wrapping it.  Awaits inside a nested function whose every call
site sits in ``asyncio.wait_for(...)`` are recognized as bounded (the
``send_and_wait`` pattern in rpc.py).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import callee_last, has_kwarg
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_BOUNDED_CALLEES = {"wait_for", "sleep"}
_UNBOUNDED_LEAF_CALLEES = {
    "wait",
    "gather",
    "get",
    "join",
    "acquire",
    "drain",
    "read",
    "readexactly",
    "readuntil",
    "readline",
    "recv",
    "communicate",
    "open_connection",
    "connect",
    "get_param",
    "getParam",
}


class _Visitor(ast.NodeVisitor):
    def __init__(self, checker: "UnboundedWaitChecker", ctx: FileContext):
        self.checker = checker
        self.ctx = ctx
        self.findings: list[Finding] = []
        # Defs whose awaits are exempt: every call of the def appears
        # inside an asyncio.wait_for(...) argument in the parent scope.
        self._protected_defs: set[int] = set()
        self._protection_depth = 0
        # Call nodes owned by an enclosing await or wait_for(...): the
        # await path (visit_Await) is the authority there, so the sync
        # .get()/.wait() branch must not re-flag them.
        self._async_owned: set[int] = set()

    # ---- wait_for-wrapped nested defs ----
    def _mark_protected(self, func: ast.AST) -> None:
        nested = {
            n.name: n
            for n in ast.walk(func)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not func
        }
        if not nested:
            return
        in_wait_for: set[int] = set()
        all_calls: dict[str, list[int]] = {}
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = (
                node.func.id if isinstance(node.func, ast.Name) else None
            )
            if name in nested:
                all_calls.setdefault(name, []).append(id(node))
            if callee_last(node) == "wait_for":
                for arg in node.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Name
                        ):
                            in_wait_for.add(id(sub))
        for name, sites in all_calls.items():
            if sites and all(s in in_wait_for for s in sites):
                self._protected_defs.add(id(nested[name]))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def _visit_func(self, node) -> None:
        self._mark_protected(node)
        protected = id(node) in self._protected_defs
        if protected:
            self._protection_depth += 1
        self.generic_visit(node)
        if protected:
            self._protection_depth -= 1

    # ---- awaits ----
    def visit_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._async_owned.add(id(node.value))
        self.generic_visit(node)
        if self._protection_depth > 0:
            return
        value = node.value
        if isinstance(value, (ast.Name, ast.Attribute)):
            self.findings.append(
                self.ctx.finding(
                    self.checker,
                    node,
                    "await of a bare future/task has no deadline — wrap "
                    "in asyncio.wait_for or reclaim via "
                    "rpc.apply_with_timeout",
                )
            )
            return
        if isinstance(value, ast.Call):
            callee = callee_last(value)
            if callee in _BOUNDED_CALLEES:
                return
            if callee in _UNBOUNDED_LEAF_CALLEES and not has_kwarg(
                value, "timeout"
            ):
                self.findings.append(
                    self.ctx.finding(
                        self.checker,
                        node,
                        f"await of .{callee}(...) has no timeout= and no "
                        "wait_for wrapper",
                    )
                )

    # ---- sync leaf waits: Future.result(), queue get, event wait ----
    def visit_Call(self, node: ast.Call) -> None:
        if callee_last(node) == "wait_for":
            # Primitives handed to wait_for ARE deadline-bounded —
            # mark them before descending so the leaf branch below
            # skips them (`await asyncio.wait_for(ev.wait(), 5)`).
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call):
                        self._async_owned.add(id(sub))
        self.generic_visit(node)
        if (
            not isinstance(node.func, ast.Attribute)
            or node.args
            or has_kwarg(node, "timeout")
        ):
            return
        attr = node.func.attr
        if attr == "result":
            self.findings.append(
                self.ctx.finding(
                    self.checker,
                    node,
                    ".result() without a timeout blocks forever if the "
                    "producer dies — pass timeout=",
                )
            )
        elif attr in ("get", "wait") and id(node) not in self._async_owned:
            # The step-queue wait pattern: loop threads must poll with
            # timeout= and re-check their stop flag (a no-arg .get()
            # can only be a queue, never dict.get(key)).  Awaited or
            # wait_for-wrapped calls belong to the await path above.
            self.findings.append(
                self.ctx.finding(
                    self.checker,
                    node,
                    f".{attr}() without a timeout parks the thread "
                    "forever — poll with timeout= and re-check the "
                    "stop flag",
                )
            )


@register
class UnboundedWaitChecker(Checker):
    code = "VDT003"
    rule = "unbounded-wait"
    description = "control-plane wait without a deadline"
    rationale = (
        "an unbounded wait turns a silent host into a wedged driver; "
        "every control-plane wait needs a deadline"
    )
    scope = (
        "distributed/",
        "executor/",
        "worker/",
        "engine/supervisor.py",
        # ISSUE 10: the router IS a control plane over replicas — a
        # silently dead backend must trigger migration, never a wedged
        # client stream (Llumnix-style migration is only safe on a
        # deadline-disciplined control plane).  Since ISSUE 17 this
        # scope also covers router/persist.py: the WAL sits on the
        # admission/checkpoint hot path, so every fsync/rotation wait
        # there must be deadline-bounded too.
        "router/",
        # ISSUE 15: the KV hand-off module drives device collectives
        # and cross-replica transfers from the engine thread — an
        # unbounded export/import wait would park token generation for
        # the whole replica behind one wedged transfer.
        "engine/kv_transfer.py",
        # ISSUE 16: the QoS registry sits on the admission hot path
        # (every reserve() resolves a class under the controller lock)
        # — a wait introduced there would stall all admission.
        # router/qos.py is already covered by the router/ scope.
        "engine/qos.py",
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        visitor = _Visitor(self, ctx)
        visitor.visit(ctx.tree)
        return visitor.findings
