"""VDT004 env-registry: VDT_* env vars live in envs.py, and the
registry is documented.

``envs.environment_variables`` is the single registry of recognized env
vars AND the replication allowlist forwarded to remote workers
(envs.py:1-9).  A ``VDT_*`` read that bypasses it is a correctness bug
twice over: the var silently never reaches remote hosts, and operators
cannot discover it.  The project half of the rule cross-checks the
registry against README.md — every registered var must be documented.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, Project, register

_PREFIX = "VDT_"
_READ_CALLS = {"os.environ.get", "os.getenv", "environ.get"}
_SUBSCRIPT_BASES = {"os.environ", "environ"}


def _vdt_literal(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value.startswith(_PREFIX)
    ):
        return node.value
    return None


@register
class EnvRegistryChecker(Checker):
    code = "VDT004"
    rule = "env-registry"
    description = "VDT_* env read outside envs.py / registry not in README"
    rationale = (
        "a VDT_* read that bypasses envs.environment_variables is "
        "invisible to operators and never replicated to remote hosts"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.scope_rel == "envs.py":
            return
        for node in ast.walk(ctx.tree):
            name = None
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted in _READ_CALLS and node.args:
                    name = _vdt_literal(node.args[0])
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if dotted_name(node.value) in _SUBSCRIPT_BASES:
                    name = _vdt_literal(node.slice)
            if name is not None:
                yield ctx.finding(
                    self,
                    node,
                    f"direct read of {name} — declare it in "
                    "envs.environment_variables and read via "
                    f"envs.{name}",
                )

    def check_project(self, project: Project) -> Iterable[Finding]:
        envs_ctx = project.get("envs.py")
        if envs_ctx is None:
            return  # fixture trees carry no registry to cross-check
        readme = envs_ctx.path.parent.parent / "README.md"
        if not readme.exists():
            return
        readme_text = readme.read_text()
        for name_node in self._registry_keys(envs_ctx.tree):
            # Word-boundary match: VDT_HEARTBEAT must not pass just
            # because VDT_HEARTBEAT_INTERVAL_SECONDS is documented.
            if not re.search(
                rf"\b{re.escape(name_node.value)}\b", readme_text
            ):
                yield envs_ctx.finding(
                    self,
                    name_node,
                    f"registry entry {name_node.value} is not documented "
                    "in README.md (env-var table)",
                )

    @staticmethod
    def _registry_keys(tree: ast.Module) -> Iterable[ast.Constant]:
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "environment_variables"
                for t in targets
            ):
                continue
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(
                        key.value, str
                    ):
                        yield key
