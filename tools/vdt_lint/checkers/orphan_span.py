"""VDT007 orphan-span: spans open via ``with`` or try/finally ``.end()``.

Migrated from tests/test_code_hygiene.py (ISSUE 5 satellite).  A manual
``start_span`` call outside a ``with`` item or a try/finally that
``.end()``s it leaks the span open if the code between open and close
raises — the trace ring then reports a phantom still-running stage.

Blind-spot fix (ISSUE 6 satellite): the old ``_guarded_start_spans``
only recognized a plain ``Assign``/``AnnAssign`` immediately before the
try/finally, so a span bound by tuple-unpacking inside a larger
statement or by a walrus (``if (sp := t.start_span(...)):``) was
reported as orphanable even though the finally closed it.  The guard
now accepts ANY statement immediately preceding a try whose finalbody
calls ``.end()`` — what matters is the finally, not the binding syntax.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import calls_named
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_NAME = "start_span"


def _guarded(tree: ast.Module) -> set[int]:
    """ids of start_span Call nodes that cannot leak open: used as a
    ``with`` item, or part of the statement immediately before a
    try/finally whose finally calls ``.end()``."""
    ok: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for call in calls_named(item.context_expr, _NAME):
                    ok.add(id(call))
        body = getattr(node, "body", None)
        if not isinstance(body, list):
            continue
        for stmt, nxt in zip(body, body[1:]):
            if not (isinstance(nxt, ast.Try) and nxt.finalbody):
                continue
            if not any(
                True
                for fin in nxt.finalbody
                for _ in calls_named(fin, "end")
            ):
                continue
            # Any statement shape counts: plain assign, tuple-unpacking,
            # walrus inside an expression/if — the finally is the guard.
            for call in calls_named(stmt, _NAME):
                ok.add(id(call))
    return ok


@register
class OrphanSpanChecker(Checker):
    code = "VDT007"
    rule = "orphan-span"
    description = "manual start_span without with/try-finally"
    rationale = (
        "a raise between open and close leaks the span open and the "
        "trace ring reports a phantom still-running stage"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        guarded = _guarded(ctx.tree)
        for call in calls_named(ctx.tree, _NAME):
            # The definition site (tracing.py's `start_span = span`
            # alias) is an assignment, not a call, so it never trips.
            if id(call) not in guarded:
                yield ctx.finding(
                    self,
                    call,
                    "manual start_span outside with/try-finally — use "
                    "`with tracer.span(...)` so a raise cannot leak an "
                    "open span",
                )
