"""VDT005 thread-leak: threads are daemons or joined; child processes
are reaped.

The PR 3 leak class: a non-daemon thread with no reachable ``join()``
keeps the process alive after the engine is torn down (chaos-soak's
no-leaked-threads assertion exists because this bit us).  Every
``threading.Thread`` must either be created ``daemon=True`` or have a
``.join(...)`` on its binding somewhere in the same file (the shutdown
path), mirroring ``MultiHostExecutor._teardown``'s loop-thread join.

ISSUE 13 extends the same invariant to child PROCESSES: a
``subprocess.Popen`` / ``multiprocessing.Process`` with no reachable
``.wait(...)`` / ``.join(...)`` (and, for multiprocessing, no
``daemon=True``) is an orphanable child — unreaped, it lingers as a
zombie holding its port, exactly what the router fleet's synchronous
reap exists to prevent.  Whether those waits are deadline-BOUNDED is
VDT003's half of the contract.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, register

_THREAD_TARGETS = {"threading.Thread", "Thread"}
# Child-process constructors: same binding discipline, zombie-shaped
# consequence.  Popen has no daemon concept (daemon= on it would be a
# TypeError anyway, so sharing the daemon check is harmless).
_PROCESS_TARGETS = {
    "subprocess.Popen",
    "Popen",
    "multiprocessing.Process",
    "mp.Process",
    "Process",
}


def _binding_of(call: ast.Call, parents: dict[int, ast.AST]) -> str | None:
    """The name/attr a Thread(...) is assigned to, as a dotted string."""
    node: ast.AST = call
    while id(node) in parents:
        parent = parents[id(node)]
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = (
                parent.targets
                if isinstance(parent, ast.Assign)
                else [parent.target]
            )
            for t in targets:
                name = dotted_name(t)
                if name is not None:
                    return name
            return None
        if isinstance(parent, ast.NamedExpr):
            return dotted_name(parent.target)
        if not isinstance(parent, (ast.expr,)):
            return None
        node = parent
    return None


@register
class ThreadLeakChecker(Checker):
    code = "VDT005"
    rule = "thread-leak"
    description = "thread without daemon= or a reachable join()"
    rationale = (
        "a non-daemon thread with no join keeps a dead engine's process "
        "alive and leaks across supervisor rebuilds"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        joined: set[str] = set()
        reaped: set[str] = set()
        daemonized: set[str] = set()
        # Popen used as a context manager reaps on __exit__.
        in_with: set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    in_with.add(id(item.context_expr))
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("join", "wait", "communicate")
            ):
                name = dotted_name(node.func.value)
                if name is not None:
                    # communicate() waits the child too (its timeout
                    # discipline is VDT003's half, like wait/join).
                    reaped.add(name)
                    if node.func.attr == "join":
                        joined.add(name)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        owner = dotted_name(t.value)
                        if owner is not None and not (
                            isinstance(node.value, ast.Constant)
                            and node.value.value is False
                        ):
                            daemonized.add(owner)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = dotted_name(node.func)
            if target in _THREAD_TARGETS:
                kind = "thread"
            elif target in _PROCESS_TARGETS:
                if id(node) in in_with:
                    continue  # `with Popen(...)` reaps on __exit__
                kind = "process"
            else:
                continue
            daemon_kw = next(
                (kw for kw in node.keywords if kw.arg == "daemon"), None
            )
            if daemon_kw is not None and not (
                isinstance(daemon_kw.value, ast.Constant)
                and daemon_kw.value.value is False
            ):
                continue
            binding = _binding_of(node, parents)
            cleaned = joined if kind == "thread" else reaped
            if binding is not None and (
                binding in cleaned or binding in daemonized
            ):
                continue
            if kind == "thread":
                where = (
                    f"`{binding}`"
                    if binding is not None
                    else "an unbound thread"
                )
                yield ctx.finding(
                    self,
                    node,
                    f"Thread bound to {where} is neither daemon=True "
                    "nor joined in this file — it outlives shutdown",
                )
            else:
                where = (
                    f"`{binding}`"
                    if binding is not None
                    else "an unbound child process"
                )
                yield ctx.finding(
                    self,
                    node,
                    f"child process bound to {where} has no reachable "
                    "wait()/join() in this file — unreaped, it lingers "
                    "as a zombie holding its port",
                )
