"""VDT009 bounded-cardinality: metric label values never derive from
unbounded sources (request ids, prompts, trace ids, token ids).

The ISSUE 12 metrics layer added client-influenced labels (slo_class),
which is safe only because engine/slo.py sanitizes and CAPS the label
space.  The failure mode this rule guards against is the classic
Prometheus cardinality bomb: a ``.labels(request_id=...)`` call mints a
new time series per request, growing the registry (and every scrape)
without bound until the process — or the monitoring stack — falls over.

The rule scans ``.labels(...)`` call sites in the metrics modules: any
argument expression that mentions an identifier, attribute, or string
key drawn from a known-unbounded source family (``request_id``/
``req_id``, ``prompt``, ``trace_id``/``span_id``, ``token_id(s)``) is
flagged.  Bounded-by-construction values (sanitized class names,
enum-like reasons, host ranks, replica ids) pass untouched.  A value
that is genuinely bounded despite its name carries a waiver naming what
bounds it.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.core import Checker, FileContext, Finding, register

# Identifier fragments that mark a value as derived from an unbounded,
# per-request source.  Matched as substrings of lowercased identifier /
# attribute / string-literal tokens inside the label-value expression.
_UNBOUNDED_FRAGMENTS = (
    "request_id",
    "req_id",
    "prompt",
    "trace_id",
    "span_id",
    "token_id",
)


def _expr_tokens(node: ast.AST) -> Iterable[tuple[str, ast.AST]]:
    """Yield (lowercased token, node) for every identifier, attribute
    tail, and string literal inside a label-value expression."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id.lower(), sub
        elif isinstance(sub, ast.Attribute):
            yield sub.attr.lower(), sub
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value.lower(), sub


def _unbounded_token(node: ast.AST) -> str | None:
    for token, _ in _expr_tokens(node):
        for fragment in _UNBOUNDED_FRAGMENTS:
            if fragment in token:
                return token
    return None


@register
class BoundedCardinalityChecker(Checker):
    code = "VDT009"
    rule = "bounded-cardinality"
    description = "metric label value derived from an unbounded source"
    rationale = (
        "a label minted per request id / prompt / trace id creates one "
        "time series per request — the registry, every scrape, and the "
        "monitoring backend grow without bound; use a bounded, "
        "sanitized label (or no label) instead"
    )
    # Package-wide: every `.labels()` call site today lives in the two
    # metrics modules (EngineMetrics / RouterMetrics), but a new module
    # minting its own labeled series is exactly the drift this guards.
    scope = None

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"
            ):
                continue
            values = list(node.args) + [
                kw.value for kw in node.keywords if kw.arg is not None
            ]
            # `.labels(**label)` dict-splat: inspect the splatted
            # expression itself (its construction names its sources).
            values += [
                kw.value for kw in node.keywords if kw.arg is None
            ]
            for value in values:
                token = _unbounded_token(value)
                if token is not None:
                    yield ctx.finding(
                        self,
                        node,
                        f"label value mentions unbounded source "
                        f"{token!r} — one time series per request; use "
                        "a bounded sanitized label or waive with what "
                        "bounds it",
                    )
                    break
