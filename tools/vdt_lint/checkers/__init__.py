"""Checker registry: importing this package registers every rule.

| code   | rule             | invariant                                        |
|--------|------------------|--------------------------------------------------|
| VDT001 | async-blocking   | no blocking calls inside ``async def`` bodies    |
| VDT002 | lock-across-await| no sync lock held across an ``await``            |
| VDT003 | unbounded-wait   | control-plane waits carry a deadline             |
| VDT004 | env-registry     | VDT_* env reads go through envs.py; registry ⊂ README |
| VDT005 | thread-leak      | threads are daemons or joined on shutdown        |
| VDT006 | silent-except    | no ``except Exception: pass``                    |
| VDT007 | orphan-span      | spans open via ``with`` / try-finally ``.end()`` |
| VDT008 | unbounded-queue  | queues/deques on the request path carry a bound  |
| VDT009 | bounded-cardinality | metric labels never derive from unbounded sources |
| VDT010 | resilient-http   | router outbound HTTP goes through the resilience wrapper |
| VDT011 | sentinel-emitter | timeline events go through SentinelLog.emit with registered kinds |
"""

from tools.vdt_lint.checkers import (  # noqa: F401
    async_blocking,
    bounded_cardinality,
    env_registry,
    lock_across_await,
    orphan_span,
    resilient_http,
    sentinel_emitter,
    silent_except,
    thread_leak,
    unbounded_queue,
    unbounded_wait,
)
