"""VDT008 unbounded-queue: queues/deques on the request path carry an
explicit bound or a justified waiver.

The ISSUE 8 overload class: before bounded admission, the scheduler's
waiting deque and the AsyncLLM intake grew without limit under offered
load the engine couldn't absorb — memory, then latency, then the
process fell over.  Every ``queue.Queue()`` / ``asyncio.Queue()`` /
``collections.deque()`` constructed in ``engine/``, ``entrypoints/``,
or ``distributed/`` must either pass an explicit bound
(``maxsize=``/``maxlen=``, or positionally) or carry a waiver naming
what bounds it upstream (admission caps, 1:1 with live handlers, a
pruning loop).  ``SimpleQueue`` has no capacity parameter at all, so it
is always flagged — bound it upstream and say how, or use a bounded
``queue.Queue``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name
from tools.vdt_lint.core import Checker, FileContext, Finding, register

# Constructors whose FIRST positional (or the named kwarg) is the bound.
# A literal 0 (queue.Queue's "infinite") does not count as a bound.
_MAXSIZE_TARGETS = {
    "Queue",
    "LifoQueue",
    "PriorityQueue",
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "_queue.Queue",
    "asyncio.Queue",
    "asyncio.LifoQueue",
    "asyncio.PriorityQueue",
}

# deque(iterable, maxlen) — the SECOND positional (or maxlen=) bounds it.
_MAXLEN_TARGETS = {"deque", "collections.deque"}

# No capacity parameter exists: always unbounded.
_ALWAYS_UNBOUNDED = {
    "SimpleQueue",
    "queue.SimpleQueue",
    "_queue.SimpleQueue",
}


def _is_zero(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) and node.value in (0, None)


def _bound_given(call: ast.Call, kwarg: str, pos_index: int) -> bool:
    kw = next((k for k in call.keywords if k.arg == kwarg), None)
    if kw is not None:
        return not _is_zero(kw.value)
    if len(call.args) > pos_index:
        return not _is_zero(call.args[pos_index])
    return False


@register
class UnboundedQueueChecker(Checker):
    code = "VDT008"
    rule = "unbounded-queue"
    description = "queue/deque constructed without an explicit bound"
    rationale = (
        "an unbounded queue on the request path turns overload into "
        "memory growth and tail latency instead of load shedding; "
        "bound it, or waive with what bounds it upstream"
    )
    scope = ("engine/", "entrypoints/", "distributed/", "router/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            if name in _ALWAYS_UNBOUNDED:
                yield ctx.finding(
                    self,
                    node,
                    f"{name}() has no capacity bound — bound the "
                    "producers and waive with the justification, or "
                    "use queue.Queue(maxsize=...)",
                )
            elif name in _MAXSIZE_TARGETS:
                if not _bound_given(node, "maxsize", 0):
                    yield ctx.finding(
                        self,
                        node,
                        f"{name}() without maxsize is unbounded — pass "
                        "an explicit bound or waive with what bounds "
                        "it upstream",
                    )
            elif name in _MAXLEN_TARGETS:
                if not _bound_given(node, "maxlen", 1):
                    yield ctx.finding(
                        self,
                        node,
                        f"{name}() without maxlen is unbounded — pass "
                        "an explicit bound or waive with what bounds "
                        "it upstream",
                    )
