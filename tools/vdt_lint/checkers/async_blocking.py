"""VDT001 async-blocking: no blocking calls inside ``async def`` bodies.

A blocking call on the event loop stalls every request, heartbeat, and
SSE stream sharing that loop — vLLM's dominant serving-regression class
(PAPERS.md, PagedAttention §6).  The fix is always the same: hop the
work onto an executor (``loop.run_in_executor``), as
``ConnectionRpcTransport`` and ``WorkerHost.run`` already do.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.vdt_lint.astutil import dotted_name, walk_skipping_functions
from tools.vdt_lint.core import Checker, FileContext, Finding, register

# Exact dotted call targets that block.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() stalls the event loop",
    "subprocess.run": "synchronous subprocess wait",
    "subprocess.call": "synchronous subprocess wait",
    "subprocess.check_call": "synchronous subprocess wait",
    "subprocess.check_output": "synchronous subprocess wait",
}

# Any call through the socket module is a sync network primitive (use
# asyncio.open_connection / loop.sock_* instead).
_SOCKET_MODULE = "socket."

# Method names that block regardless of receiver: concurrent futures,
# sync multiprocessing pipes, and path-object file I/O.
_BLOCKING_METHODS = {
    "result": "Future.result() blocks the loop (await it, or run_in_executor)",
    "send_bytes": "sync pipe write (run_in_executor, like ConnectionRpcTransport)",
    "recv_bytes": "sync pipe read (run_in_executor, like ConnectionRpcTransport)",
    "read_text": "file I/O on the event loop",
    "write_text": "file I/O on the event loop",
    "read_bytes": "file I/O on the event loop",
    "write_bytes": "file I/O on the event loop",
}

_OPEN_BUILTIN = "open"


def _blocking_reason(call: ast.Call) -> str | None:
    dotted = dotted_name(call.func)
    if dotted is not None:
        if dotted in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[dotted]
        if dotted.startswith(_SOCKET_MODULE):
            return f"sync socket call {dotted}()"
    if isinstance(call.func, ast.Attribute):
        reason = _BLOCKING_METHODS.get(call.func.attr)
        if reason is not None:
            return reason
    if isinstance(call.func, ast.Name) and call.func.id == _OPEN_BUILTIN:
        return "file I/O on the event loop"
    return None


@register
class AsyncBlockingChecker(Checker):
    code = "VDT001"
    rule = "async-blocking"
    description = "blocking call inside an async def body"
    rationale = (
        "a blocking call on the event loop stalls every request, "
        "heartbeat, and SSE stream sharing it"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            # Nested sync defs/lambdas are excluded: they may be handed
            # to run_in_executor, where blocking is the whole point.
            for sub in walk_skipping_functions(node):
                if not isinstance(sub, ast.Call):
                    continue
                reason = _blocking_reason(sub)
                if reason is not None:
                    yield ctx.finding(
                        self,
                        sub,
                        f"blocking call in `async def {node.name}`: "
                        f"{reason}",
                    )
