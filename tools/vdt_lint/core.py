"""Framework core: parse once, run every checker, classify findings.

The driver parses each target file exactly once into a ``FileContext``
(AST + source + waiver map) and hands the same context to every
registered checker — adding a checker never adds a parse pass, which is
what keeps the tier-1 lint gate cheap as the rule catalog grows.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

REPO_ROOT = Path(__file__).resolve().parents[2]
PACKAGE_ROOT = REPO_ROOT / "vllm_distributed_tpu"
DEFAULT_BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# Matches "vdt-lint: disable=rule-a,rule-b" (or "disable=all") anywhere
# inside a comment; everything after the rule list (an em-dash
# justification, say) is ignored.
_WAIVER_RE = re.compile(r"vdt-lint:\s*disable=([A-Za-z0-9_,\- ]+)")

_ALL = "all"


@dataclass(frozen=True)
class Finding:
    code: str  # "VDT003"
    rule: str  # "unbounded-wait"
    path: str  # repo-root-relative posix path (display + baseline key)
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.rule}] {self.message}"


class FileContext:
    """One parsed target file, shared by every checker."""

    def __init__(self, path: Path, rel: str, scope_rel: str, source: str):
        self.path = path
        self.rel = rel
        self.scope_rel = scope_rel
        self.source = source
        self.tree: ast.Module = ast.parse(source, filename=str(path))
        self.waivers: dict[int, set[str]] = _parse_waivers(source)

    def finding(self, checker: "Checker", node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(checker.code, checker.rule, self.rel, line, message)


def _parse_waivers(source: str) -> dict[int, set[str]]:
    """line -> waived rule names.  A trailing comment waives its own
    line; a comment that is the whole line waives the next non-blank,
    non-comment line (so long statements can carry a waiver above)."""
    waivers: dict[int, set[str]] = {}
    lines = source.splitlines()
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return waivers
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _WAIVER_RE.search(tok.string)
        if m is None:
            continue
        # Each comma-separated piece is "<rule> [justification...]":
        # only the first word is the rule, so `disable=VDT003 because
        # the caller bounds it` (or an ASCII-hyphen justification)
        # still waives VDT003 instead of silently matching nothing.
        rules = {
            piece.split()[0]
            for piece in m.group(1).split(",")
            if piece.split()
        }
        line = tok.start[0]
        own_line = lines[line - 1].lstrip().startswith("#")
        if own_line:
            # Bind to the next line that holds code.
            target = line + 1
            while target <= len(lines) and (
                not lines[target - 1].strip()
                or lines[target - 1].lstrip().startswith("#")
            ):
                target += 1
            waivers.setdefault(target, set()).update(rules)
        else:
            waivers.setdefault(line, set()).update(rules)
    return waivers


class Project:
    """Everything a whole-project checker needs: the parsed files plus
    the roots they were collected from."""

    def __init__(self, contexts: list[FileContext], roots: list[Path]):
        self.contexts = contexts
        self.roots = roots

    def get(self, scope_rel: str) -> FileContext | None:
        for ctx in self.contexts:
            if ctx.scope_rel == scope_rel:
                return ctx
        return None


class Checker:
    """One invariant.  Subclasses set the metadata and override
    ``check_file`` (per parsed file, already scope-filtered) and/or
    ``check_project`` (once per run)."""

    code: str = "VDT000"
    rule: str = "abstract"
    description: str = ""
    rationale: str = ""
    # Path prefixes (package-relative, posix) the checker applies to;
    # None = every scanned file.  "engine/supervisor.py" matches one file.
    scope: tuple[str, ...] | None = None

    def applies(self, scope_rel: str) -> bool:
        if self.scope is None:
            return True
        return any(
            scope_rel == s or scope_rel.startswith(s) for s in self.scope
        )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_REGISTRY: dict[str, Checker] = {}


def register(cls: type[Checker]) -> type[Checker]:
    inst = cls()
    for key in (inst.rule, inst.code):
        if key in _REGISTRY:
            raise ValueError(f"duplicate checker registration: {key}")
    _REGISTRY[inst.rule] = inst
    _REGISTRY[inst.code] = inst
    return cls


def all_checkers() -> list[Checker]:
    seen: dict[str, Checker] = {}
    for inst in _REGISTRY.values():
        seen.setdefault(inst.code, inst)
    return sorted(seen.values(), key=lambda c: c.code)


@dataclass
class Report:
    files: int = 0
    new: list[Finding] = field(default_factory=list)
    waived: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)

    @property
    def all_findings(self) -> list[Finding]:
        return self.new + self.waived + self.baselined

    def summary(self) -> str:
        return (
            f"vdt-lint: {len(self.new)} new finding(s), "
            f"{len(self.waived)} waived, {len(self.baselined)} baselined "
            f"across {self.files} file(s)"
        )


def _collect_files(paths: Iterable[Path]) -> Iterator[tuple[Path, Path]]:
    """Yield (file, scan_root) pairs, each file once."""
    seen: set[Path] = set()
    for p in paths:
        p = Path(p).resolve()
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if f not in seen and "__pycache__" not in f.parts:
                    seen.add(f)
                    yield f, p
        elif p not in seen:
            seen.add(p)
            yield p, p.parent


def _scope_rel(path: Path, scan_root: Path) -> str:
    """Package-relative path used for checker scoping: parts after the
    last ``vllm_distributed_tpu`` component when present (the real
    package), otherwise relative to the scanned root (fixture trees)."""
    parts = path.parts
    if "vllm_distributed_tpu" in parts[:-1]:
        idx = len(parts) - 1 - parts[:-1][::-1].index("vllm_distributed_tpu")
        return "/".join(parts[idx:])
    try:
        return path.relative_to(scan_root).as_posix()
    except ValueError:  # pragma: no cover
        return path.name


def _display_rel(path: Path) -> str:
    try:
        return path.relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def _is_waived(finding: Finding, ctx: FileContext) -> bool:
    rules = ctx.waivers.get(finding.line)
    if not rules:
        return False
    return bool(rules & {finding.rule, finding.code, _ALL})


def run_lint(
    paths: Iterable[Path | str] | None = None,
    baseline: Iterable[dict] | None | str = "default",
    checkers: Iterable[Checker] | None = None,
) -> Report:
    """Parse every target once, run every checker, classify findings as
    new / waived / baselined.  ``baseline="default"`` loads the
    committed file; ``None`` disables baselining."""
    from tools.vdt_lint.baseline import load_baseline, match_baseline

    paths = [Path(p) for p in (paths or [PACKAGE_ROOT])]
    if baseline == "default":
        baseline = load_baseline(DEFAULT_BASELINE_PATH)
    checkers = list(checkers) if checkers is not None else all_checkers()

    report = Report()
    contexts: list[FileContext] = []
    raw: list[tuple[Finding, FileContext | None]] = []
    for file, scan_root in _collect_files(paths):
        try:
            source = file.read_text()
            ctx = FileContext(
                file, _display_rel(file), _scope_rel(file, scan_root), source
            )
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # Classified with everything else (ctx None: no inline
            # waivers in an unparseable file, but baselining works).
            raw.append((
                Finding(
                    "VDT000",
                    "parse-error",
                    _display_rel(file),
                    getattr(e, "lineno", 0) or 0,
                    f"could not parse: {e}",
                ),
                None,
            ))
            continue
        contexts.append(ctx)
    report.files = len(contexts)

    project = Project(contexts, [Path(p).resolve() for p in paths])
    by_rel = {ctx.rel: ctx for ctx in contexts}
    for checker in checkers:
        for ctx in contexts:
            if checker.applies(ctx.scope_rel):
                for finding in checker.check_file(ctx):
                    raw.append((finding, ctx))
        for finding in checker.check_project(project):
            raw.append((finding, by_rel.get(finding.path)))

    baseline_entries = list(baseline) if baseline else []
    for finding, ctx in sorted(
        raw, key=lambda fc: (fc[0].path, fc[0].line, fc[0].code)
    ):
        if ctx is not None and _is_waived(finding, ctx):
            report.waived.append(finding)
        elif match_baseline(finding, baseline_entries):
            report.baselined.append(finding)
        else:
            report.new.append(finding)
    return report
