"""Small AST helpers shared by the checkers."""

from __future__ import annotations

import ast
from typing import Iterator

FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted_name(node: ast.AST) -> str | None:
    """Flatten ``a.b.c`` attribute chains to ``"a.b.c"``; None for
    anything that is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def callee_last(call: ast.Call) -> str | None:
    """The terminal name of a call target: ``x.y.wait_for(...)`` ->
    ``"wait_for"``; ``open(...)`` -> ``"open"``."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def calls_named(node: ast.AST, name: str) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and callee_last(sub) == name:
            yield sub


def walk_skipping_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree without descending into nested function/lambda
    definitions — "this code runs HERE, not in some deferred scope"."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, FUNCTION_NODES):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


_SUSPENSION_NODES = (ast.Await, ast.AsyncFor, ast.AsyncWith)


def contains_await(node: ast.AST) -> bool:
    """True if executing ``node`` can suspend the coroutine: an
    ``await``, ``async for`` (suspends at each __anext__), or ``async
    with`` (suspends at __aenter__/__aexit__) in the same scope."""
    if isinstance(node, FUNCTION_NODES):
        return False  # a nested def's awaits run later, in its own scope
    if isinstance(node, _SUSPENSION_NODES):
        return True
    return any(
        isinstance(sub, _SUSPENSION_NODES)
        for sub in walk_skipping_functions(node)
    )
