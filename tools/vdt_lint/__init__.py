"""vdt-lint: project-native static analysis (ISSUE 6 tentpole).

An AST-based framework that machine-checks the concurrency, registry,
and failure-handling invariants accumulated across PRs 1-4:

- each ``Checker`` encodes one project invariant and reports
  ``Finding``s against a shared, parsed-once ``FileContext``;
- ``# vdt-lint: disable=<rule>`` inline comments waive a finding with a
  human justification at the site;
- a committed baseline file (``tools/vdt_lint/baseline.json``) holds
  pre-existing findings that are tolerated but must not grow;
- the CLI (``python -m tools.vdt_lint``) and the tier-1 pytest gate
  (``tests/test_code_hygiene.py``) both fail on any NEW finding.

Run: ``python -m tools.vdt_lint [--format json|text] [paths]``.
"""

from tools.vdt_lint.core import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    PACKAGE_ROOT,
    REPO_ROOT,
    Checker,
    FileContext,
    Finding,
    Project,
    Report,
    all_checkers,
    register,
    run_lint,
)
from tools.vdt_lint.baseline import load_baseline, save_baseline  # noqa: F401

# Importing the checkers package populates the registry.
import tools.vdt_lint.checkers  # noqa: F401, E402
