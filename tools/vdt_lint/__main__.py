import sys

from tools.vdt_lint.cli import main

sys.exit(main())
