"""Committed-baseline support: pre-existing findings that are tolerated
(grandfathered) but must not grow.

Entries are (code, path, line) triples keyed by repo-root-relative
paths; editing the offending code invalidates the entry, so baseline
debt cannot silently survive a rewrite of the line it points at.  The
ISSUE 6 contract keeps ``distributed/`` and ``executor/`` baseline-free
(enforced by tests/test_code_hygiene.py).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable

from tools.vdt_lint.core import Finding

_VERSION = 1


def load_baseline(path: Path | str) -> list[dict]:
    path = Path(path)
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    if data.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} in {path}"
        )
    return list(data.get("findings", []))


def save_baseline(path: Path | str, findings: Iterable[Finding]) -> None:
    entries = sorted(
        (
            {"code": f.code, "path": f.path, "line": f.line}
            for f in findings
        ),
        key=lambda e: (e["path"], e["line"], e["code"]),
    )
    Path(path).write_text(
        json.dumps({"version": _VERSION, "findings": entries}, indent=2)
        + "\n"
    )


def match_baseline(finding: Finding, entries: list[dict]) -> bool:
    return any(
        e.get("code") == finding.code
        and e.get("path") == finding.path
        and e.get("line") == finding.line
        for e in entries
    )
