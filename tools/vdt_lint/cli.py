"""CLI: ``python -m tools.vdt_lint [--format json|text] [paths]``.

Exit status 0 when the tree is clean (no unwaived, un-baselined
findings), 1 otherwise — so the command can gate CI standalone, in
lock-step with the tier-1 pytest gate (tests/test_code_hygiene.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.vdt_lint.baseline import save_baseline
from tools.vdt_lint.core import (
    DEFAULT_BASELINE_PATH,
    PACKAGE_ROOT,
    Finding,
    all_checkers,
    run_lint,
)


def _finding_dict(f: Finding) -> dict:
    return {
        "code": f.code,
        "rule": f.rule,
        "path": f.path,
        "line": f.line,
        "message": f.message,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.vdt_lint",
        description=(
            "Project-native static analysis for the engine's "
            "concurrency, registry, and failure-handling invariants."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        help=f"files/dirs to lint (default: {PACKAGE_ROOT.name}/)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE_PATH),
        help="baseline file (default: the committed one)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined findings as new",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept current new findings into --baseline and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for checker in all_checkers():
            scope = (
                ", ".join(checker.scope) if checker.scope else "package-wide"
            )
            print(f"{checker.code}  {checker.rule:<18} [{scope}]")
            print(f"        {checker.rationale}")
        return 0

    from tools.vdt_lint.baseline import load_baseline

    baseline = (
        None if args.no_baseline else load_baseline(args.baseline)
    )
    report = run_lint(args.paths or None, baseline=baseline)

    if args.write_baseline:
        save_baseline(args.baseline, report.new + report.baselined)
        print(
            f"vdt-lint: baselined {len(report.new) + len(report.baselined)} "
            f"finding(s) into {args.baseline}"
        )
        return 0

    status = 1 if report.new else 0
    try:
        if args.format == "json":
            print(
                json.dumps(
                    {
                        "new": [_finding_dict(f) for f in report.new],
                        "waived": [_finding_dict(f) for f in report.waived],
                        "baselined": [
                            _finding_dict(f) for f in report.baselined
                        ],
                        "files": report.files,
                    },
                    indent=2,
                )
            )
        else:
            for f in report.new:
                print(f.render())
            print(report.summary(), file=sys.stderr)
    except BrokenPipeError:
        # `... | head` closed the pipe mid-report: truncated output is
        # fine, but the exit status must still reflect the findings
        # (CI pipefail relies on it).  Point stdout at devnull so the
        # interpreter's exit flush doesn't re-raise.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    return status
