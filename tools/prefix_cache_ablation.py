"""Prefix-caching ablation: same prompt set with caching on vs off.

Runs an identical repeated-prompt workload (a shared system-prefix chat
pattern) through two engines — one with ``--enable-prefix-caching``, one
without — and reports warm TTFT (a later round of the same prompts) and
the prefix-cache hit rate for each, plus a greedy-equivalence check that
the cached engine's outputs are bit-identical to the cold engine's.
Prints ONE JSON line, like bench.py.

Three rounds per engine: round 1 is cold (compiles + fills the cache),
round 2 warms the chunk shapes the cached run uses (its prefill token
buckets differ from the cold run's, so measuring it would charge the
cached engine an XLA compile the cold engine never pays), round 3 is the
measured warm round.

``--tiered`` (ISSUE 14) switches to the tiered-index ablation instead:
**flat vs radix vs radix+spill** warm TTFT at a CONSTRAINED page pool
(disjoint chains cycled one at a time, so by the time a chain returns
its pages have been evicted — discarded by the flat cache, spilled to
host DRAM by the tiered one), plus a **restore-vs-recompute crossover
sweep** over prompt lengths (the same workload with the restore path
forced on vs forced off) — the empirical basis for setting
``VDT_KV_SPILL_RESTORE_MIN_TOKENS``.

Invocation (CPU, synthetic weights — no checkpoint needed):

    JAX_PLATFORMS=cpu python tools/prefix_cache_ablation.py
    JAX_PLATFORMS=cpu python tools/prefix_cache_ablation.py --tiered

or against a real model / the TPU:

    python tools/prefix_cache_ablation.py --model meta-llama/Llama-2-7b-hf
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _build_prompts(n: int, prompt_len: int, shared_len: int) -> list[list[int]]:
    shared = [(13 * j) % 900 + 1 for j in range(shared_len)]
    return [
        shared + [(7 * i + 3 * j) % 900 + 1 for j in range(prompt_len - shared_len)]
        for i in range(n)
    ]


def _run_round(engine, prompts, tag: str, max_tokens: int):
    """Submit every prompt, drain, return (outputs, ttft list in s)."""
    from vllm_distributed_tpu.sampling_params import SamplingParams

    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}{i}", prompt_token_ids=p, sampling_params=sp)
    done: dict[str, object] = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
    outs = [done[f"{tag}{i}"] for i in range(len(prompts))]
    ttfts = [o.metrics.ttft for o in outs if o.metrics.ttft is not None]
    cached = [o.metrics.cached_tokens for o in outs]
    return [list(o.outputs[0].token_ids) for o in outs], ttfts, cached


def _measure_mode(model: str, enable: bool, args) -> tuple[dict, list]:
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine

    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model,
            skip_tokenizer_init=True,
            load_format=args.load_format,
            num_kv_pages=args.num_kv_pages,
            page_size=args.page_size,
            max_num_seqs=args.num_prompts,
            max_model_len=args.prompt_len + args.max_tokens + 8,
            enable_prefix_caching=enable,
        )
    )
    prompts = _build_prompts(args.num_prompts, args.prompt_len, args.shared_prefix_len)
    t0 = time.perf_counter()
    outputs, cold_ttfts, _ = _run_round(engine, prompts, "c", args.max_tokens)
    cold_s = time.perf_counter() - t0
    _run_round(engine, prompts, "s", args.max_tokens)  # shape warmer
    t0 = time.perf_counter()
    warm_outputs, warm_ttfts, warm_cached = _run_round(
        engine, prompts, "w", args.max_tokens
    )
    warm_s = time.perf_counter() - t0
    assert warm_outputs == outputs, "warm round diverged from cold round"
    sched = engine.scheduler
    queries, hits = sched.prefix_cache_queries, sched.prefix_cache_hits
    rendered = engine.metrics.render().decode()
    metrics_hits = 0.0
    for line in rendered.splitlines():
        if line.startswith("vllm:prefix_cache_hits_total"):
            metrics_hits = float(line.rsplit(" ", 1)[1])
    detail = {
        "prefix_caching": enable,
        "cold_round_s": round(cold_s, 3),
        "warm_round_s": round(warm_s, 3),
        "ttft_cold_ms_mean": round(statistics.mean(cold_ttfts) * 1e3, 2),
        "ttft_warm_ms_mean": round(statistics.mean(warm_ttfts) * 1e3, 2),
        "ttft_warm_ms_p50": round(statistics.median(warm_ttfts) * 1e3, 2),
        "warm_cached_tokens_per_req": round(statistics.mean(warm_cached), 1),
        "prefix_cache_queries": queries,
        "prefix_cache_hits": hits,
        "prefix_cache_hit_rate": round(hits / queries, 4) if queries else 0.0,
        "metrics_endpoint_hits": metrics_hits,
    }
    engine.shutdown()
    return detail, outputs


def _build_engine(model: str, args, **kw):
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine

    defaults = dict(
        model=model,
        skip_tokenizer_init=True,
        load_format=args.load_format,
        page_size=args.page_size,
        max_num_seqs=args.num_prompts,
        max_model_len=args.prompt_len + args.max_tokens + 8,
    )
    defaults.update(kw)
    return LLMEngine.from_engine_args(EngineArgs(**defaults))


def _cycle_disjoint(engine, prompts, tag, max_tokens, rounds=3):
    """Cycle disjoint chains ONE AT A TIME through a constrained pool
    (each comes back after the others evicted it); returns the final
    cycle's outputs and TTFTs (in seconds)."""
    outs, ttfts = [], []
    for rnd in range(rounds):
        outs, ttfts = [], []
        for i, p in enumerate(prompts):
            o, t, _ = _run_round(
                engine, [p], f"{tag}{rnd}-{i}", max_tokens
            )
            outs.append(o[0])
            ttfts.extend(t)
    return outs, ttfts


def _measure_tiered(model: str, args) -> dict:
    """flat vs radix vs radix+spill at a constrained pool, plus the
    restore-vs-recompute crossover sweep."""
    prompts = [
        [(101 * (i + 1) + 7 * j) % 900 + 1 for j in range(args.prompt_len)]
        for i in range(args.num_prompts)
    ]
    modes = {
        "flat": dict(
            enable_prefix_caching=True, prefix_cache_index="flat"
        ),
        "radix": dict(enable_prefix_caching=True),
        "radix_spill": dict(
            enable_prefix_caching=True,
            kv_spill_host_pages=args.host_pages,
            kv_spill_restore_min_tokens=args.page_size,
        ),
    }
    report: dict = {"modes": {}}
    baseline = None
    for name, kw in modes.items():
        engine = _build_engine(
            model, args, num_kv_pages=args.constrained_kv_pages, **kw
        )
        outs, ttfts = _cycle_disjoint(
            engine, prompts, name, args.max_tokens
        )
        sched = engine.scheduler
        report["modes"][name] = {
            "warm_ttft_ms_mean": round(statistics.mean(ttfts) * 1e3, 2),
            "warm_ttft_ms_p50": round(statistics.median(ttfts) * 1e3, 2),
            "prefix_cache_hits": sched.prefix_cache_hits,
            "prefix_cache_host_hits": getattr(
                sched, "prefix_cache_hits_host", 0
            ),
            "kv_spill_pages": getattr(sched, "kv_spill_pages", 0),
            "kv_restore_pages": getattr(sched, "kv_restore_pages", 0),
        }
        engine.shutdown()
        if baseline is None:
            baseline = outs
        elif outs != baseline:
            report["modes"][name]["outputs_bit_identical"] = False
    report["outputs_bit_identical"] = all(
        m.get("outputs_bit_identical", True)
        for m in report["modes"].values()
    )
    flat = report["modes"]["flat"]
    tier = report["modes"]["radix_spill"]
    report["gate"] = {
        "hit_tokens_radix_spill_gt_flat": (
            tier["prefix_cache_hits"] > flat["prefix_cache_hits"]
        ),
        "warm_ttft_radix_spill_lt_flat": (
            tier["warm_ttft_ms_mean"] < flat["warm_ttft_ms_mean"]
        ),
    }
    # Crossover sweep: same cycled workload per prompt length, restore
    # forced on (min=1 token) vs off (min > prompt) — where the curves
    # cross is the empirical VDT_KV_SPILL_RESTORE_MIN_TOKENS.
    sweep = []
    for plen in args.crossover_lens:
        row = {"prompt_len": plen}
        chains = [
            [(37 * (i + 3) + 11 * j) % 900 + 1 for j in range(plen)]
            for i in range(args.num_prompts)
        ]
        for policy, min_tokens in (
            ("restore", 1),
            ("recompute", plen + args.page_size),
        ):
            engine = _build_engine(
                model,
                args,
                num_kv_pages=args.constrained_kv_pages,
                max_model_len=plen + args.max_tokens + 8,
                enable_prefix_caching=True,
                kv_spill_host_pages=args.host_pages,
                kv_spill_restore_min_tokens=min_tokens,
            )
            _, ttfts = _cycle_disjoint(
                engine, chains, f"x{plen}{policy}", args.max_tokens
            )
            row[f"{policy}_ttft_ms_mean"] = round(
                statistics.mean(ttfts) * 1e3, 2
            )
            if policy == "restore":
                row["restored_pages"] = engine.scheduler.kv_restore_pages
            engine.shutdown()
        sweep.append(row)
    report["crossover"] = sweep
    return report


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=None, help="default: tiny synthetic llama")
    ap.add_argument("--load-format", default=None, choices=["auto", "dummy"])
    ap.add_argument("--num-prompts", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=256)
    ap.add_argument(
        "--shared-prefix-len",
        type=int,
        default=192,
        help="leading tokens shared by every prompt (system-prompt pattern)",
    )
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--num-kv-pages", type=int, default=1024)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument(
        "--tiered",
        action="store_true",
        help="ISSUE 14 ablation: flat vs radix vs radix+spill at a "
        "constrained pool + restore-vs-recompute crossover sweep",
    )
    ap.add_argument(
        "--constrained-kv-pages",
        type=int,
        default=None,
        help="pool size for the tiered ablation (default: enough for "
        "~half the cycled chains, forcing whole-chain eviction)",
    )
    ap.add_argument(
        "--host-pages",
        type=int,
        default=None,
        help="host-DRAM tier size for the radix+spill mode (default: "
        "enough for every cycled chain)",
    )
    ap.add_argument(
        "--crossover-lens",
        type=str,
        default=None,
        help="comma-separated prompt lengths for the restore-vs-"
        "recompute sweep (default: prompt_len/4, /2, x1, x2)",
    )
    args = ap.parse_args()

    model = args.model
    if model is None:
        from vllm_distributed_tpu.testing import write_llama_config

        model = write_llama_config()
        args.load_format = args.load_format or "dummy"
    args.load_format = args.load_format or "auto"

    if args.tiered:
        per_chain = (args.prompt_len + args.max_tokens) // args.page_size + 2
        if args.constrained_kv_pages is None:
            args.constrained_kv_pages = max(
                per_chain * max(args.num_prompts // 2, 1) + 1, 8
            )
        if args.host_pages is None:
            args.host_pages = per_chain * args.num_prompts
        if args.crossover_lens is None:
            base = args.prompt_len
            args.crossover_lens = sorted(
                {max(base // 4, args.page_size), base // 2, base, 2 * base}
            )
        else:
            args.crossover_lens = [
                int(x) for x in args.crossover_lens.split(",") if x
            ]
        result = {
            "bench": "prefix_cache_ablation",
            "mode": "tiered",
            "model": model,
            "num_prompts": args.num_prompts,
            "prompt_len": args.prompt_len,
            "constrained_kv_pages": args.constrained_kv_pages,
            "host_pages": args.host_pages,
            **_measure_tiered(model, args),
        }
        print(json.dumps(result))
        return

    off, outputs_off = _measure_mode(model, False, args)
    on, outputs_on = _measure_mode(model, True, args)
    result = {
        "bench": "prefix_cache_ablation",
        "model": model,
        "num_prompts": args.num_prompts,
        "prompt_len": args.prompt_len,
        "shared_prefix_len": args.shared_prefix_len,
        "off": off,
        "on": on,
        "warm_ttft_speedup": round(
            off["ttft_warm_ms_mean"] / max(on["ttft_warm_ms_mean"], 1e-9), 2
        ),
        "outputs_bit_identical": outputs_on == outputs_off,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
